// Native max-flow scheduler core for mode 3 (the scheduling hot path).
//
// The reference computes its dissemination plan with Edmonds-Karp over an
// adjacency matrix inside an exponential+binary search on the completion
// time (/root/reference/distributor/flow.go:146-353).  At pod scale
// (32+ nodes x 80+ layers) that search dominates leader latency, so this
// library runs the whole loop natively: Dinic's algorithm (O(V^2 E), far
// better than Edmonds-Karp's O(V E^2) on these dense layered graphs) over
// an edge list whose capacities are affine in the candidate time t:
//
//     cap_i(t) = clamp(cap_const_i + cap_per_t_i * t / t_scale, 0, INF)
//
// which covers every edge class in the flow model: NIC edges (0 + bw*t),
// source-class edges (0 + rate*t), class->layer edges (INF + 0*t), and
// layer->receiver edges (size + 0*t).  t_scale decouples the time
// granularity from the rate units: rates stay bytes/second while t counts
// milliseconds (t_scale=1000) — the reference's integer-second search
// (flow.go:155-187) pads every sub-second plan to 1 s.  Floor division
// keeps caps integral and monotone in t, so the search is unchanged.
//
// Exposed as a plain C ABI for ctypes; no Python.h dependency.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int64_t kInf = int64_t{1} << 62;

// Saturating a + b*t/scale in 128-bit, clamped to [0, kInf].
inline int64_t affine_cap(int64_t c, int64_t per_t, int64_t t,
                          int64_t t_scale) {
  __int128 v =
      (__int128)c + (__int128)per_t * (__int128)t / (t_scale > 0 ? t_scale : 1);
  if (v < 0) return 0;
  if (v > (__int128)kInf) return kInf;
  return (int64_t)v;
}

struct Dinic {
  struct Edge {
    int32_t to;
    int64_t cap;
    int32_t rev;   // index of reverse edge in graph[to]
    int32_t orig;  // original edge index, -1 for reverse edges
  };

  int32_t n;
  std::vector<std::vector<Edge>> graph;
  std::vector<int32_t> level, iter;

  explicit Dinic(int32_t n_) : n(n_), graph(n_), level(n_), iter(n_) {}

  void add_edge(int32_t u, int32_t v, int64_t cap, int32_t orig) {
    graph[u].push_back({v, cap, (int32_t)graph[v].size(), orig});
    graph[v].push_back({u, 0, (int32_t)graph[u].size() - 1, -1});
  }

  bool bfs(int32_t s, int32_t t) {
    std::fill(level.begin(), level.end(), -1);
    std::vector<int32_t> q;
    q.reserve(n);
    level[s] = 0;
    q.push_back(s);
    for (size_t h = 0; h < q.size(); ++h) {
      int32_t u = q[h];
      for (const Edge& e : graph[u]) {
        // No early exit on reaching t: the full level graph is needed for
        // each phase to compute a true blocking flow (the O(V^2 E) bound).
        if (e.cap > 0 && level[e.to] < 0) {
          level[e.to] = level[u] + 1;
          q.push_back(e.to);
        }
      }
    }
    return level[t] >= 0;
  }

  int64_t dfs(int32_t u, int32_t t, int64_t f) {
    if (u == t) return f;
    for (int32_t& i = iter[u]; i < (int32_t)graph[u].size(); ++i) {
      Edge& e = graph[u][i];
      if (e.cap > 0 && level[u] < level[e.to]) {
        int64_t d = dfs(e.to, t, f < e.cap ? f : e.cap);
        if (d > 0) {
          e.cap -= d;
          graph[e.to][e.rev].cap += d;
          return d;
        }
      }
    }
    return 0;
  }

  int64_t max_flow(int32_t s, int32_t t) {
    int64_t flow = 0;
    while (bfs(s, t)) {
      std::fill(iter.begin(), iter.end(), 0);
      int64_t f;
      while ((f = dfs(s, t, kInf)) > 0) flow += f;
    }
    return flow;
  }
};

// Build the graph for candidate time t and run max flow.  When out_flows is
// non-null it receives, per original edge, the flow pushed through it.
int64_t solve_at(int32_t n, int32_t m, const int32_t* eu, const int32_t* ev,
                 const int64_t* cap_const, const int64_t* cap_per_t,
                 int32_t s, int32_t t_sink, int64_t t, int64_t t_scale,
                 int64_t* out_flows) {
  Dinic d(n);
  std::vector<int64_t> caps(m);
  for (int32_t i = 0; i < m; ++i) {
    caps[i] = affine_cap(cap_const[i], cap_per_t[i], t, t_scale);
    d.add_edge(eu[i], ev[i], caps[i], i);
  }
  int64_t flow = d.max_flow(s, t_sink);
  if (out_flows != nullptr) {
    std::memset(out_flows, 0, sizeof(int64_t) * (size_t)m);
    for (int32_t u = 0; u < n; ++u) {
      for (const Dinic::Edge& e : d.graph[u]) {
        if (e.orig >= 0) out_flows[e.orig] = caps[e.orig] - e.cap;
      }
    }
  }
  return flow;
}

}  // namespace

extern "C" {

// Single max-flow evaluation at a fixed time t (building block / testing).
int64_t flow_max_flow_at(int32_t n, int32_t m, const int32_t* eu,
                         const int32_t* ev, const int64_t* cap_const,
                         const int64_t* cap_per_t, int32_t s, int32_t t_sink,
                         int64_t t, int64_t t_scale, int64_t* out_flows) {
  return solve_at(n, m, eu, ev, cap_const, cap_per_t, s, t_sink, t, t_scale,
                  out_flows);
}

// Full scheduler search (flow.go:146-218 equivalent): exponential search for
// a feasible completion time, binary search for the minimum, then one final
// solve whose per-edge flows are written to out_flows.  Returns the chosen
// time; *out_achieved reports the flow actually routed at that time (it can
// fall short of `required` only when the instance is infeasible, mirroring
// the reference's t_upper bail-out at flow.go:158-166).
int64_t flow_min_time_schedule(int32_t n, int32_t m, const int32_t* eu,
                               const int32_t* ev, const int64_t* cap_const,
                               const int64_t* cap_per_t, int32_t s,
                               int32_t t_sink, int64_t required,
                               int64_t t_scale, int64_t* out_flows,
                               int64_t* out_achieved) {
  int64_t t_upper = 1;
  while (solve_at(n, m, eu, ev, cap_const, cap_per_t, s, t_sink, t_upper,
                  t_scale, nullptr) < required) {
    if (t_upper > kInf / 2) break;  // infeasible: no t can satisfy required
    t_upper *= 2;
  }

  int64_t lo = 1, hi = t_upper, best = t_upper;
  while (lo <= hi) {
    int64_t mid = lo + (hi - lo) / 2;
    if (solve_at(n, m, eu, ev, cap_const, cap_per_t, s, t_sink, mid, t_scale,
                 nullptr) < required) {
      lo = mid + 1;
    } else {
      best = best < mid ? best : mid;
      hi = mid - 1;
    }
  }

  int64_t achieved = solve_at(n, m, eu, ev, cap_const, cap_per_t, s, t_sink,
                              best, t_scale, out_flows);
  if (out_achieved != nullptr) *out_achieved = achieved;
  return best;
}

}  // extern "C"
