"""Incremental sharded HBM ingest: fragments land on-mesh as they arrive.

The runtime-facing device plane for receivers.  The reference's terminal
state is host RAM (``/root/reference/distributor/node.go:435-446``); the
TPU-native terminal state is the layer replicated in the HBM of its
pipeline stage's devices.  The naive way to get there — assemble on host,
then ``device_put`` the full layer replicated — pays the host→device link
``layer_size × n_devices`` bytes and only starts after the last network
byte.  This module does it the TPU way, with a platform-split terminal
hop (the two physical situations want opposite designs):

- **Accelerator (stream)**: the layer's byte range is tiled across the
  stage's devices (the same offset/size shape as a mode-3 flow plan,
  flow.go:193-211); each arriving fragment is cut against that tiling and
  each piece is DMA'd to its device immediately as its OWN buffer
  (``jax.device_put`` is asynchronous, so piece k+1's host-side staging
  overlaps piece k's DMA — and all of it overlaps the network receive).
  ``finalize`` splices the pieces with one on-device concat per device —
  HBM-bandwidth work, negligible next to the host-link DMA — then one
  tiled ``all_gather`` replicates the layer across the stage over ICI.
  PCIe carries ``layer_size`` bytes exactly once, pipelined; no
  preallocated zero-fill, no per-piece read-modify-write of a big buffer.
- **CPU backend (host-accumulate)**: there is no host→device link —
  "device memory" IS host memory, so any ``device_put`` is pure-overhead
  copying (measured ~5× slower than a plain memcpy on the bench host).
  Fragments are memcpy'd into a preallocated 64-byte-aligned host buffer
  per span, and ``finalize`` adopts each buffer zero-copy as its device's
  array via DLPack (``utils.hostmem``).  The full layer materializes with
  ONE host memcpy total — faster than the naive bulk ``device_put`` of
  the same bytes, which is exactly the bar ``bench.py`` measures.

``ingest_bytes`` is the one-shot form (whole buffer already on host) used
by mode-0/1/2 receivers; it routes through
``parallel.plan.execute_flow_plan`` with jobs synthesized by
``ops.reassembly.split_offsets`` — i.e. the dissemination runtime executes
its terminal hop as a flow plan on the mesh.
"""

from __future__ import annotations

import functools
import threading
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.reassembly import split_offsets
from ..sched.flow import FlowJob
from ..utils import hostmem, intervals, trace
from .collectives import gather_tiles, gather_tiles_batched
from .plan import execute_flow_plan
from .plan_cache import bucket_pad


def flat_mesh(devices: Sequence[jax.Device], axis: str = "ingest") -> Mesh:
    """A 1-axis mesh over an explicit device list (a stage's devices)."""
    return Mesh(np.asarray(list(devices), dtype=object), (axis,))


def synthesize_jobs(total_bytes: int, n: int, layer_id: int = 0) -> List[FlowJob]:
    """An even byte-range tiling of a layer as FlowJobs — the shape a mode-3
    plan has, for stages where the real per-seeder split isn't available."""
    return [
        FlowJob(sender_id=r, layer_id=layer_id, dest_id=0,
                data_size=size, offset=off)
        for r, (off, size) in enumerate(split_offsets(total_bytes, n))
        if size > 0
    ]


def ingest_bytes(data, devices: Sequence[jax.Device]) -> jax.Array:
    """One-shot sharded ingest: split ``data`` across ``devices`` (1/n of
    the host→device traffic each) and all-gather over ICI so the full
    layer lands replicated on all of them.  Returns a uint8 jax.Array.

    Single-CPU-device fast path: copy once into an aligned buffer and
    adopt it zero-copy (``utils.hostmem``) — a plain ``device_put`` here
    would memcpy the same bytes twice as slowly for no semantic gain."""
    data = memoryview(data)
    n = len(devices)
    if n == 1:
        if devices[0].platform == "cpu":
            buf = hostmem.aligned_empty(len(data))
            hostmem.copy_into(buf, 0, data)
            return hostmem.adopt_as_device_array(buf, devices[0])
        return jax.device_put(np.frombuffer(data, dtype=np.uint8), devices[0])
    if len(data) < n:
        # Too small to tile one byte per device; still must land replicated
        # on ALL the stage's devices (the documented contract).
        return jax.device_put(
            np.frombuffer(data, dtype=np.uint8),
            NamedSharding(flat_mesh(devices), P()),
        )
    mesh = flat_mesh(devices)
    jobs = synthesize_jobs(len(data), n)
    frags = [bytes(data[j.offset : j.offset + j.data_size]) for j in jobs]
    return execute_flow_plan(jobs, frags, mesh, "ingest", dtype=jnp.uint8)


def _concat_pad_impl(pieces, pad: int):
    """Splice offset-ordered pieces into one padded span buffer — a single
    compiled HBM-local concat (cached per piece-shape tuple, which repeats
    across a run's layers: every layer of a model shares its flow split)."""
    buf = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
    if buf.shape[0] < pad:
        buf = jnp.pad(buf, (0, pad - buf.shape[0]))
    return buf


_concat_pad_impl.__name__ = "_concat_pad"  # keep the traced name
_concat_pad = functools.partial(
    jax.jit, static_argnames=("pad",))(_concat_pad_impl)
# (A donate_argnums twin was tried here and measured useless: XLA
# donation is input→output ALIASING, and no concat output can alias an
# input buffer — the warning fires and nothing frees early.  The early-
# free that does work is reference-dropping: _span_buffers re-points the
# retained piece lists at the spliced buffers, so the piece originals
# free the moment the splice retires instead of living until close.)


class ShardedLayerIngest:
    """Incremental device ingest of one layer onto a device set.

    Fragments arrive in any order with byte offsets (the mode-3 receive
    path, node.go:1520-1567); ``write`` lands each piece on its span's
    device immediately — overlapping HBM ingest with the network receive —
    and ``finalize`` runs the splice + gather once coverage is complete.

    Thread-safe: the receiver's handler pool may deliver fragments
    concurrently.  ``write`` CLAIMS its uncovered byte ranges under the
    lock before moving any bytes (so overlapping duplicates never copy
    twice, and concurrent writers can't both land the same range), then
    does the heavy byte movement outside the lock
    (``utils.intervals.ClaimedCoverage`` — a failed claim rolls its
    coverage back), and ``finalize`` blocks until coverage is complete
    AND no claim is outstanding — so a completion handler racing a
    sibling fragment handler can never splice a buffer with holes.

    Peak device footprint is ~2× the layer's span bytes during the splice
    (pieces + concat output), same order as the gather epilogue the
    multi-device path already pays; the piece originals free the moment
    the splice retires (``_span_buffers`` re-points their retained
    references at the spliced buffers) instead of living until close.
    """

    def __init__(self, total_bytes: int, devices: Sequence[jax.Device],
                 stream: Optional[bool] = None):
        if total_bytes <= 0:
            raise ValueError("empty layer")
        self.total = total_bytes
        self.devices = list(devices)
        n = len(self.devices)
        # One span per device; spans differ by <=1 byte, buffers are padded
        # to the largest so the final gather is one tiled collective.
        self.spans: List[Tuple[int, int]] = list(split_offsets(total_bytes, n))
        self.pad = max(size for _, size in self.spans)
        # Gather pad: bucketed (plan_cache.bucket_pad) so near-equal
        # layers share ONE compiled gather executable.  Single-device
        # sets skip the gather entirely — their buffer must stay exactly
        # total-sized (zero-copy adoption depends on it).
        self.gpad = bucket_pad(self.pad) if n > 1 else self.pad
        # ``stream`` overrides the platform auto-split (None): tests and
        # CPU-mesh dryruns use it to exercise the accelerator arm.
        if stream is None:
            stream = not all(d.platform == "cpu" for d in self.devices)
        self._cpu = not stream
        self._lock = threading.Lock()
        self._complete = threading.Condition(self._lock)
        # Claim/commit coverage (shared discipline with the receiver's
        # fragment assembly): failed claims roll back, salvage reads only
        # committed ranges.
        self._cov = intervals.ClaimedCoverage()
        self._failed = False
        self._closed = False  # finalize/salvage ran: late writes no-op
        if self._cpu:
            # Host-accumulate (see module docstring).  gpad-sized so the
            # multi-device gather needs no reallocation; the tail past the
            # span's real size is never read (gather_tiles slices it off).
            self._host: Optional[List[np.ndarray]] = [
                hostmem.aligned_empty(self.gpad) for _ in range(n)
            ]
            self._pieces: Optional[List[List[Tuple[int, jax.Array]]]] = None
        else:
            self._host = None
            self._pieces = [[] for _ in range(n)]  # (local_off, piece)

    def share_host_buffer(self, buf) -> bool:
        """Adopt the caller's reassembly buffer as this ingest's (single)
        span buffer — the zero-copy CPU arm.

        When it succeeds, the caller's own assembly writes ARE the ingest
        (it reports them via :meth:`mark`), ``write`` is never needed,
        and ``finalize`` adopts the very same memory as the device array:
        the layer is staged with zero ingest-side copies.  Only valid on
        the CPU arm with one span (multi-span tilings place different
        byte ranges on different devices), with an adoptable buffer, and
        before any coverage landed.  Idempotent for the same buffer."""
        if not self._cpu or len(self.spans) != 1:
            return False
        with self._lock:
            if self._closed or self._failed:
                return False
            if self._host is not None and self._host[0] is buf:
                return True
            if self._cov.committed() or not self._cov.idle():
                return False  # bytes already landed in the old buffer
            if not (isinstance(buf, np.ndarray)
                    and hostmem.is_adoptable(buf)
                    and buf.nbytes == self.pad):
                return False
            self._host = [buf]
            return True

    def mark(self, offset: int, end: int) -> None:
        """Record externally-written coverage (shared-buffer mode): the
        caller already placed ``[offset, end)`` into the shared span
        buffer; only the coverage accounting remains."""
        with self._lock:
            if self._closed:
                return
            tok, _ = self._cov.claim(offset, end)
            if tok is not None:
                self._cov.commit(tok)
            if self._cov.idle():
                self._complete.notify_all()

    def write(self, offset: int, data) -> None:
        """Cut ``data`` (at absolute byte ``offset``) against the device
        tiling; move each piece toward its device's span.

        ``data`` is either a host buffer (bytes/bytearray/memoryview —
        the TCP receive path: pieces are host→device DMAs) or a 1-D uint8
        ``jax.Array`` already resident on some device (the pod-fabric
        path, ``parallel/fabric.py``: pieces are device→device transfers,
        which ride ICI on real hardware — the host link carries nothing)."""
        is_device = isinstance(data, jax.Array)
        if is_device:
            if data.ndim != 1 or data.dtype != np.uint8:
                raise ValueError("device fragments must be 1-D uint8")
            length = int(data.shape[0])
        else:
            data = memoryview(data)
            length = len(data)
        end = offset + length
        if offset < 0 or end > self.total:
            raise ValueError(
                f"fragment [{offset}, {end}) outside layer of {self.total} bytes"
            )
        with self._lock:
            if self._closed:
                # A late duplicate racing finalize: its bytes are already
                # covered (finalize only runs at full coverage).
                return
            tok, claims = self._cov.claim(offset, end)
            if tok is None:
                return  # full duplicate — idempotent
        landed: List[Tuple[int, int, jax.Array]] = []
        try:
            for lo, hi in claims:
                for r, (s_off, s_size) in enumerate(self.spans):
                    a = max(lo, s_off)
                    b = min(hi, s_off + s_size)
                    if a >= b:
                        continue
                    if self._cpu:
                        src = data[a - offset : b - offset]
                        piece = (np.asarray(src) if is_device
                                 else np.frombuffer(src, np.uint8))
                        # Claimed ranges are exclusive: concurrent writers
                        # memcpy into disjoint slices, safely lock-free
                        # (memmove-grade, GIL released — hostmem).
                        hostmem.copy_into(self._host[r], a - s_off, piece)
                    else:
                        if is_device:
                            src = data[a - offset : b - offset]  # on-src slice
                        else:
                            src = np.frombuffer(
                                data[a - offset : b - offset], np.uint8)
                        landed.append(
                            (r, a - s_off,
                             jax.device_put(src, self.devices[r]))
                        )
        except Exception:
            with self._lock:
                # Roll the claim's coverage back (its bytes never landed —
                # salvage must not report them) and poison the ingest so
                # finalize falls back to bulk staging.
                self._cov.abort(tok)
                self._failed = True
                self._complete.notify_all()
            raise
        with self._lock:
            self._cov.commit(tok)
            if not self._closed and self._pieces is not None:
                for r, local_off, piece in landed:
                    self._pieces[r].append((local_off, piece))
            if self._cov.idle():
                # Wakes finalize (full coverage) and salvage (quiescence).
                self._complete.notify_all()

    def _quiesce(self, timeout: float = 30.0) -> None:
        """Wait until no write claim is in flight (test/diagnostic hook;
        does NOT wait for full coverage)."""
        with self._lock:
            self._complete.wait_for(self._cov.idle, timeout=timeout)

    def fail(self) -> None:
        """Mark the ingest broken (a device write failed); wakes any
        ``finalize`` waiter, which then raises so the caller falls back to
        bulk staging."""
        with self._lock:
            self._failed = True
            self._complete.notify_all()

    def salvage(self) -> List[Tuple[int, bytes]]:
        """Read the covered byte ranges back out of the span buffers —
        the escape hatch when the gather collective (or a later write)
        fails: everything successfully written is already staged, so a
        host-side fallback assembly needs no retained copies of the
        in-flight fragments.  Closes the ingest."""
        with self._lock:
            # Quiesce in-flight claims first: coverage is reserved BEFORE
            # bytes move, so reading mid-claim could return holes; a
            # claim still in flight past the timeout is excluded by
            # committed().
            self._complete.wait_for(self._cov.idle, timeout=30.0)
            self._closed = True
            covered = self._cov.committed()
            if self._cpu:
                out: List[Tuple[int, bytes]] = []
                for s, e in covered:
                    for r, (s_off, s_size) in enumerate(self.spans):
                        lo = max(s, s_off)
                        hi = min(e, s_off + s_size)
                        if lo < hi:
                            out.append((
                                lo,
                                self._host[r][lo - s_off : hi - s_off]
                                .tobytes(),
                            ))
                return out
            pieces = [sorted(p) for p in self._pieces]
        out = []
        for r, (s_off, s_size) in enumerate(self.spans):
            for local_off, piece in pieces[r]:
                data = jax.device_get(piece).tobytes()
                # Spliced pieces are gpad-padded past the span's real
                # size; the pad tail is not layer bytes.
                data = data[: max(0, s_size - local_off)]
                if data:
                    out.append((s_off + local_off, data))
        return out

    def _splice(self, r: int, pieces: List[Tuple[int, jax.Array]]) -> jax.Array:
        """One device's offset-ordered pieces → its padded span buffer.
        Full coverage + exclusive claims guarantee the pieces tile the
        span exactly, so this is a straight concat (+ tail pad)."""
        if not pieces:  # a zero-size span (more devices than bytes)
            with jax.default_device(self.devices[r]):
                return jnp.zeros(self.gpad, dtype=jnp.uint8)
        if len(pieces) == 1 and pieces[0][1].shape[0] == self.gpad:
            return pieces[0][1]  # whole span arrived as one piece: no copy
        return _concat_pad([p for _, p in pieces], self.gpad)

    def _span_buffers(self, timeout: float = 120.0) -> List[jax.Array]:
        """Block until coverage is complete, close the ingest, and return
        one gpad-sized device-resident span buffer per device — the
        staged halves of the terminal gather.  The shared head of
        ``finalize`` and ``finalize_many``."""
        with self._lock:
            self._complete.wait_for(
                lambda: self._failed or self._cov.complete(self.total),
                timeout=timeout,
            )
            self._closed = True  # any write from here on is a no-op
            if self._failed:
                raise RuntimeError("ingest failed; fall back to bulk staging")
            if not self._cov.complete(self.total):
                landed = intervals.covered(self._cov.committed())
                raise RuntimeError(
                    f"ingest incomplete after {timeout}s: "
                    f"{landed}/{self.total} bytes landed"
                )
            pieces = (None if self._pieces is None
                      else [sorted(p) for p in self._pieces])
        n = len(self.devices)
        if self._cpu:
            # Zero-copy adoption: the aligned host buffers BECOME the
            # device arrays (the write memcpy was the only byte movement).
            # _closed guarantees nothing writes the buffers ever again.
            return [hostmem.adopt_as_device_array(b, d)
                    for b, d in zip(self._host, self.devices)]
        bufs = [self._splice(r, pieces[r]) for r in range(n)]
        with self._lock:
            # Early free: the piece originals are only retained for
            # salvage; the spliced buffers carry the same committed
            # bytes (salvage clamps their gpad tails to the span size),
            # so re-pointing releases the originals' device memory now
            # instead of at close.
            self._pieces = [[(0, b)] for b in bufs]
        return bufs

    def finalize(self, timeout: float = 120.0) -> jax.Array:
        """Splice the spans and (multi-device) all-gather them into the
        full layer, replicated on every device of the set.  Blocks until
        the ingest's own coverage is complete and no write is in flight.
        The returned array's device work may still be in flight — callers
        that must not ack unreal bytes block on it (or hand it to a
        ``fabric.PlanWindow``)."""
        with trace.phase("splice"):
            bufs = self._span_buffers(timeout)
        n = len(self.devices)
        if n == 1:  # split_offsets(total, 1): pad == gpad == total
            return bufs[0]
        mesh = flat_mesh(self.devices)
        global_shape = (n * self.gpad,)
        v = jax.make_array_from_single_device_arrays(
            global_shape, NamedSharding(mesh, P("ingest")), bufs
        )
        sizes = tuple(size for _, size in self.spans)
        return gather_tiles(mesh, "ingest", sizes, pad=self.gpad)(v)


def finalize_many(ingests: Sequence["ShardedLayerIngest"],
                  timeout: float = 120.0) -> List[jax.Array]:
    """Plan batching at the terminal hop: K same-tiling ingests finish as
    ONE batched gather — one collective dispatch and one compiled
    executable for the whole batch, instead of K serial finalizes.

    All ingests must share the device set and span tiling (equal-size
    layers — the common mode-3 case; ``runtime/receiver.py`` groups them
    by the leader's batch hints).  Each device concatenates its K span
    buffers locally (HBM-bandwidth work) and the batched gather
    replicates every layer on every device.  Returns one replicated
    layer per ingest, in order; raises if any ingest failed or the
    tilings differ — the caller then falls back to per-plan finalize."""
    if not ingests:
        return []
    first = ingests[0]
    if len(ingests) == 1:
        return [first.finalize(timeout)]
    for ing in ingests[1:]:
        if (ing.devices != first.devices or ing.spans != first.spans
                or ing._cpu != first._cpu or ing.gpad != first.gpad):
            raise ValueError("batched ingests must share device tiling")
    n = len(first.devices)
    if n == 1:
        # No gather to batch: each finalize is already collective-free.
        return [ing.finalize(timeout) for ing in ingests]
    k = len(ingests)
    with trace.phase("splice"):
        per_ingest = [ing._span_buffers(timeout) for ing in ingests]
        # Device-local stacking: K gpad-sized tiles back to back.  The
        # inputs are committed to device r, so the concat runs there.
        shards = [
            jnp.concatenate([per_ingest[i][r] for i in range(k)])
            for r in range(n)
        ]
    mesh = flat_mesh(first.devices)
    v = jax.make_array_from_single_device_arrays(
        (n * k * first.gpad,), NamedSharding(mesh, P("ingest")), shards
    )
    sizes = tuple(size for _, size in first.spans)
    out = gather_tiles_batched(
        mesh, "ingest", sizes, tuple(range(n)), k, pad=first.gpad
    )(v)
    return [out[i] for i in range(k)]


def hbm_headroom_bytes(device=None):
    """Free HBM on ``device`` (default: the first local device), or
    ``None`` when the platform doesn't report memory stats (CPU
    backend, some plugins).  The zero-downtime swap's staging policy
    reads this per layer (docs/swap.md): a v2 blob decodes straight
    into HBM only when the headroom comfortably covers it, and falls
    back to host-RAM staging when tight — ``None`` means "unknown",
    which callers treat per their own risk posture (the swap treats it
    as roomy: on the CPU backend device memory IS host memory)."""
    try:
        import jax

        d = device if device is not None else jax.devices()[0]
        stats = d.memory_stats()
        if not stats:
            return None
        limit = stats.get("bytes_limit")
        used = stats.get("bytes_in_use")
        if limit is None or used is None:
            return None
        return max(0, int(limit) - int(used))
    except Exception:  # noqa: BLE001 — a probe must never break staging
        return None
