"""Incremental sharded HBM ingest: fragments land on-mesh as they arrive.

The runtime-facing device plane for receivers.  The reference's terminal
state is host RAM (``/root/reference/distributor/node.go:435-446``); the
TPU-native terminal state is the layer replicated in the HBM of its
pipeline stage's devices.  The naive way to get there — assemble on host,
then ``device_put`` the full layer replicated — pays the host→device link
``layer_size × n_devices`` bytes and only starts after the last network
byte.  This module does it the TPU way:

- The layer's byte range is tiled across the stage's devices (the same
  offset/size shape as a mode-3 flow plan, flow.go:193-211).
- Each arriving network fragment is cut against that tiling and each piece
  is DMA'd to exactly ONE device, into a preallocated shard buffer at its
  local offset (``lax.dynamic_update_slice`` under donation) — so PCIe
  carries ``layer_size`` bytes total, overlapped with the network receive.
- On completion, one tiled ``all_gather`` replicates the layer across the
  stage over ICI — the fast fabric does the ×n, not the host link.

``ingest_bytes`` is the one-shot form (whole buffer already on host) used
by mode-0/1/2 receivers; it routes through
``parallel.plan.execute_flow_plan`` with jobs synthesized by
``ops.reassembly.split_offsets`` — i.e. the dissemination runtime executes
its terminal hop as a flow plan on the mesh.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.reassembly import _write_1d, split_offsets
from ..sched.flow import FlowJob
from ..utils import intervals
from .collectives import gather_tiles
from .plan import execute_flow_plan


def flat_mesh(devices: Sequence[jax.Device], axis: str = "ingest") -> Mesh:
    """A 1-axis mesh over an explicit device list (a stage's devices)."""
    return Mesh(np.asarray(list(devices), dtype=object), (axis,))


def synthesize_jobs(total_bytes: int, n: int, layer_id: int = 0) -> List[FlowJob]:
    """An even byte-range tiling of a layer as FlowJobs — the shape a mode-3
    plan has, for stages where the real per-seeder split isn't available."""
    return [
        FlowJob(sender_id=r, layer_id=layer_id, dest_id=0,
                data_size=size, offset=off)
        for r, (off, size) in enumerate(split_offsets(total_bytes, n))
        if size > 0
    ]


def ingest_bytes(data, devices: Sequence[jax.Device]) -> jax.Array:
    """One-shot sharded ingest: split ``data`` across ``devices`` (1/n of
    the host→device traffic each) and all-gather over ICI so the full
    layer lands replicated on all of them.  Returns a uint8 jax.Array."""
    data = memoryview(data)
    n = len(devices)
    if n == 1:
        return jax.device_put(np.frombuffer(data, dtype=np.uint8), devices[0])
    if len(data) < n:
        # Too small to tile one byte per device; still must land replicated
        # on ALL the stage's devices (the documented contract).
        return jax.device_put(
            np.frombuffer(data, dtype=np.uint8),
            NamedSharding(flat_mesh(devices), P()),
        )
    mesh = flat_mesh(devices)
    jobs = synthesize_jobs(len(data), n)
    frags = [bytes(data[j.offset : j.offset + j.data_size]) for j in jobs]
    return execute_flow_plan(jobs, frags, mesh, "ingest", dtype=jnp.uint8)


class ShardedLayerIngest:
    """Incremental device ingest of one layer onto a device set.

    Fragments arrive in any order with byte offsets (the mode-3 receive
    path, node.go:1520-1567); ``write`` lands each piece on its span's
    device immediately — overlapping HBM ingest with the network receive —
    and ``finalize`` runs the gather collective once coverage is complete.

    Thread-safe: the receiver's handler pool may deliver fragments
    concurrently.  The ingest keeps its OWN byte-coverage intervals, and
    ``finalize`` blocks until they cover the layer — so a completion
    handler racing a sibling fragment handler (host coverage counted, device
    write not yet executed) can never gather a buffer with holes.
    """

    def __init__(self, total_bytes: int, devices: Sequence[jax.Device]):
        if total_bytes <= 0:
            raise ValueError("empty layer")
        self.total = total_bytes
        self.devices = list(devices)
        n = len(self.devices)
        # One span per device; spans differ by <=1 byte, buffers are padded
        # to the largest so the final gather is one tiled collective.
        self.spans: List[Tuple[int, int]] = list(split_offsets(total_bytes, n))
        self.pad = max(size for _, size in self.spans)
        self._lock = threading.Lock()
        self._complete = threading.Condition(self._lock)
        self._covered: List[Tuple[int, int]] = []
        self._failed = False
        self._closed = False  # finalize ran: late duplicate writes no-op
        # Zeros are created ON each device (no host materialization, no
        # host->device transfer of bytes that are about to be overwritten).
        self._bufs: List[jax.Array] = []
        for d in self.devices:
            with jax.default_device(d):
                self._bufs.append(jnp.zeros(self.pad, dtype=jnp.uint8))

    def write(self, offset: int, data) -> None:
        """Cut ``data`` (at absolute byte ``offset``) against the device
        tiling; move each piece to its device's shard buffer.

        ``data`` is either a host buffer (bytes/bytearray/memoryview —
        the TCP receive path: pieces are host→device DMAs) or a 1-D uint8
        ``jax.Array`` already resident on some device (the pod-fabric
        path, ``parallel/fabric.py``: pieces are device→device transfers,
        which ride ICI on real hardware — the host link carries nothing)."""
        is_device = isinstance(data, jax.Array)
        if is_device:
            if data.ndim != 1 or data.dtype != np.uint8:
                raise ValueError("device fragments must be 1-D uint8")
            length = int(data.shape[0])
        else:
            data = memoryview(data)
            length = len(data)
        end = offset + length
        if offset < 0 or end > self.total:
            raise ValueError(
                f"fragment [{offset}, {end}) outside layer of {self.total} bytes"
            )
        if self._closed:
            # Cheap early exit for a late duplicate racing finalize (benign
            # race: _closed only transitions False→True; the locked check
            # below still guards the donation chain).
            return
        # Cut against the tiling and issue the host→device DMAs OUTSIDE the
        # lock: the 16-worker handler pool must not serialize behind device
        # transfers (nor block finalize waiters on them).  The lock is then
        # held only to swap the donated shard buffers (dispatch-only; the
        # donation chain requires exclusive ownership of _bufs) and to
        # update coverage.
        pieces = []
        for r, (s_off, s_size) in enumerate(self.spans):
            lo = max(offset, s_off)
            hi = min(end, s_off + s_size)
            if lo >= hi:
                continue
            if is_device:
                piece = data[lo - offset : hi - offset]  # lazy on-src slice
            else:
                piece = np.frombuffer(data[lo - offset : hi - offset], np.uint8)
            pieces.append(
                (r, lo - s_off, jax.device_put(piece, self.devices[r]))
            )
        with self._lock:
            if self._closed:
                # A late duplicate racing finalize: its bytes are already
                # covered (finalize only runs at full coverage), and a
                # donating write here would invalidate the buffers the
                # gather is consuming.
                return
            for r, local_off, dev_piece in pieces:
                self._bufs[r] = _write_1d(
                    self._bufs[r], dev_piece, jnp.asarray(local_off, jnp.int32)
                )
            self._covered = intervals.insert(self._covered, offset, end)
            if intervals.covered(self._covered) >= self.total:
                self._complete.notify_all()

    def fail(self) -> None:
        """Mark the ingest broken (a device write failed); wakes any
        ``finalize`` waiter, which then raises so the caller falls back to
        bulk staging."""
        with self._lock:
            self._failed = True
            self._complete.notify_all()

    def salvage(self) -> List[Tuple[int, bytes]]:
        """Read the covered byte ranges back out of the shard buffers
        (device→host) — the escape hatch when the gather collective (or a
        later write) fails: everything successfully written is already on
        the dest's devices, so a host-side fallback assembly needs no
        retained copies of the in-flight fragments.  Closes the ingest."""
        with self._lock:
            self._closed = True
            covered = list(self._covered)
            bufs = [np.asarray(jax.device_get(b)) for b in self._bufs]
        out: List[Tuple[int, bytes]] = []
        for s, e in covered:
            for r, (s_off, s_size) in enumerate(self.spans):
                lo = max(s, s_off)
                hi = min(e, s_off + s_size)
                if lo < hi:
                    out.append((lo, bufs[r][lo - s_off : hi - s_off].tobytes()))
        return out

    def finalize(self, timeout: float = 120.0) -> jax.Array:
        """All-gather the shard buffers into the full layer, replicated on
        every device of the set.  Blocks until the ingest's own coverage is
        complete (in-flight sibling writes), then gathers."""
        with self._lock:
            self._complete.wait_for(
                lambda: self._failed
                or intervals.covered(self._covered) >= self.total,
                timeout=timeout,
            )
            self._closed = True  # any write from here on is a no-op
            if self._failed:
                raise RuntimeError("ingest failed; fall back to bulk staging")
            if intervals.covered(self._covered) < self.total:
                raise RuntimeError(
                    f"ingest incomplete after {timeout}s: "
                    f"{intervals.covered(self._covered)}/{self.total} bytes"
                )
            bufs = list(self._bufs)
        if len(self.devices) == 1:
            # split_offsets(total, 1) gives one exact span, so pad == total
            # and the shard buffer IS the layer — a [:total] slice here
            # would be a full-layer HBM copy for nothing.
            return bufs[0] if self.pad == self.total else bufs[0][: self.total]
        mesh = flat_mesh(self.devices)
        n = len(self.devices)
        global_shape = (n * self.pad,)
        v = jax.make_array_from_single_device_arrays(
            global_shape, NamedSharding(mesh, P("ingest")), bufs
        )
        sizes = tuple(size for _, size in self.spans)
        return gather_tiles(mesh, "ingest", sizes)(v)
