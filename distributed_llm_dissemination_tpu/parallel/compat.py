"""JAX API compatibility: one import site for renamed entry points.

The device plane targets the current ``jax.shard_map(f, mesh=...,
in_specs=..., out_specs=..., check_vma=...)`` API.  Older runtimes (the
0.4.x line some containers bake in) only ship it as
``jax.experimental.shard_map.shard_map`` with the replication check
named ``check_rep``.  Every collective in this package routes through
THIS wrapper so the whole data plane works on both, instead of each
call site dying with ``module 'jax' has no attribute 'shard_map'`` and
silently demoting fabric transfers to the host path.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

else:  # pre-alias runtimes: the experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


if hasattr(jax.lax, "axis_size"):

    def axis_size(axis) -> int:
        """Static size of a named mesh axis inside a manual context."""
        return jax.lax.axis_size(axis)

else:

    def axis_size(axis) -> int:
        """Static size of a named mesh axis inside a manual context.
        Pre-``lax.axis_size`` runtimes record it in the trace's axis
        environment: late 0.4.x ``axis_frame`` returns the size itself
        (an int), earlier releases return a frame object carrying it."""
        import jax.core as _core

        frame = _core.axis_frame(axis)
        return frame if isinstance(frame, int) else frame.size
