"""WeightMover: staged host→HBM transfer of layer bytes.

The TPU replacement for the reference's terminal delivery state: where the
Go system leaves layer bytes in host RAM (``InmemLayer``,
``/root/reference/distributor/node.go:435-446``), this framework stages
them into device HBM as jax Arrays (``LayerLocation.HBM``).  Bulk staging
is pipelined: ``jax.device_put`` is async, so every layer's host view is
prepared and its DMA issued before the first completion is awaited — the
overlap that keeps HBM ingest at line rate.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import LayerID, LayerLocation, LayerSrc, LayersSrc
from ..utils.logging import log


def bytes_to_array(data, dtype=jnp.bfloat16) -> np.ndarray:
    """View raw layer bytes (any buffer: bytes, bytearray, ndarray) as a
    1-D device-ready array, zero-padding to the dtype's itemsize.  Aligned
    inputs are zero-copy views."""
    itemsize = np.dtype(dtype).itemsize
    n = len(data)
    rem = n % itemsize
    if rem:
        padded = np.empty(n + itemsize - rem, dtype=np.uint8)
        padded[:n] = np.frombuffer(data, dtype=np.uint8)
        padded[n:] = 0
        return padded.view(np.dtype(dtype))
    return np.frombuffer(data, dtype=np.uint8).view(np.dtype(dtype))


def array_to_bytes(arr: jax.Array) -> bytes:
    """Round-trip: HBM array back to the raw byte blob."""
    return np.asarray(jax.device_get(arr)).tobytes()


@dataclasses.dataclass
class StageResult:
    layer_id: LayerID
    array: jax.Array
    nbytes: int
    seconds: float


class WeightMover:
    """Moves layer byte blobs into device HBM under a given sharding.

    ``sharding`` defaults to single-device placement; pass a
    ``NamedSharding`` to land a layer replicated/sharded across a mesh
    stage in one hop (XLA performs the host→HBM scatter/broadcast).
    """

    def __init__(self, sharding=None, dtype=jnp.bfloat16):
        self.sharding = sharding
        self.dtype = dtype

    def _placement(self, device=None):
        if device is not None:
            return device
        if self.sharding is not None:
            return self.sharding
        return jax.devices()[0]

    @staticmethod
    def _host_view(layer: LayerSrc):
        """Zero-copy host buffer when the layer is RAM-resident."""
        if layer.meta.location == LayerLocation.INMEM and layer.inmem_data is not None:
            return layer.inmem_data
        return layer.read_bytes()

    def stage(self, layer: LayerSrc, device=None) -> jax.Array:
        """One layer host→HBM; updates the LayerSrc in place to HBM state."""
        host = bytes_to_array(self._host_view(layer), self.dtype)
        arr = jax.device_put(host, self._placement(device))
        layer.device_array = arr
        layer.meta.location = LayerLocation.HBM
        return arr

    def stage_layers(
        self,
        layers: LayersSrc,
        order: Optional[Sequence[LayerID]] = None,
        device=None,
    ) -> List[StageResult]:
        """Pipelined bulk staging: issue device_put for every layer (each
        returns immediately, so layer N+1's host view is prepared while N's
        DMA is in flight), then drain completions in order.  A layer's
        ``seconds`` is its *completion delta* — time from the previous
        layer's completion (or batch start) to its own — so the per-layer
        figures sum to the batch wall time and each one's bytes/seconds is a
        meaningful ingest rate for that layer's slot in the pipeline."""
        ids = list(order if order is not None else sorted(layers))
        placement = self._placement(device)
        results: List[StageResult] = []
        in_flight: List[Tuple[LayerID, jax.Array, int]] = []
        prev = time.monotonic()  # batch start: host prep counts as ingest
        for lid in ids:
            layer = layers[lid]
            host = bytes_to_array(self._host_view(layer), self.dtype)
            arr = jax.device_put(host, placement)  # async: returns immediately
            in_flight.append((lid, arr, host.nbytes))
            layer.device_array = arr
            layer.meta.location = LayerLocation.HBM
        for lid, arr, nbytes in in_flight:
            arr.block_until_ready()
            now = time.monotonic()
            dt = now - prev
            prev = now
            results.append(StageResult(lid, arr, nbytes, dt))
            log.debug(
                "layer staged to HBM",
                layerID=lid,
                mib=round(nbytes / (1 << 20), 2),
                gbps=round(nbytes / max(dt, 1e-9) / 1e9, 2),
            )
        return results

    def throughput_gbps(self, results: Iterable[StageResult]) -> float:
        """Aggregate ingest throughput: total bytes over the batch span
        (completion deltas sum to last-completion − batch start)."""
        results = list(results)
        total = sum(r.nbytes for r in results)
        span = sum(r.seconds for r in results)
        return total / max(span, 1e-9) / 1e9
