"""Compiled-collective executable cache: the device plane's warm path.

Every gather the dissemination runtime dispatches is an XLA program
compiled for one (mesh, axis, tile pad, batch) shape.  At physical layer
sizes the compile dominates the transfer (the recorded
``physical_4node_fabric`` row spent ~22x the TCP row's wall clock, most
of it per-plan compile + dispatch latency) — yet mode-3 tilings repeat
across a model's layers, so the same executable can serve every one of
them.  This module makes that reuse explicit and measurable:

- ``ExecutableCache``: a keyed LRU over built executables with hit/miss
  counters and cumulative build (compile) seconds, so a run can assert
  "compiled once, reused k times" instead of hoping.
- ``bucket_pad``: rounds a tile pad up to a small bucket set (top three
  significant bits, <=12.5% waste) so layers of *near*-equal size land
  on the same executable key instead of each compiling their own.

The idea is the reusable-collective-program framing of arXiv:2112.01075
(redistribution as a compiled, portable collective) applied to the
dissemination terminal hop.  ``collectives.gather_tiles_at`` routes its
gather programs through ``GATHER_CACHE`` and its splice programs through
``SPLICE_CACHE``; ``stats()`` aggregates both for harness reports
(``bench.py``, ``cli/podrun.py`` → ``cli/ttd_matrix.py``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Hashable

from ..utils.logging import log

# Pads below this round up to exactly this (one bucket for all tiny
# tiles — a 17-byte and a 40-byte control blob share one program).
_BUCKET_FLOOR = 64


def bucket_pad(pad: int) -> int:
    """Round ``pad`` up to the bucket set: the next value with at most
    the top FOUR bits significant (64, 68, ..., 128, 136, 144, ...).
    Guarantees <=12.5% padding waste while collapsing the unbounded
    space of layer sizes onto 16 buckets per power of two — distinct
    layers of near-equal size then reuse one compiled gather."""
    if pad <= _BUCKET_FLOOR:
        return _BUCKET_FLOOR
    granule = 1 << max(0, pad.bit_length() - 4)
    return -(-pad // granule) * granule


class ExecutableCache:
    """Keyed LRU over built executables, with reuse accounting.

    ``get(key, builder)`` returns the cached executable for ``key`` or
    builds (and times) it.  Builds run under the lock on purpose: two
    concurrent plans with the same shape must compile ONCE, not race two
    multi-second XLA compiles for the same program."""

    def __init__(self, kind: str, capacity: int = 128):
        self.kind = kind
        self.capacity = capacity
        self._lock = threading.RLock()
        self._store: Dict[Hashable, object] = {}  # insertion-ordered LRU
        self.hits = 0
        self.misses = 0
        self.build_s = 0.0

    def get(self, key: Hashable, builder: Callable[[], object]):
        with self._lock:
            if key in self._store:
                self.hits += 1
                self._store[key] = self._store.pop(key)  # LRU touch
                return self._store[key]
            self.misses += 1
            t0 = time.monotonic()
            built = builder()
            dt = time.monotonic() - t0
            self.build_s += dt
            self._store[key] = built
            while len(self._store) > self.capacity:
                self._store.pop(next(iter(self._store)))
            log.debug("collective executable built", kind=self.kind,
                      compile_ms=round(dt * 1000, 1),
                      cached=len(self._store))
            return built

    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "compile_ms": round(self.build_s * 1000, 1)}

    def reset(self) -> None:
        """Drop entries AND counters (tests/benchmarks isolate runs)."""
        with self._lock:
            self._store.clear()
            self.hits = 0
            self.misses = 0
            self.build_s = 0.0


# The two program families of the terminal hop: the collective itself
# (expensive: shard_map + all_gather, keyed only by mesh/axis/pad/batch
# so bucketed same-shape plans share it) and the device-local re-splice
# (cheap, keyed by the exact tile sizes).
GATHER_CACHE = ExecutableCache("gather")
SPLICE_CACHE = ExecutableCache("splice")


def stats() -> dict:
    """Aggregate cache stats for harness reports: overall hits/misses/
    compile plus the per-family split."""
    g, s = GATHER_CACHE.stats(), SPLICE_CACHE.stats()
    return {
        "hits": g["hits"] + s["hits"],
        "misses": g["misses"] + s["misses"],
        "compile_ms": round(g["compile_ms"] + s["compile_ms"], 1),
        "gather": g,
        "splice": s,
    }


def reset_stats() -> None:
    GATHER_CACHE.reset()
    SPLICE_CACHE.reset()


def log_stats() -> None:
    """One structured record of the run's executable reuse — harnesses
    grep this to assert hits > misses on multi-layer rounds."""
    log.info("collective cache stats", **stats())
