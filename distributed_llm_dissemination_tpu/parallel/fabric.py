"""Pod-fabric data plane: layer bytes ride the device fabric, not TCP.

The north-star replacement for the reference's inter-node data plane
(``/root/reference/distributor/transport.go:267-274, 308-373``): when every
node of a topology is a stage of ONE device mesh (a TPU pod), a scheduled
layer transfer needs no socket stream at all.  The leader turns its plan
into a ``DevicePlanMsg`` — a small control message listing per-sender byte
ranges — and the bytes move as device traffic:

1. each *seeder* uploads exactly its planned byte range onto its own
   stage's devices (the host→HBM hop it would have paid to serve a TCP
   send anyway),
2. the *destination* pulls every contribution into its stage's shard
   buffers — a device-to-device transfer that rides ICI on real hardware —
   and one tiled all-gather replicates the finished layer within the stage
   (``parallel.ingest.ShardedLayerIngest`` fed device arrays).

TCP carries only the control plane (announce/plan/ack/startup), exactly
the split SURVEY §1 calls the key design idea to preserve.

``FabricPlane`` is the rendezvous between the two halves.  Under a single
controller (one process addressing the whole mesh — the virtual-device
test topology, or a single-process pod driver) it is an in-process
registry: publish/collect by plan id.  Under multi-controller SPMD the
same hand-off is the compiled collective itself (every process enters
``jax.jit`` with its local shards); that path needs ``jax.distributed``
mesh formation first (see ``parallel/multihost.py``) and is documented in
the README runbook rather than wired here.

A sender serving the same layer to two destinations publishes one
contribution per plan (each dest's plan has its own id) — the fabric
analogue of the reference opening one fresh connection per transfer
(transport.go:267-274).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Iterator, List, Tuple

from ..utils import trace
from ..utils.logging import log


class FabricPlane:
    """In-process publish/collect rendezvous for device-plan transfers.

    Contributions are ``(byte_offset, uint8 device array)`` pairs keyed by
    plan id.  ``collect`` yields them *as they arrive*, so a destination
    overlaps its ICI ingest with later senders' host→HBM uploads instead
    of waiting for the full set."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # plan_id -> list of (offset, device array)
        self._contribs: Dict[str, List[Tuple[int, object]]] = {}
        # plan_id -> last-publish monotonic time, for stale-plan GC (a plan
        # whose dest died would otherwise pin device buffers forever).
        self._touched: Dict[str, float] = {}
        # Pod-delivery shard board (docs/fabric.md): key -> {rank: bytes}.
        # Unlike ``_contribs`` (one consumer per plan), every pod member
        # reads the SAME shard set — entries are refcounted out by
        # ``pod_done`` (one call per member) instead of consumed by the
        # first collect.  This is the single-controller stand-in for the
        # ICI hop: each member's shard crosses process memory, never the
        # accounted NIC links.
        self._pod_parts: Dict[object, Dict[int, bytes]] = {}
        self._pod_done: Dict[object, set] = {}

    def publish(self, plan_id: str, offset: int, arr) -> None:
        """Sender side: register one device-resident byte-range fragment."""
        with self._cond:
            self._contribs.setdefault(plan_id, []).append((offset, arr))
            self._touched[plan_id] = time.monotonic()
            self._cond.notify_all()

    def collect(
        self, plan_id: str, count: int, timeout: float = 120.0
    ) -> Iterator[Tuple[int, object]]:
        """Destination side: yield ``count`` contributions as they arrive.

        Raises ``TimeoutError`` if the remaining contributions don't show
        up in time (a crashed seeder — the leader's failure detector will
        re-plan; the superseding plan has a fresh id).  The plan's entries
        are discarded once fully consumed; abandon via ``discard``."""
        got = 0
        deadline = time.monotonic() + timeout
        while got < count:
            with self._cond:
                while len(self._contribs.get(plan_id, ())) <= got:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise TimeoutError(
                            f"plan {plan_id}: {got}/{count} contributions "
                            f"after {timeout}s"
                        )
                    self._cond.wait(left)
                fresh = list(self._contribs[plan_id][got:])
            for item in fresh:
                yield item
                got += 1
        self.discard(plan_id)

    def discard(self, plan_id: str) -> None:
        """Drop a plan's buffered contributions (frees their device
        arrays once the consumer releases its references)."""
        with self._cond:
            self._contribs.pop(plan_id, None)
            self._touched.pop(plan_id, None)

    # --------------------------------------------- pod shard board

    def pod_publish(self, key, rank: int, data) -> None:
        """Pod member side: register shard ``rank``'s wire bytes under
        ``key`` (one key per (layer, pod)).  Duplicates no-op — a
        re-plan re-completion must not flap the set."""
        with self._cond:
            parts = self._pod_parts.setdefault(key, {})
            if rank not in parts:
                parts[rank] = bytes(data)
            self._touched[("pod", key)] = time.monotonic()
            self._cond.notify_all()

    def pod_wait_new(self, key, have: int, timeout: float):
        """Block until the board holds MORE than ``have`` shards for
        ``key`` (any completion order), then return a snapshot of the
        full ``{rank: bytes}`` map; None on timeout.  Members drain the
        board incrementally: each new shard feeds ``submit_shard`` the
        moment it appears, so the gather fires on the last arrival."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while len(self._pod_parts.get(key) or ()) <= have:
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self._cond.wait(left)
            return dict(self._pod_parts[key])

    def pod_done(self, key, members: int, who=None) -> None:
        """Member ``who`` finished gathering ``key``; the entry drops
        once ``members`` DISTINCT members have (a set, not a counter —
        one member's retry after a timeout must not double-count and
        drop the board under a slower member still draining it)."""
        with self._cond:
            done = self._pod_done.setdefault(key, set())
            done.add(who)
            if len(done) >= members:
                self._pod_parts.pop(key, None)
                self._pod_done.pop(key, None)
                self._touched.pop(("pod", key), None)

    def gc(self, max_age: float = 600.0) -> int:
        """Drop plans idle longer than ``max_age`` seconds; returns how
        many were dropped.  Cheap enough to call opportunistically."""
        cutoff = time.monotonic() - max_age
        with self._cond:
            stale = [p for p, ts in self._touched.items() if ts < cutoff]
            for p in stale:
                if isinstance(p, tuple) and p and p[0] == "pod":
                    self._pod_parts.pop(p[1], None)
                    self._pod_done.pop(p[1], None)
                else:
                    self._contribs.pop(p, None)
                self._touched.pop(p, None)
        return len(stale)

    def pending(self) -> int:
        with self._cond:
            return len(self._contribs)


class PlanWindow:
    """Full in-flight window over dispatched plan collectives.

    JAX dispatch is async, but the legacy dest path round-tripped per
    plan: dispatch the gather, ``block_until_ready``, ack, next plan —
    so plan k+1's host staging and uploads idled behind plan k's
    collective.  This window keeps up to ``max_plans`` (and
    ``byte_budget`` bytes) of dispatched collectives in flight: callers
    ``submit`` the un-blocked array with completion callbacks and move
    straight on to the next plan's staging; a retirement thread blocks
    on the OLDEST array and fires ``on_ready`` only once its device work
    really finished — an ack can never name bytes that might still
    fail.  ``submit`` blocks (backpressure) when the window is full, so
    device memory stays bounded.

    The collective wall time of each plan (submit → device-ready) lands
    in the ``collective`` phase bucket (``utils.trace``)."""

    def __init__(self, max_plans: int = 4, byte_budget: int = 2 << 30):
        self.max_plans = max(1, max_plans)
        self.byte_budget = byte_budget
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._q: collections.deque = collections.deque()
        self._bytes = 0
        self._retiring = False  # a popped entry's callback is running
        self._closed = False
        # ONE retirement thread, started eagerly: a lazy check-and-start
        # from submit() could race two first submitters into two threads
        # both retiring the same queue head (double-ack + a dropped
        # callback).  The window itself is created lazily by its owner,
        # so idle receivers never pay for the thread.
        self._thread = threading.Thread(
            target=self._run, name="plan-window", daemon=True
        )
        self._thread.start()

    def submit(self, label: str, arr, nbytes: int,
               on_ready: Callable, on_error: Callable) -> None:
        """Enqueue one dispatched collective; blocks while the window is
        full (the caller IS the backpressure point)."""
        with self._cond:
            self._cond.wait_for(
                lambda: self._closed
                or (len(self._q) < self.max_plans
                    and (self._bytes + nbytes <= self.byte_budget
                         or not self._q))
            )
            if self._closed:
                raise RuntimeError("plan window closed")
            self._q.append((label, arr, nbytes, on_ready, on_error,
                            time.monotonic()))
            self._bytes += nbytes
            self._cond.notify_all()

    def _run(self) -> None:
        import jax

        while True:
            with self._cond:
                self._cond.wait_for(lambda: self._q or self._closed)
                if not self._q and self._closed:
                    return
                label, arr, nbytes, on_ready, on_error, t0 = self._q[0]
            err = None
            try:
                jax.block_until_ready(arr)
            except Exception as e:  # noqa: BLE001 — surface via callback
                err = e
            dt = time.monotonic() - t0
            trace.add_phase("collective", dt)
            with self._cond:
                # Popped for CAPACITY before the callback runs (the next
                # submit may proceed), but drain() also waits on
                # _retiring so "drained" really means the callback —
                # store + ack — finished, not just the pop.
                self._q.popleft()
                self._bytes -= nbytes
                self._retiring = True
                self._cond.notify_all()
            try:
                if err is None:
                    on_ready(arr, dt)
                else:
                    on_error(err)
            except Exception as e:  # noqa: BLE001 — a callback must not
                log.error("plan window callback failed", plan=label,
                          err=repr(e))  # kill the retirement loop
            finally:
                with self._cond:
                    self._retiring = False
                    self._cond.notify_all()

    def drain(self, timeout: float = 120.0) -> bool:
        """Block until every submitted plan retired — queue empty AND the
        last retirement's callback returned (tests/shutdown: an ack may
        ride that callback)."""
        with self._cond:
            return self._cond.wait_for(
                lambda: not self._q and not self._retiring, timeout=timeout)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
