"""Pod-fabric data plane: layer bytes ride the device fabric, not TCP.

The north-star replacement for the reference's inter-node data plane
(``/root/reference/distributor/transport.go:267-274, 308-373``): when every
node of a topology is a stage of ONE device mesh (a TPU pod), a scheduled
layer transfer needs no socket stream at all.  The leader turns its plan
into a ``DevicePlanMsg`` — a small control message listing per-sender byte
ranges — and the bytes move as device traffic:

1. each *seeder* uploads exactly its planned byte range onto its own
   stage's devices (the host→HBM hop it would have paid to serve a TCP
   send anyway),
2. the *destination* pulls every contribution into its stage's shard
   buffers — a device-to-device transfer that rides ICI on real hardware —
   and one tiled all-gather replicates the finished layer within the stage
   (``parallel.ingest.ShardedLayerIngest`` fed device arrays).

TCP carries only the control plane (announce/plan/ack/startup), exactly
the split SURVEY §1 calls the key design idea to preserve.

``FabricPlane`` is the rendezvous between the two halves.  Under a single
controller (one process addressing the whole mesh — the virtual-device
test topology, or a single-process pod driver) it is an in-process
registry: publish/collect by plan id.  Under multi-controller SPMD the
same hand-off is the compiled collective itself (every process enters
``jax.jit`` with its local shards); that path needs ``jax.distributed``
mesh formation first (see ``parallel/multihost.py``) and is documented in
the README runbook rather than wired here.

A sender serving the same layer to two destinations publishes one
contribution per plan (each dest's plan has its own id) — the fabric
analogue of the reference opening one fresh connection per transfer
(transport.go:267-274).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Tuple


class FabricPlane:
    """In-process publish/collect rendezvous for device-plan transfers.

    Contributions are ``(byte_offset, uint8 device array)`` pairs keyed by
    plan id.  ``collect`` yields them *as they arrive*, so a destination
    overlaps its ICI ingest with later senders' host→HBM uploads instead
    of waiting for the full set."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # plan_id -> list of (offset, device array)
        self._contribs: Dict[str, List[Tuple[int, object]]] = {}
        # plan_id -> last-publish monotonic time, for stale-plan GC (a plan
        # whose dest died would otherwise pin device buffers forever).
        self._touched: Dict[str, float] = {}

    def publish(self, plan_id: str, offset: int, arr) -> None:
        """Sender side: register one device-resident byte-range fragment."""
        with self._cond:
            self._contribs.setdefault(plan_id, []).append((offset, arr))
            self._touched[plan_id] = time.monotonic()
            self._cond.notify_all()

    def collect(
        self, plan_id: str, count: int, timeout: float = 120.0
    ) -> Iterator[Tuple[int, object]]:
        """Destination side: yield ``count`` contributions as they arrive.

        Raises ``TimeoutError`` if the remaining contributions don't show
        up in time (a crashed seeder — the leader's failure detector will
        re-plan; the superseding plan has a fresh id).  The plan's entries
        are discarded once fully consumed; abandon via ``discard``."""
        got = 0
        deadline = time.monotonic() + timeout
        while got < count:
            with self._cond:
                while len(self._contribs.get(plan_id, ())) <= got:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise TimeoutError(
                            f"plan {plan_id}: {got}/{count} contributions "
                            f"after {timeout}s"
                        )
                    self._cond.wait(left)
                fresh = list(self._contribs[plan_id][got:])
            for item in fresh:
                yield item
                got += 1
        self.discard(plan_id)

    def discard(self, plan_id: str) -> None:
        """Drop a plan's buffered contributions (frees their device
        arrays once the consumer releases its references)."""
        with self._cond:
            self._contribs.pop(plan_id, None)
            self._touched.pop(plan_id, None)

    def gc(self, max_age: float = 600.0) -> int:
        """Drop plans idle longer than ``max_age`` seconds; returns how
        many were dropped.  Cheap enough to call opportunistically."""
        cutoff = time.monotonic() - max_age
        with self._cond:
            stale = [p for p, ts in self._touched.items() if ts < cutoff]
            for p in stale:
                self._contribs.pop(p, None)
                self._touched.pop(p, None)
        return len(stale)

    def pending(self) -> int:
        with self._cond:
            return len(self._contribs)
