"""Dissemination as XLA collectives over a device mesh.

The TPU data plane replacing the reference's TCP byte streams
(``/root/reference/distributor/transport.go``): each dissemination mode has
a collective-program equivalent compiled via ``jax.shard_map`` onto a
``jax.sharding.Mesh`` so the layer bytes ride ICI into HBM (SURVEY §5.8):

- **mode 0** (leader broadcast, node.go:326-352) → ``replicate`` /
  ``one_to_all``: a single source's HBM copy lands on every device.
- **mode 1** (peer retransmission, node.go:554-608) → ``ring_broadcast``:
  an explicit ``ppermute`` ring relay — each hop forwards the layer to its
  neighbor while later hops are still pending; the device analogue of the
  cut-through pipe relay (transport.go:144-196).
- **mode 3** (multi-sender byte-range split, flow.go:193-211) →
  ``allgather_shards``: every seeder holds a byte-range shard and one
  tiled ``all_gather`` reassembles the full layer everywhere at the full
  bisection bandwidth.

These programs are jit-compiled once per (shape, mesh) and reused per
layer; the scalar plumbing stays on host (the control plane).

``gather_tiles`` is the one the runtime ships bytes through: the fabric
dest's ingest (``ingest.ShardedLayerIngest.finalize``) and the device-
executed flow plan (``plan.execute_flow_plan``) both compile it.  The
mode-shaped programs (``ring_broadcast``/``one_to_all``/``permute_blocks``)
are schedule-parity forms kept for comparison tests and as building
blocks for topology-aware schedules.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import shard_map

# XLA's CPU backend deadlocks when two collective EXECUTIONS over
# overlapping device sets interleave: each execution's per-device worker
# threads can join the other's rendezvous (observed live on the 0.4.x
# line — "waiting for all participants to arrive at rendezvous
# RendezvousKey{run_id=861}" next to run_id=862, both wedged forever).
# Concurrent plans DO dispatch gathers concurrently (handler threads,
# the in-flight window), so on the CPU backend every gather runs
# dispatch→completion under one process-wide lock.  Accelerator
# backends keep the fully-async pipeline — ordered device streams make
# concurrent dispatch safe there.
_CPU_COLLECTIVE_LOCK = threading.Lock()


def _collective_guard():
    if jax.default_backend() == "cpu":
        return _CPU_COLLECTIVE_LOCK
    return contextlib.nullcontext()


def replicate(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Mode-0 equivalent: replicate onto every device of the mesh.  XLA
    emits the broadcast (single source → all) over ICI."""
    return jax.device_put(x, NamedSharding(mesh, P()))


def shard_along(x: jax.Array, mesh: Mesh, axis: str) -> jax.Array:
    """Split a 1-D layer into per-device byte-range shards along ``axis``
    (the device-plane form of flow.go's offset/dataSize jobs)."""
    return jax.device_put(x, NamedSharding(mesh, P(axis)))


def gather_tiles(mesh: Mesh, axis: str, sizes: Tuple[int, ...], pad=None):
    """Compiled: each device holds one PADDED tile of a byte blob (tile i
    is ``sizes[i]`` real elements); one ``all_gather`` + static re-splice
    yields the full blob replicated on every device of the mesh.

    THE terminal-hop collective of the dissemination runtime: both
    ``plan.execute_flow_plan`` (a mode-3 flow schedule executed as one
    device program) and ``ingest.ShardedLayerIngest.finalize`` (the
    receiver's incremental HBM ingest) compile through here — unequal
    flow-job splits are padded to the largest tile (or ``pad``), and the
    re-splice slices the real sizes back out.

    The identity-order case of ``gather_tiles_at`` (one shared builder,
    one compile cache)."""
    return gather_tiles_at(mesh, axis, sizes, tuple(range(len(sizes))),
                           pad=pad)


def _gather_padded(mesh: Mesh, axis: str, pad: int, k: int, dtype):
    """The cached COLLECTIVE program: every device contributes ``k``
    padded tiles; one tiled ``all_gather`` replicates all n*k tiles as a
    ``(n, k, pad)`` array on every device.  Keyed ONLY by (mesh, axis,
    pad, k, dtype) — the tile sizes are deliberately absent, so every
    plan whose pads land in the same ``plan_cache.bucket_pad`` bucket
    reuses one executable instead of compiling its own."""
    from .plan_cache import GATHER_CACHE

    n = mesh.shape[axis]
    key = (mesh, axis, n, pad, k, np.dtype(dtype).str)

    def build():
        def per_device(frag):
            return lax.all_gather(frag.reshape(k, pad), axis)  # (n, k, pad)

        fn = jax.jit(
            lambda v: shard_map(
                per_device, mesh=mesh,
                in_specs=P(axis), out_specs=P(),
                check_vma=False,
            )(v)
        )
        # Compile EAGERLY when the runtime supports it, so the build
        # time in the cache stats is the real XLA compile (and the first
        # plan's dispatch doesn't pay it inside its collective phase).
        try:
            spec = jax.ShapeDtypeStruct(
                (n * k * pad,), np.dtype(dtype),
                sharding=NamedSharding(mesh, P(axis)))
            return fn.lower(spec).compile()
        except Exception:  # noqa: BLE001 — lazy jit is still correct
            return fn

    return GATHER_CACHE.get(key, build)


def _splice_tiles(sizes: Tuple[int, ...], order: Tuple[int, ...], k: int):
    """The cached RE-SPLICE program: slice each gathered tile back to its
    real size and concatenate in offset order — device-local HBM work,
    no collective.  Keyed by the exact sizes (a static-shape program),
    but cheap to compile next to the gather."""
    from .plan_cache import SPLICE_CACHE

    def build():
        def fn(g):  # (n, k, pad) replicated
            outs = []
            for kk in range(k):
                parts = [lax.slice(g[r, kk], (0,), (sizes[r],))
                         for r in order if sizes[r] > 0]
                outs.append(jnp.concatenate(parts) if len(parts) > 1
                            else parts[0])
            return outs[0] if k == 1 else jnp.stack(outs)

        return jax.jit(fn)

    return SPLICE_CACHE.get((sizes, order, k), build)


def gather_tiles_at(mesh: Mesh, axis: str, sizes: Tuple[int, ...],
                    order: Tuple[int, ...], pad=None):
    """``gather_tiles`` with an explicit re-splice permutation: the blob's
    k-th byte range (in offset order) lives on device rank ``order[k]``.
    The multi-controller SPMD fabric needs this because contributions sit
    on their SENDER's stage devices — whichever mesh ranks those are —
    not on ranks sorted by offset.

    ``pad``: the per-tile padded element count the caller staged its
    buffers at (>= max(sizes)); defaults to max(sizes).  Callers bucket
    it (``plan_cache.bucket_pad``) so same-bucket plans share ONE
    compiled collective; the splice slices the real sizes back out."""
    pad_ = int(pad) if pad else (max(sizes) if sizes else 0)

    def run(v):
        with _collective_guard():
            g = _gather_padded(mesh, axis, pad_, 1, v.dtype)(v)
            out = _splice_tiles(tuple(sizes), tuple(order), 1)(g)
            if jax.default_backend() == "cpu":
                jax.block_until_ready(out)  # execution ends inside the lock
            return out

    return run


def gather_tiles_batched(mesh: Mesh, axis: str, sizes: Tuple[int, ...],
                         order: Tuple[int, ...], k: int, pad=None):
    """Plan batching: K same-tiling blobs move as ONE collective.

    Each device stages its K tiles back to back (``(k * pad,)`` per
    device, tile j of blob i at ``i * pad``); one ``all_gather``
    replicates all of them and the splice returns ``(k, total)`` — blob
    i is row i.  One dispatch + one executable for K layers, which is
    exactly what amortizes per-plan latency when a model's same-shape
    layers ship together."""
    if k <= 0:
        raise ValueError(f"batch size must be positive, got {k}")
    pad_ = int(pad) if pad else (max(sizes) if sizes else 0)

    def run(v):
        with _collective_guard():
            g = _gather_padded(mesh, axis, pad_, k, v.dtype)(v)
            out = _splice_tiles(tuple(sizes), tuple(order), k)(g)
            if jax.default_backend() == "cpu":
                jax.block_until_ready(out)
            return out if k > 1 else out.reshape(1, -1)

    return run


def gather_byte_shards(parts, total: int, verify_digest=None,
                       codec: str = "", decode=None):
    """Materialize a FULL layer from its byte-range shards on-mesh
    (docs/sharding.md, docs/fabric.md): each ``(shard_index, bytes)``
    part is one ``1/N@K`` floor-split slice of a ``total``-byte layer;
    the N tiles land one-per-device on an N-device mesh and ONE tiled
    ``all_gather`` (the existing ``gather_tiles`` path — padded tiles,
    static re-splice) replicates the layer, which is then read back
    byte-exact.  On a real pod the hop is ICI at bisection bandwidth —
    the wire never carried more than each dest's shard.

    ``parts``: iterable of ``(k, data)`` covering ALL of [0, N) in any
    order.  ``verify_digest``: optional stamped full-layer digest in
    the shards' WIRE form — the gathered blob is checked against it
    before being returned (the acceptance gate: post-gather bytes must
    match the pre-shard stamp; for quantized pod deliveries this is
    the leader's codec-qualified full digest).

    Codec awareness (docs/codec.md): the shards may be slices of a
    quantized wire blob — ``codec`` names the form ("" = canonical) and
    ``decode = (cfg, blob_id)`` asks for the per-blob dequant: on the
    mesh path the gathered blob is ALREADY HBM-resident, so
    ``quant.device_decode_jit(codec)`` consumes the replicated device
    array directly (no host round trip) and the call returns
    ``(wire_bytes, leaves)`` with the decoded leaves carrying the
    stager's leading length-1 axis; the host-fallback path decodes on
    host.  Without ``decode`` the return is plain wire bytes.

    The tile pad is bucketed (``plan_cache.bucket_pad``) so every
    same-bucket layer of a model reuses ONE compiled gather program —
    the pod-delivery reconstruction compiles once, not per layer.

    Falls back to a host-side concatenation — loudly, counted on
    ``shard.gather_host_fallback`` — when the runtime has fewer devices
    than shards (the gather is then still byte-exact, just not an ICI
    collective)."""
    from ..core.types import shard_range
    from ..utils import trace
    from ..utils.logging import log

    by_k = {}
    for k, data in parts:
        by_k[int(k)] = data
    n = len(by_k)
    if n == 0 or sorted(by_k) != list(range(n)):
        raise ValueError(f"shard set incomplete: have {sorted(by_k)}")
    sizes = []
    for k in range(n):
        off, size = shard_range(f"1/{n}@{k}" if n > 1 else "", total)
        if len(by_k[k]) != size:
            raise ValueError(
                f"shard {k}/{n} is {len(by_k[k])} bytes; spec says {size}")
        sizes.append(size)

    gathered_dev = None
    if n == 1:
        out = bytes(by_k[0])
    elif len(jax.devices()) < n:
        trace.count("shard.gather_host_fallback")
        log.warn("fewer devices than shards; gathering on host instead "
                 "of the mesh", shards=n, devices=len(jax.devices()))
        out = b"".join(bytes(by_k[k]) for k in range(n))
    else:
        from .plan_cache import bucket_pad

        devices = jax.devices()[:n]
        mesh = Mesh(np.array(devices), ("shards",))
        # Bucketed pad: same-bucket layers share one compiled gather
        # (plan_cache) — the splice slices the real sizes back out.
        pad = bucket_pad(max(sizes))
        staged = np.zeros((n, pad), dtype=np.uint8)
        for k in range(n):
            staged[k, : sizes[k]] = np.frombuffer(bytes(by_k[k]), np.uint8)
        v = jax.device_put(
            staged.reshape(n * pad),
            NamedSharding(mesh, P("shards")))
        gathered_dev = gather_tiles(mesh, "shards", tuple(sizes),
                                    pad=pad)(v)
        out = np.asarray(jax.device_get(gathered_dev)).tobytes()[:total]
    if len(out) != total:
        raise ValueError(f"gathered {len(out)} bytes; layer is {total}")
    if verify_digest:
        from ..utils import integrity

        if not integrity.digest_matches(out, verify_digest):
            raise ValueError("gathered layer failed the stamped "
                             "full-layer digest")
    trace.count("shard.gathered_layers")
    if decode is None:
        return out
    # Dequant AFTER the gather (and only after the digest gate above —
    # corrupt bytes must never reach the decode): the device path feeds
    # the already-replicated HBM blob straight into the codec's jit.
    # Advisory: a decode failure (bytes that aren't a model blob) costs
    # only the staged leaves — the materialized wire bytes still return.
    try:
        leaves = _decode_gathered(out, gathered_dev, total, codec, decode)
    except Exception as e:  # noqa: BLE001 — decode is an optimization
        log.warn("post-gather dequant failed; bulk staging will cover "
                 "the blob", err=repr(e))
        leaves = None
    return out, leaves


def _decode_gathered(wire: bytes, gathered_dev, total: int, codec: str,
                     decode):
    """The codec-aware tail of ``gather_byte_shards``: decode the
    gathered wire blob into staged leaves ({name: (1, *shape)} — the
    streaming stager's layout) under ``codec``, on device when the
    gather left an HBM-resident copy."""
    from ..models import quant, serde
    from ..utils import trace

    cfg, blob_id = decode
    specs = tuple(serde.head_param_specs(cfg)
                  if blob_id == serde.head_blob_id(cfg)
                  else serde.layer_param_specs(cfg))
    dt_name = np.dtype(cfg.dtype).name
    if not codec:
        codec = "raw"
    if gathered_dev is not None and codec not in quant.ENTROPY_CODECS:
        # The replicated gather output is padded past ``total``; the
        # decode jits take exact-size blobs — one device-local slice.
        # Entropy forms have no device decode program: they fall to the
        # host branch (the gather kept the host wire copy).
        blob = jax.lax.slice(gathered_dev, (0,), (total,))
        trace.count("pod.device_dequants")
        return quant.device_decode_jit(codec)((blob,), specs, dt_name)
    decoded = quant.decode_blob_host(cfg, blob_id, wire, codec)
    return {name: arr[None] for name, arr in decoded.items()}


@functools.lru_cache(maxsize=64)
def _allgather_fn(mesh: Mesh, axis: str):
    @jax.jit
    def gather(v):
        return shard_map(
            lambda s: lax.all_gather(s, axis, tiled=True),
            mesh=mesh,
            in_specs=P(axis),
            out_specs=P(),
            check_vma=False,
        )(v)

    return gather


def allgather_shards(shards: jax.Array, mesh: Mesh, axis: str) -> jax.Array:
    """Mode-3 equivalent: every device contributes its shard; the full
    layer materializes replicated on all devices in one collective."""
    return _allgather_fn(mesh, axis)(shards)


@functools.lru_cache(maxsize=64)
def _ring_broadcast_fn(mesh: Mesh, axis: str, src: int):
    n = mesh.shape[axis]
    fwd: Tuple[Tuple[int, int], ...] = tuple((i, (i + 1) % n) for i in range(n))

    def per_device(buf):
        idx = lax.axis_index(axis)
        # Hop distance from the source along the ring.
        dist = (idx - src) % n

        def step(k, b):
            recv = lax.ppermute(b, axis, fwd)
            # Devices exactly k hops downstream adopt the relayed copy;
            # earlier hops already hold it, later hops wait their turn.
            return jnp.where(dist == k, recv, b)

        return lax.fori_loop(1, n, step, buf)

    @jax.jit
    def broadcast(v):
        return shard_map(
            per_device,
            mesh=mesh,
            in_specs=P(axis),
            out_specs=P(axis),
            check_vma=False,
        )(v)

    return broadcast


def ring_broadcast(
    per_device: jax.Array, mesh: Mesh, axis: str, src: int = 0
) -> jax.Array:
    """Mode-1 equivalent: relay the source device's block around the ring
    with n-1 ``ppermute`` hops until every device holds it.

    ``per_device`` is sharded along ``axis`` (one block per device); the
    result is also sharded, with every block equal to the source's.  On a
    TPU torus each hop is a neighbor ICI transfer, so the relay pipelines
    exactly like the reference's TeeReader cut-through chain."""
    return _ring_broadcast_fn(mesh, axis, src)(per_device)


@functools.lru_cache(maxsize=64)
def _permute_fn(mesh: Mesh, axis: str, perm: Tuple[Tuple[int, int], ...]):
    @jax.jit
    def permute(v):
        return shard_map(
            lambda s: lax.ppermute(s, axis, perm),
            mesh=mesh,
            in_specs=P(axis),
            out_specs=P(axis),
            check_vma=False,
        )(v)

    return permute


def permute_blocks(
    per_device: jax.Array,
    mesh: Mesh,
    axis: str,
    perm: Sequence[Tuple[int, int]],
) -> jax.Array:
    """General leader-directed point-to-point schedule: one
    ``collective_permute`` step moving each source's block to its dest —
    the device-plane form of a batch of retransmitMsg commands
    (distributor/message.go:94-118)."""
    return _permute_fn(mesh, axis, tuple(perm))(per_device)


@functools.lru_cache(maxsize=64)
def _one_to_all_fn(mesh: Mesh, axis: str, src: int):
    @jax.jit
    def run(v):
        def per_device(s):
            idx = lax.axis_index(axis)
            contrib = jnp.where(idx == src, s, jnp.zeros_like(s))
            return lax.psum(contrib, axis)

        return shard_map(
            per_device, mesh=mesh, in_specs=P(axis), out_specs=P(),
            check_vma=False,
        )(v)

    return run


def one_to_all(
    x: jax.Array, mesh: Mesh, axis: str, src: int = 0
) -> jax.Array:
    """Mode-0 as an explicit collective: zero-mask every non-source block
    and psum — the source's block lands everywhere.  Prefer ``replicate``
    (XLA broadcast) in production; this exists for schedule parity tests."""
    return _one_to_all_fn(mesh, axis, src)(x)
