"""Multi-controller SPMD fabric: layer bytes over the device mesh when
every node is its OWN OS process (one per TPU host).

The single-controller ``FabricPlane`` (``parallel/fabric.py``) hands
device arrays between threads of one process.  On a real pod there is no
such process: each host runs its own controller (the reference's
per-host process model, ``/root/reference/cmd/main.go:113-146``), all of
them joined into one JAX runtime by ``parallel/multihost.py``.  Data can
then only move between hosts through a COLLECTIVE that every process
enters with the same program — the multi-controller discipline of
jax.distributed.

This module is that discipline applied to dissemination:

- The leader turns a scheduled transfer into a ``DevicePlanMsg`` carrying
  a global sequence number and broadcasts it to EVERY node (not just the
  participants — all processes must enter the collective).
- Each process runs one ``SpmdFabric`` executor thread that executes
  plans strictly in seq order.  For plan k, every process derives the
  SAME scope and slot assignment from the message alone: the collective
  runs on the SUB-MESH of the participating stages (the senders' stages
  ∪ the dest's stage), each layout entry landing on an unused device
  rank of its sender's stage within that scope.  A process with no
  device in the scope advances the seq WITHOUT entering any collective
  — so the layer replicates onto the participants only (a 2-stage
  transfer on a 32-stage pod pays a 2-stage gather, not a pod-wide
  one), and plans with disjoint participants genuinely overlap across
  the pod.
- Participants upload the byte ranges they own onto their own local
  devices, assemble the scoped sharded array, and enter one compiled
  gather (``collectives.gather_tiles_at``); the byte traffic rides ICI
  on real hardware.
- The plan's dest keeps its local copy (stage-replicated, exactly the
  ``-hbm`` terminal state); other participants drop theirs immediately.
- Execution is PIPELINED: the executor dispatches a plan's uploads and
  gather asynchronously and only blocks when a small in-flight window
  fills (or the queue idles), so plan k+1's host→device uploads overlap
  plan k's collective.  Per-process seq order — and therefore the
  cross-process enqueue order every pair of participants agrees on — is
  unchanged; a plan's result resolves only once its device work really
  finished, so a dest never acks bytes that could still fail.

An empty-layout plan is a CANCELLATION: the leader aborted dispatch
mid-broadcast, and every process advances past the seq without entering
a collective — a process that entered while another skipped would hang
the pod, so cancellation must be globally ordered too.

Failure domain: a process that never receives seq k stalls the fabric
(later plans queue behind it).  That is inherent to lockstep SPMD — the
control plane (ordered, retried TCP) is the reliability layer, and the
executor logs loudly when a gap persists past ``gap_timeout``.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..utils.logging import log

PLAN_WAIT_S = 120.0  # dest-side wait for its plan's collective
# Dispatched-but-unretired plans (bounds device memory).  The window is
# BYTE-budgeted: many small plans pipeline deep (their cost is per-plan
# dispatch latency, which the window amortizes), while multi-GiB plans
# keep only as many gathers in flight as the budget allows — one rule
# instead of a small/large mode switch.  Window depth is a LOCAL pacing
# choice: it never changes the per-process enqueue order, so processes
# with different depths still interoperate.
MAX_INFLIGHT = 16            # hard cap on dispatched-but-unretired plans
MAX_INFLIGHT_SMALL = MAX_INFLIGHT  # retained alias (older tests/docs)
INFLIGHT_BYTE_BUDGET = int(os.environ.get(
    "DLD_INFLIGHT_BYTE_BUDGET", 1 << 30))


class PlanFailed(RuntimeError):
    pass


class _Result:
    """One plan's outcome: a device array (dest), None (cancelled /
    not-dest), or an exception."""

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None

    def resolve(self, value=None, error: Optional[BaseException] = None):
        self.value = value
        self.error = error
        self.event.set()

    def get(self, timeout: float):
        if not self.event.wait(timeout):
            raise PlanFailed(f"no collective result after {timeout}s")
        if self.error is not None:
            raise PlanFailed(str(self.error)) from self.error
        return self.value


class SpmdFabric:
    """Per-process executor of globally-ordered fabric plans.

    ``placement`` must cover every node (``parallel.mesh.fabric_placement``)
    and be identical on all processes (host-aligned device order makes it
    so).  ``bind_store(layers, lock)`` is called by the node constructor:
    the executor reads ONLY this node's own byte ranges through it."""

    kind = "spmd"

    def __init__(self, placement, my_node: int, gap_timeout: float = 60.0):
        stages = list(placement.node_to_stage.values())
        if len(set(stages)) != len(stages):
            # Two nodes (= two processes) sharing a stage means some
            # node's byte ranges would sit on another process's devices:
            # that process can't fill them, and the owner would raise
            # mid-lockstep while peers hang in the collective.  Refuse
            # deterministically at startup on EVERY process instead.
            raise ValueError(
                "spmd fabric needs one stage per node (one stage == one "
                f"host); got node_to_stage={placement.node_to_stage} — "
                "size the mesh pipeline axis to the node count"
            )
        self.placement = placement
        self.my_node = my_node
        self.gap_timeout = gap_timeout
        # Stall-recovery hook (set by the owning node): called with the
        # ascending list of MISSING seqs each time the executor sits a
        # full gap_timeout on a hole — the node reports them to the
        # leader (PlanResendReqMsg) so the lockstep self-heals instead
        # of relying on a human reading "stalled" logs.  Rate-limited
        # naturally: one call per gap_timeout window.
        self.on_gap = None
        self._layers = None
        self._layers_lock: Optional[threading.Lock] = None
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: Dict[int, object] = {}  # seq -> DevicePlanMsg
        self._results: Dict[str, _Result] = {}  # plan_id -> result
        self._next_seq = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="spmd-fabric", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- wiring

    def bind_store(self, layers, lock: threading.Lock) -> None:
        self._layers = layers
        self._layers_lock = lock

    def _read_span(self, layer_id: int, off: int, size: int) -> Optional[bytes]:
        if self._layers is None:
            return None
        with self._layers_lock:
            layer = self._layers.get(layer_id)
        if layer is None:
            return None
        return layer.read_span(off, size)

    # ------------------------------------------------------------ protocol

    def submit(self, msg) -> _Result:
        """Enqueue one plan (any role); returns its result handle.  The
        dest waits on it; everyone else may drop it.

        A CANCELLATION (empty layout) for a still-pending seq replaces the
        original: the leader cancels when its broadcast partially failed,
        and a process that kept the original would enter a collective some
        peer never will.  (A plan already being executed can no longer be
        cancelled — that residual window is part of the pod failure
        domain, see the module docstring.)"""
        with self._cond:
            if self._closed:
                raise PlanFailed("fabric closed")
            res = self._results.get(msg.plan_id)
            if res is None:
                res = self._results[msg.plan_id] = _Result()
            if msg.seq < self._next_seq:
                return res  # already executed (or executing)
            if msg.seq in self._pending:
                if not msg.layout:
                    self._pending[msg.seq] = msg  # cancel overrides
                return res
            self._pending[msg.seq] = msg
            self._cond.notify_all()
        return res

    def wait_result(self, res: _Result, base_timeout: float = PLAN_WAIT_S):
        """Dest-side wait that tolerates a deep queue: a fixed wall clock
        would spuriously fail a late-seq plan during a healthy large
        startup (k earlier plans each pay compile + upload + a pod-wide
        collective).  The timeout only counts windows WITHOUT progress —
        as long as the executor keeps retiring seqs, keep waiting."""
        while True:
            with self._lock:
                seen = self._next_seq
            try:
                return res.get(base_timeout)
            except PlanFailed:
                with self._lock:
                    progressed = self._next_seq > seen
                if not progressed:
                    raise

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)

    # ------------------------------------------------------------ executor

    def _retire_oldest(self, inflight) -> None:
        """Block until the oldest dispatched plan's device work finished,
        then resolve its result — success and failure both surface HERE,
        so a dest only ever acks bytes that really landed."""
        import time as _time

        import jax

        from ..utils import trace

        plan_id, res, value, out, _sz, t0 = inflight.popleft()
        try:
            jax.block_until_ready(out)
        except Exception as e:  # noqa: BLE001 — resolve, don't die
            log.error("spmd fabric plan failed", plan=plan_id, err=repr(e))
            res.resolve(error=e)
            return
        trace.add_phase("collective", _time.monotonic() - t0)
        res.resolve(value=value)

    def _run(self) -> None:
        # (plan_id, result, dest value, gathered array, bytes)
        # dispatched but not yet known-finished.  The deque IS the
        # pipeline: dispatch runs ahead of completion by up to the
        # size-aware window (MAX_INFLIGHT, or MAX_INFLIGHT_SMALL when
        # everything in flight is small).
        inflight = collections.deque()
        while True:
            with self._cond:
                waited = self._cond.wait_for(
                    lambda: self._closed or self._next_seq in self._pending,
                    # With work in flight, don't sleep the whole gap:
                    # retire it while the queue is idle.
                    timeout=0.02 if inflight else self.gap_timeout,
                )
                if self._closed:
                    for res in self._results.values():
                        if not res.event.is_set():
                            res.resolve(error=PlanFailed("fabric closed"))
                    return
                msg = None
                stalled_on = sorted(self._pending) if self._pending else []
                if waited:
                    msg = self._pending.pop(self._next_seq)
                    self._next_seq += 1
                    # Kept (resolved) in _results so late duplicate
                    # deliveries get the settled handle instead of a
                    # dangling fresh one; the map grows by one small entry
                    # per plan per run.
                    res = self._results[msg.plan_id]
            if msg is None:
                if inflight:
                    self._retire_oldest(inflight)
                elif stalled_on:
                    # Later seqs queued behind a gap: the pod-wide
                    # lockstep is stalled.  Make it loud AND ask the
                    # control plane to heal it (on_gap → the leader
                    # re-sends its retained plan, or cancels the seq).
                    missing = sorted(
                        set(range(self._next_seq, max(stalled_on) + 1))
                        - set(stalled_on)
                    )
                    log.error(
                        "spmd fabric stalled waiting for plan seq",
                        next_seq=self._next_seq,
                        queued=stalled_on,
                        missing=missing,
                    )
                    hook = self.on_gap
                    if hook is not None and missing:
                        try:
                            hook(missing)
                        except Exception as e:  # noqa: BLE001 — advisory
                            log.error("on_gap hook failed", err=repr(e))
                continue
            try:
                value, out = self._execute(msg)
            except Exception as e:  # noqa: BLE001 — resolve, don't die
                log.error("spmd fabric plan failed", plan=msg.plan_id,
                          err=repr(e))
                res.resolve(error=e)
                continue
            if out is None:  # cancelled / not a participant: no device work
                res.resolve(value=value)
                continue
            import time as _time

            inflight.append((msg.plan_id, res, value, out, msg.total_size,
                             _time.monotonic()))
            # Byte-budgeted window: retire the oldest until the in-flight
            # set fits the budget (always keeping at least one dispatched
            # plan — a single over-budget plan still pipelines with the
            # next one's host staging) and the hard count cap.
            while (len(inflight) > MAX_INFLIGHT
                   or (sum(e[4] for e in inflight) > INFLIGHT_BYTE_BUDGET
                       and len(inflight) > 1)):
                self._retire_oldest(inflight)

    # ----------------------------------------------------------- collective

    def _plan_scope(self, msg) -> list:
        """The sub-mesh of a plan: the participating stages' devices (the
        senders' stages ∪ the dest's stage) in stage order — identical on
        every process (the placement is).  The collective runs on exactly
        these devices; everyone else sits the plan out."""
        stages = sorted(
            {self.placement.node_to_stage[s] for s, _, _ in msg.layout}
            | {self.placement.node_to_stage[msg.dest_id]}
        )
        return [d for st in stages for d in self.placement.stage_devices(st)]

    def _slot_assignment(
        self, layout: List[Tuple[int, int, int]], flat: list
    ) -> Tuple[Tuple[int, ...], Tuple[int, ...], Dict[int, Tuple[int, int, int]]]:
        """Deterministic (message-only) mapping of layout entries to device
        ranks WITHIN the plan's scope: each contribution lands on an unused
        device of its sender's stage.  Returns (sizes by rank, ranks in
        offset order, rank -> (sender, offset, size))."""
        rank_of = {id(d): i for i, d in enumerate(flat)}
        used: set = set()
        by_rank: Dict[int, Tuple[int, int, int]] = {}
        order: List[int] = []
        for sender, off, size in sorted(layout, key=lambda e: e[1]):
            stage_ranks = [rank_of[id(d)]
                           for d in self.placement.devices_for_node(sender)]
            free = [r for r in stage_ranks if r not in used]
            if not free:
                raise PlanFailed(
                    f"sender {sender} has more ranges than stage devices"
                )
            r = free[0]
            used.add(r)
            by_rank[r] = (sender, off, size)
            order.append(r)
        sizes = tuple(
            by_rank[r][2] if r in by_rank else 0 for r in range(len(flat))
        )
        return sizes, tuple(order), by_rank

    def _execute(self, msg):
        """Dispatch one plan's uploads + gather.  Returns (dest value,
        gathered array) — the array is a live device-work handle the
        caller retires later — or (None, None) when there is nothing to
        enter (cancellation, or this process is outside the scope)."""
        if not msg.layout:
            log.info("spmd fabric plan cancelled", plan=msg.plan_id)
            return None, None
        import jax
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .collectives import gather_tiles_at
        from .ingest import flat_mesh

        flat = self._plan_scope(msg)
        proc = jax.process_index()
        if not any(d.process_index == proc for d in flat):
            # Out of scope: the participants' collective doesn't involve
            # this process's devices; just advance the seq.
            return None, None
        from .plan_cache import bucket_pad

        sizes, order, by_rank = self._slot_assignment(msg.layout, flat)
        total = sum(sizes)
        if total != msg.total_size:
            raise PlanFailed(
                f"layout covers {total} bytes, plan says {msg.total_size}"
            )
        # Bucketed tile pad: plans with near-equal splits reuse ONE
        # compiled gather (plan_cache) instead of compiling per layer.
        pad = bucket_pad(max(sizes))
        mesh = flat_mesh(flat, axis="fabric")

        # My ranges MUST sit on my local devices (one stage == one host
        # under the host-aligned order) — otherwise this process would
        # silently contribute zeros.  Checked before any device work so
        # the failure is loud, not corrupt.
        for rank, (sender, _, _) in by_rank.items():
            if sender == self.my_node and flat[rank].process_index != proc:
                raise PlanFailed(
                    f"my range's slot (rank {rank}) is not a local device; "
                    "placement is not host-aligned"
                )

        from ..utils import trace

        shards = []
        with trace.phase("upload"):
            for rank, dev in enumerate(flat):
                if dev.process_index != proc:
                    continue
                buf = np.zeros(pad, np.uint8)
                entry = by_rank.get(rank)
                if entry is not None and entry[0] == self.my_node:
                    _, off, size = entry
                    data = self._read_span(msg.layer_id, off, size)
                    if data is None:
                        raise PlanFailed(
                            f"no local bytes for layer {msg.layer_id}"
                        )
                    buf[:size] = np.frombuffer(data, np.uint8)
                shards.append(jax.device_put(buf, dev))

        v = jax.make_array_from_single_device_arrays(
            (len(flat) * pad,), NamedSharding(mesh, P("fabric")), shards
        )
        # NOT blocked here: the caller's in-flight window retires it, so
        # the next plan's uploads overlap this gather on the device queue.
        out = gather_tiles_at(mesh, "fabric", sizes, order, pad=pad)(v)
        # Pod-delivery reconstruction (docs/fabric.md): every node in the
        # advisory keep-list retains the gathered layer, not just the
        # nominal dest — one collective materializes the full tree on
        # ALL pod members.
        keepers = {msg.dest_id} | {int(n) for n in (msg.pod or ())}
        if self.my_node not in keepers:
            return None, out
        # Keep the LOCAL copy: the gather leaves the full layer replicated
        # on every scope device; this node's addressable shards are its
        # stage's devices (host-aligned order) — re-wrap them as a local
        # stage-replicated array, the -hbm terminal state.
        local_shards = [s.data for s in out.addressable_shards]
        stage = self.placement.node_to_stage[self.my_node]
        stage_mesh = self.placement.stage_mesh(stage)
        try:
            arr = jax.make_array_from_single_device_arrays(
                out.shape, NamedSharding(stage_mesh, P()), local_shards
            )
        except Exception:  # noqa: BLE001 — single-device copy still correct
            arr = local_shards[0]
        return arr, out
