"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context support is first-class in this framework: the sequence dim is
sharded over the ``sp`` mesh axis and attention runs blockwise — each
device keeps its Q shard resident while K/V blocks rotate around the ring
via ``lax.ppermute``, accumulating with an online (flash-style) softmax.
Communication of the next K/V block overlaps the current block's matmuls on
TPU (XLA schedules the ppermute DMA concurrently), so attention over an
S-long sequence costs S/sp memory per chip and n-1 neighbor hops.

This is new capability relative to the reference (which has no compute at
all, SURVEY §2.3); the pattern follows the public ring-attention /
blockwise-attention literature (see PAPERS.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_NEG_INF = -1e30


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    s_local: int,
) -> jax.Array:
    """Causal GQA ring attention inside a manual (shard_map) context.

    q: [b, s_local, h, hd] — this device's query block (heads may be
    tp-sharded; grouping is h//kv locally).
    k, v: [b, s_local, kv, hd] — this device's key/value block, already
    position-encoded with *global* positions.
    Returns [b, s_local, h, hd].
    """
    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    b, sq, h, hd = q.shape
    kvh = k.shape[2]
    group = h // kvh
    qg = q.reshape(b, sq, kvh, group, hd)
    q_pos = my * s_local + jnp.arange(s_local)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        k_blk, v_blk, m, l, o = carry
        # The block in hand originated at device (my - i) mod n.
        src = (my - i) % n
        k_pos = src * s_local + jnp.arange(s_local)
        logits = jnp.einsum(
            "bskgh,btkh->bkgst", qg, k_blk, preferred_element_type=jnp.float32
        ) / np.sqrt(hd)
        causal = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(causal, logits, _NEG_INF)

        m_new = jnp.maximum(m, logits.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        pv = jnp.einsum("bkgst,btkh->bkgsh", p.astype(v_blk.dtype), v_blk)
        o_new = o * alpha[..., None].astype(o.dtype) + pv

        # Skip the final rotation: after the last accumulation the blocks
        # are discarded, so that hop would be a wasted ICI transfer.
        k_nxt, v_nxt = lax.cond(
            i < n - 1,
            lambda kv: (
                lax.ppermute(kv[0], axis, perm),
                lax.ppermute(kv[1], axis, perm),
            ),
            lambda kv: kv,
            (k_blk, v_blk),
        )
        return (k_nxt, v_nxt, m_new, l_new, o_new), None

    m0 = jnp.full((b, kvh, group, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, kvh, group, sq), jnp.float32)
    o0 = jnp.zeros((b, kvh, group, sq, hd), v.dtype)
    (k_f, v_f, m_f, l_f, o_f), _ = lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(n)
    )
    out = o_f / jnp.maximum(l_f, 1e-30)[..., None].astype(o_f.dtype)
    # [b, kv, g, s, hd] -> [b, s, h, hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, h, hd)
