"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context support is first-class in this framework: the sequence dim is
sharded over the ``sp`` mesh axis and attention runs blockwise — each
device keeps its Q shard resident while K/V blocks rotate around the ring
via ``lax.ppermute``, accumulating with an online (flash-style) softmax.
Communication of the next K/V block overlaps the current block's matmuls on
TPU (XLA schedules the ppermute DMA concurrently), so attention over an
S-long sequence costs S/sp memory per chip and n-1 neighbor hops.

The per-block compute is ``ops.flash_attention.block_attention`` — a
pallas TPU kernel when shapes are MXU-tileable (logits never leave VMEM),
the lax oracle otherwise — and the ring loop merges each block's partial
softmax stats with ``merge_partials``.  The K/V carry is kept in
[b, kvh, t, hd] layout so the kernel consumes it without per-hop
transposes; ``ppermute`` is layout-oblivious.

This is new capability relative to the reference (which has no compute at
all, SURVEY §2.3); the pattern follows the public ring-attention /
blockwise-attention literature (see PAPERS.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.flash_attention import _NEG_INF, block_attention, merge_partials


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    s_local: int,
) -> jax.Array:
    """Causal GQA ring attention inside a manual (shard_map) context.

    q: [b, s_local, h, hd] — this device's query block (heads may be
    tp-sharded; grouping is h//kv locally).
    k, v: [b, s_local, kv, hd] — this device's key/value block, already
    position-encoded with *global* positions.
    Returns [b, s_local, h, hd].
    """
    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    b, sq, h, hd = q.shape
    kvh = k.shape[2]
    group = h // kvh
    # [b, s, kvh, g|1, hd] -> kernel layouts (loop-invariant, done once).
    qg = q.reshape(b, sq, kvh, group, hd).transpose(0, 2, 3, 1, 4)
    kt = k.transpose(0, 2, 1, 3)  # [b, kvh, t, hd] — the ring carry layout
    vt = v.transpose(0, 2, 1, 3)
    q_off = (my * s_local).astype(jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        k_blk, v_blk, o, m, l = carry
        # The block in hand originated at device (my - i) mod n.
        src = (my - i) % n
        part = block_attention(
            qg, k_blk, v_blk, q_off, (src * s_local).astype(jnp.float32)
        )
        o, m, l = merge_partials((o, m, l), part)

        # Skip the final rotation: after the last accumulation the blocks
        # are discarded, so that hop would be a wasted ICI transfer.
        k_nxt, v_nxt = lax.cond(
            i < n - 1,
            lambda kv: (
                lax.ppermute(kv[0], axis, perm),
                lax.ppermute(kv[1], axis, perm),
            ),
            lambda kv: kv,
            (k_blk, v_blk),
        )
        return (k_nxt, v_nxt, o, m, l), None

    m0 = jnp.full((b, kvh, group, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, kvh, group, sq), jnp.float32)
    o0 = jnp.zeros((b, kvh, group, sq, hd), jnp.float32)
    if hasattr(lax, "pcast"):
        # The accumulators become device-varying after the first merge
        # (the K/V carry is varying); the scan carry must start that way.
        m0, l0, o0 = (
            lax.pcast(x, (axis,), to="varying") for x in (m0, l0, o0)
        )
    (_, _, o_f, m_f, l_f), _ = lax.scan(
        step, (kt, vt, o0, m0, l0), jnp.arange(n)
    )
    out = (o_f / jnp.maximum(l_f, 1e-30)[..., None]).astype(v.dtype)
    # [b, kv, g, s, hd] -> [b, s, h, hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, h, hd)
