"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context support is first-class in this framework: the sequence dim is
sharded over the ``sp`` mesh axis and attention runs blockwise — each
device keeps its Q shard resident while K/V blocks rotate around the ring
via ``lax.ppermute``, accumulating with an online (flash-style) softmax.
Communication of the next K/V block overlaps the current block's matmuls on
TPU (XLA schedules the ppermute DMA concurrently), so attention over an
S-long sequence costs S/sp memory per chip and n-1 neighbor hops.

The per-block compute is ``ops.flash_attention.block_attention`` — a
pallas TPU kernel when shapes are MXU-tileable (logits never leave VMEM),
the lax oracle otherwise — and the ring loop merges each block's partial
softmax stats with ``merge_partials``.  The K/V carry is kept in
[b, kvh, t, hd] layout so the kernel consumes it without per-hop
transposes; ``ppermute`` is layout-oblivious.

Training memory matches the ring-attention paper's budget because the
op defines its own backward: autodiff of the forward scan would stack
every rotated K/V block as a residual (n copies = the full sequence per
chip), so instead the custom vjp saves only the device's own shard
(q, k, v, out, lse) and RE-ROTATES K/V in the backward ring, with dK/dV
accumulators traveling alongside and arriving home after n hops.

This is new capability relative to the reference (which has no compute at
all, SURVEY §2.3); the pattern follows the public ring-attention /
blockwise-attention literature (see PAPERS.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.flash_attention import _NEG_INF, block_attention, merge_partials
from .compat import axis_size


def _vary(axis, *xs):
    """Mark freshly-created accumulators as device-varying over ``axis``
    (needed whenever the surrounding shard_map checks vma)."""
    if hasattr(lax, "pcast"):
        return tuple(lax.pcast(x, (axis,), to="varying") for x in xs)
    return xs


def _ring_forward(q, k, v, axis, s_local):
    """The forward ring; returns out plus the per-row log-sum-exp and the
    kernel-layout tensors the custom backward needs."""
    n = axis_size(axis)
    my = lax.axis_index(axis)
    b, sq, h, hd = q.shape
    kvh = k.shape[2]
    group = h // kvh
    # [b, s, kvh, g|1, hd] -> kernel layouts (loop-invariant, done once).
    qg = q.reshape(b, sq, kvh, group, hd).transpose(0, 2, 3, 1, 4)
    kt = k.transpose(0, 2, 1, 3)  # [b, kvh, t, hd] — the ring carry layout
    vt = v.transpose(0, 2, 1, 3)
    q_off = (my * s_local).astype(jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        k_blk, v_blk, o, m, l = carry
        # The block in hand originated at device (my - i) mod n.
        src = (my - i) % n

        def visible(oml):
            part = block_attention(
                qg, k_blk, v_blk, q_off,
                (src * s_local).astype(jnp.float32),
            )
            return merge_partials(oml, part)

        # A block strictly in the future (src > my) is fully masked and
        # contributes nothing: skip its compute entirely — half the
        # per-step work on a causal ring (the hop still happens; the
        # ring's schedule is fixed).
        o, m, l = lax.cond(src <= my, visible, lambda oml: oml, (o, m, l))

        # Skip the final rotation: after the last accumulation the blocks
        # are discarded, so that hop would be a wasted ICI transfer.
        k_nxt, v_nxt = lax.cond(
            i < n - 1,
            lambda kv: (
                lax.ppermute(kv[0], axis, perm),
                lax.ppermute(kv[1], axis, perm),
            ),
            lambda kv: kv,
            (k_blk, v_blk),
        )
        return (k_nxt, v_nxt, o, m, l), None

    m0 = jnp.full((b, kvh, group, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, kvh, group, sq), jnp.float32)
    o0 = jnp.zeros((b, kvh, group, sq, hd), jnp.float32)
    m0, l0, o0 = _vary(axis, m0, l0, o0)
    (_, _, o_f, m_f, l_f), _ = lax.scan(
        step, (kt, vt, o0, m0, l0), jnp.arange(n)
    )
    l_safe = jnp.maximum(l_f, 1e-30)
    out_g = o_f / l_safe[..., None]  # normalized, f32, kernel layout
    lse = m_f + jnp.log(l_safe)  # per-row log-sum-exp
    out = out_g.astype(v.dtype)
    # [b, kv, g, s, hd] -> [b, s, h, hd]
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, sq, h, hd)
    return out, (qg, kt, vt, out_g, lse)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    s_local: int,
) -> jax.Array:
    """Causal GQA ring attention inside a manual (shard_map) context.

    q: [b, s_local, h, hd] — this device's query block (heads may be
    tp-sharded; grouping is h//kv locally).
    k, v: [b, s_local, kv, hd] — this device's key/value block, already
    position-encoded with *global* positions.
    Returns [b, s_local, h, hd].

    Differentiation runs a RING BACKWARD (``defvjp`` below): K/V blocks
    are re-rotated around the ``axis`` ring while dK/dV accumulators
    travel with them, arriving home after n hops.  Residual memory is
    the device's own O(S/sp) shard (q, k, v, out, lse) — NOT the n
    stacked K/V copies that autodiff of the forward scan would save,
    which is what makes long-context training fit the ring-attention
    memory budget.
    """
    out, _ = _ring_forward(q, k, v, axis, s_local)
    return out


def _ring_attention_fwd(q, k, v, axis, s_local):
    out, res = _ring_forward(q, k, v, axis, s_local)
    return out, res


def _ring_attention_bwd(axis, s_local, res, dout):
    """Flash-style ring backward: p = exp(s - lse) is recomputed per
    block; dK/dV ride the rotating carry and return home after n hops."""
    qg, kt, vt, out_g, lse = res
    n = axis_size(axis)
    my = lax.axis_index(axis)
    b, kvh, group, sq, hd = qg.shape
    scale = 1.0 / np.sqrt(hd)
    h = kvh * group

    # Caller layout [b, s, h, hd] -> kernel layout, f32.
    dg = (
        dout.reshape(b, sq, kvh, group, hd)
        .transpose(0, 2, 3, 1, 4)
        .astype(jnp.float32)
    )
    # D_i = sum_d dout_i * out_i (the softmax-normalizer gradient term).
    d_row = jnp.einsum("bkgsh,bkgsh->bkgs", dg, out_g)

    q_ids = my * s_local + jnp.arange(sq)
    qg32 = qg.astype(jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        k_blk, v_blk, dk_blk, dv_blk, dq = carry
        src = (my - i) % n  # same visiting order as the forward

        def visible(args):
            k_blk, v_blk, dk_blk, dv_blk, dq = args
            k32 = k_blk.astype(jnp.float32)
            v32 = v_blk.astype(jnp.float32)
            s = jnp.einsum(
                "bkgsh,bkth->bkgst", qg32, k32,
                preferred_element_type=jnp.float32,
            ) * scale
            k_ids = src * s_local + jnp.arange(s_local)
            causal = q_ids[:, None] >= k_ids[None, :]
            s = jnp.where(causal, s, _NEG_INF)
            p = jnp.exp(s - lse[..., None])  # masked entries underflow to 0

            dv_blk = dv_blk + jnp.einsum("bkgst,bkgsh->bkth", p, dg)
            dp = jnp.einsum("bkgsh,bkth->bkgst", dg, v32)
            ds = p * (dp - d_row[..., None]) * scale
            dq = dq + jnp.einsum("bkgst,bkth->bkgsh", ds, k32)
            dk_blk = dk_blk + jnp.einsum("bkgst,bkgsh->bkth", ds, qg32)
            return dk_blk, dv_blk, dq

        # A block strictly in the future (src > my) has p == 0 for every
        # row: skip its five einsums — half the backward FLOPs on a
        # causal ring, the same skip the forward kernel does per tile.
        dk_blk, dv_blk, dq = lax.cond(
            src <= my,
            visible,
            lambda args: (args[2], args[3], args[4]),
            (k_blk, v_blk, dk_blk, dv_blk, dq),
        )

        # dK/dV need all n rotations to arrive home; K/V are dead after
        # the last accumulation, so skip their final hop (same wasted-
        # transfer elision as the forward).
        k_blk, v_blk = lax.cond(
            i < n - 1,
            lambda kv: (
                lax.ppermute(kv[0], axis, perm),
                lax.ppermute(kv[1], axis, perm),
            ),
            lambda kv: kv,
            (k_blk, v_blk),
        )
        dk_blk, dv_blk = (
            lax.ppermute(x, axis, perm) for x in (dk_blk, dv_blk)
        )
        return (k_blk, v_blk, dk_blk, dv_blk, dq), None

    zeros_kv = jnp.zeros((b, kvh, s_local, hd), jnp.float32)
    dq0 = jnp.zeros((b, kvh, group, sq, hd), jnp.float32)
    dk0, dv0, dq0 = _vary(axis, zeros_kv, zeros_kv, dq0)
    (_, _, dk_f, dv_f, dq_f), _ = lax.scan(
        step, (kt, vt, dk0, dv0, dq0), jnp.arange(n)
    )

    dq = (
        dq_f.transpose(0, 3, 1, 2, 4)
        .reshape(b, sq, h, hd)
        .astype(qg.dtype)
    )
    dk = dk_f.transpose(0, 2, 1, 3).astype(kt.dtype)
    dv = dv_f.transpose(0, 2, 1, 3).astype(vt.dtype)
    return dq, dk, dv


ring_attention.defvjp(_ring_attention_fwd, _ring_attention_bwd)
