from . import plan_cache  # noqa: F401
from .collectives import (  # noqa: F401
    allgather_shards,
    gather_tiles,
    gather_tiles_batched,
    one_to_all,
    permute_blocks,
    replicate,
    ring_broadcast,
    shard_along,
)
from .fabric import FabricPlane, PlanWindow  # noqa: F401
from .mesh import (  # noqa: F401
    StagePlacement,
    assignment_to_placement,
    fabric_placement,
    make_mesh,
    mesh_from_conf,
)
from .mover import (  # noqa: F401
    StageResult,
    WeightMover,
    array_to_bytes,
    bytes_to_array,
)
from .multihost import (  # noqa: F401
    ProcessLayout,
    derive_layout,
    maybe_initialize,
)
