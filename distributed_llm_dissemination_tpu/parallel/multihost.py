"""Multi-host mesh formation: jax.distributed wiring from the topology.

The reference scales by one OS process per host, wired by the JSON config
(``/root/reference/cmd/main.go:113-146``) — each process only ever talks
TCP.  A TPU pod needs one more layer: every per-host process must join ONE
JAX runtime (``jax.distributed.initialize``) so ``jax.devices()`` spans the
pod and a configured Mesh can place stages across hosts.  This module
derives that wiring from the same JSON topology (node list order → process
rank, leader's host → coordinator), so multi-host runs need no extra
flags — the config that describes the cluster also forms the mesh.

Single-host runs (no ``Distributed`` section) are a clean no-op.  On CPU
backends cross-process collectives need gloo; the init flips
``jax_cpu_collectives_implementation`` automatically so the 2-process CPU
smoke deployment (tests/test_multihost.py) and a real TPU pod share one
code path.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..core.config import Config
from ..core.types import NodeID
from ..utils.logging import log

# JAX's own default coordinator port, reused when the config names none.
DEFAULT_COORDINATOR_PORT = 8476


def honor_jax_platforms() -> None:
    """Make the JAX_PLATFORMS env var effective even where a site hook
    (e.g. a TPU plugin's sitecustomize) imported jax at interpreter start:
    the backend itself initializes on first use, so flipping the config
    before that still wins.  No-op when the backend is already live.

    ENTRY POINTS ONLY (cli.main / podrun): it re-applies whatever the
    environment says, so calling it from library code would clobber an
    embedder's explicit ``jax.config.update("jax_platforms", ...)`` with
    the ambient launch environment's value."""
    import os

    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return  # before importing jax: pure-TCP nodes stay jax-free
    import jax

    try:
        jax.config.update("jax_platforms", want)
    except RuntimeError:
        pass  # backend already initialized; leave as-is


@dataclasses.dataclass
class ProcessLayout:
    """One node-process's place in the pod-wide JAX runtime."""

    coordinator: str
    num_processes: int
    process_id: int


def derive_layout(conf: Config, my_id: NodeID) -> ProcessLayout:
    """Map this node to a jax.distributed process rank.

    Rank = the node's position in the id-sorted node list (stable across
    hosts: every process derives the same order from the same config).
    Coordinator = the configured ``Distributed.Coordinator``, else the
    leader node's host on JAX's default coordinator port — the leader host
    is already the one address every node must reach."""
    ids = sorted(nc.id for nc in conf.nodes)
    if my_id not in ids:
        raise ValueError(f"node {my_id} not in config nodes {ids}")
    coordinator = ""
    if conf.distributed is not None:
        coordinator = conf.distributed.coordinator
    if not coordinator:
        from ..core.config import get_leader_conf

        leader_addr = get_leader_conf(conf).addr
        host = leader_addr.rsplit(":", 1)[0] if ":" in leader_addr else leader_addr
        coordinator = f"{host or '127.0.0.1'}:{DEFAULT_COORDINATOR_PORT}"
    return ProcessLayout(
        coordinator=coordinator,
        num_processes=len(ids),
        process_id=ids.index(my_id),
    )


def host_aligned_device_order(conf: Config, assignment) -> list:
    """Global device list reordered so pipeline-stage blocks follow node
    locality: stage i's devices are the ones owned by the process that
    runs the node mapped to stage i.

    On a multi-host mesh, ``jax.devices()`` comes back in process order —
    but stage order is semantic (contiguous layers on consecutive stages,
    ``mesh.ranked_assignees``), and node id ↔ process rank follows the
    id-sorted node list (``derive_layout``).  A mesh built over the raw
    device order would hand node N a stage whose devices live on some
    other host, and every ``device_put`` of a delivered layer would fail.
    Feeding THIS order to ``make_mesh`` makes each node's stage locally
    addressable, so ``-hbm`` works across hosts.

    Works for any pipeline-axis position: the order returned is the
    row-major flattening of a device array whose index s along the
    pipeline axis is exactly process-rank-of-stage-s's device block — so
    ``make_mesh``'s plain reshape reproduces the alignment.  Requires one
    pipeline stage's device count to equal one process's (stage ↔ host,
    the TPU-VM shape); single-process runs return the plain device
    list."""
    import jax

    if jax.process_count() <= 1 or conf.mesh is None:
        return list(jax.devices())
    import numpy as np

    from .mesh import ranked_assignees

    shape = tuple(conf.mesh.axis_sizes)
    names = list(conf.mesh.axis_names)
    k = names.index(conf.mesh.pipeline_axis)
    n_stages = shape[k]
    per_stage = int(np.prod(shape)) // n_stages

    ids = sorted(nc.id for nc in conf.nodes)
    by_proc: dict = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, []).append(d)
    counts = {rank: len(devs) for rank, devs in by_proc.items()}
    if len(set(counts.values())) != 1:
        raise ValueError(
            f"uneven devices per process {counts}: host-aligned stages "
            "need a uniform TPU-VM shape"
        )
    per_proc = next(iter(counts.values()))
    if per_stage != per_proc:
        raise ValueError(
            f"one pipeline stage spans {per_stage} devices but each "
            f"process owns {per_proc}: shape the mesh so one stage == "
            f"one host (mesh {dict(zip(names, shape))}, "
            f"{len(by_proc)} processes)"
        )
    staged = ranked_assignees(assignment)
    stage_nodes = staged + [n for n in ids if n not in set(staged)]
    if n_stages > len(stage_nodes):
        raise ValueError(
            f"mesh has {n_stages} pipeline stages but only "
            f"{len(stage_nodes)} configured nodes to own them"
        )
    blocks = [by_proc[ids.index(node_id)] for node_id in stage_nodes[:n_stages]]
    rest_shape = shape[:k] + shape[k + 1 :]
    arr = np.empty((n_stages, per_stage), dtype=object)
    for s, block in enumerate(blocks):
        arr[s] = block
    arr = np.moveaxis(arr.reshape((n_stages,) + rest_shape), 0, k)
    return list(arr.reshape(-1))


def maybe_initialize(conf: Config, my_id: NodeID) -> Optional[ProcessLayout]:
    """Join the pod-wide JAX runtime when the config asks for one.

    Returns the layout when ``jax.distributed`` was initialized, ``None``
    for the single-host fallback (no ``Distributed`` section, or a
    single-node topology).  Must run before the first JAX backend use in
    the process — the CLI calls it right after parsing the config."""
    if conf.distributed is None or len(conf.nodes) < 2:
        return None
    layout = derive_layout(conf, my_id)
    import jax

    if conf.distributed.cpu_collectives:
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              conf.distributed.cpu_collectives)
        except (ValueError, RuntimeError) as e:
            log.warn("couldn't set cpu collectives", err=repr(e))
    log.info("joining pod-wide jax runtime",
             coordinator=layout.coordinator,
             process_id=layout.process_id,
             num_processes=layout.num_processes)
    jax.distributed.initialize(
        coordinator_address=layout.coordinator,
        num_processes=layout.num_processes,
        process_id=layout.process_id,
    )
    log.info("pod-wide jax runtime up",
             local_devices=len(jax.local_devices()),
             global_devices=len(jax.devices()))
    return layout


def maybe_shutdown() -> None:
    """Leave the pod-wide JAX runtime in an orderly way at process exit.

    ``jax.distributed.initialize`` starts C++ service/heartbeat threads
    that interpreter teardown destroys while still joinable — an
    occasional ``std::terminate`` (SIGABRT) on an otherwise-successful
    run.  Shutting the client down first joins them.  No-op when the
    runtime was never initialized; peer-already-gone errors are expected
    at exit (the other end of a finished run may close first) and only
    logged."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return
    try:
        client = jax._src.distributed.global_state.client
    except AttributeError:
        client = None
    if client is None:
        return
    try:
        jax.distributed.shutdown()
        log.info("pod-wide jax runtime shut down")
    except Exception as e:  # noqa: BLE001 — exit path must not raise
        log.warn("pod-wide jax runtime shutdown failed", err=repr(e))
