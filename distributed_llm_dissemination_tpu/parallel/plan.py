"""Flow schedules as compiled device collective programs.

The bridge between the host scheduler and the TPU data plane: a mode-3
plan is a set of per-sender byte-range jobs (``sched.flow.FlowJob``,
reference flow.go:193-211); on a device mesh the same plan becomes ONE
XLA collective — every seeder device contributes exactly its planned
byte range and a tiled ``all_gather`` materializes the full layer on all
devices over ICI.  This is the SPMD resolution of the reference's
asymmetric event-driven protocol (SURVEY §7 "hard parts"): the leader
computes the plan on the host control plane, then all participants enter
the same compiled program.

Unequal ranges are handled by padding each contribution to the plan's
largest range; the re-splice back to the contiguous layer happens
on-device with static slice bounds (sizes are compile-time constants of
the program), so XLA fuses it with the gather epilogue.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..sched.flow import FlowJob
from .collectives import gather_tiles, gather_tiles_batched
from .plan_cache import bucket_pad


def plan_layout(jobs: Sequence[FlowJob]) -> List[Tuple[int, int, int]]:
    """Validate + order one layer's jobs into a device layout.

    Returns ``[(sender_id, offset, size), ...]`` sorted by offset: device
    rank i on the mesh axis carries sender ``layout[i][0]``'s byte range,
    which is how a caller maps ``fragment_bytes[i]`` to the seeder that
    holds those bytes.  Raises if the ranges don't tile a contiguous
    ``[0, total)`` — a malformed plan must fail loudly before any device
    work is launched."""
    spans = sorted((j.offset, j.data_size, j.sender_id) for j in jobs)
    layout = []
    pos = 0
    for off, size, sender_id in spans:
        if off != pos:
            raise ValueError(
                f"plan does not tile: expected offset {pos}, got {off}"
            )
        layout.append((sender_id, off, size))
        pos += size
    return layout


def execute_flow_plan(
    jobs: Sequence[FlowJob],
    fragment_bytes: Sequence[bytes],
    mesh: Mesh,
    axis: str,
    dtype=jnp.uint8,
) -> jax.Array:
    """Run one layer's flow plan as a single device collective.

    ``fragment_bytes[i]`` is the byte range of the i-th job in
    ``plan_layout`` order (what that seeder would have sent over TCP).
    The number of jobs must not exceed the mesh axis size; idle devices
    contribute zero-size ranges.  Returns the full layer, replicated on
    every device of the mesh, as a 1-D array of ``dtype``."""
    layout = plan_layout(jobs)
    n = mesh.shape[axis]
    if len(layout) > n:
        raise ValueError(f"{len(layout)} fragments > {n} devices on '{axis}'")
    itemsize = np.dtype(dtype).itemsize
    sizes = [size // itemsize for _, _, size in layout]
    if any(size % itemsize for _, _, size in layout):
        raise ValueError(f"fragment sizes must be multiples of {itemsize}")
    sizes += [0] * (n - len(layout))  # idle devices
    # Bucketed pad (plan_cache.bucket_pad): near-equal layers land on the
    # same compiled gather instead of each paying its own XLA compile.
    pad = bucket_pad(max(sizes)) if n > 1 else max(sizes)

    devices = mesh.devices.reshape(-1)
    shards = []
    for rank in range(n):
        buf = np.zeros(pad, dtype=dtype)
        if rank < len(layout):
            frag = np.frombuffer(fragment_bytes[rank], dtype=dtype)
            buf[: sizes[rank]] = frag
        shards.append(jax.device_put(buf, devices[rank]))
    global_shape = (n * pad,)
    v = jax.make_array_from_single_device_arrays(
        global_shape, NamedSharding(mesh, P(axis)), shards
    )
    return gather_tiles(mesh, axis, tuple(sizes), pad=pad)(v)


def execute_flow_plans(
    plans: Sequence[Tuple[Sequence[FlowJob], Sequence[bytes]]],
    mesh: Mesh,
    axis: str,
    dtype=jnp.uint8,
) -> List[jax.Array]:
    """Plan batching: K same-tiling flow plans as ONE device collective.

    ``plans`` is ``[(jobs, fragment_bytes), ...]``; every plan must tile
    the same total with the same per-job split (a model's equal-size
    layers under one schedule — the common mode-3 case).  Each device
    stages its K tiles back to back and a single batched gather
    replicates all K layers everywhere: one dispatch and one compiled
    executable amortized over the whole batch, instead of K serial
    collectives.  Returns one replicated layer per plan, in order."""
    if not plans:
        return []
    if len(plans) == 1:
        jobs, frags = plans[0]
        return [execute_flow_plan(jobs, frags, mesh, axis, dtype=dtype)]
    layouts = [plan_layout(jobs) for jobs, _ in plans]
    shape0 = [(off, size) for _, off, size in layouts[0]]
    for lay in layouts[1:]:
        if [(off, size) for _, off, size in lay] != shape0:
            raise ValueError("batched plans must share one tiling shape")
    n = mesh.shape[axis]
    if len(shape0) > n:
        raise ValueError(f"{len(shape0)} fragments > {n} devices on '{axis}'")
    itemsize = np.dtype(dtype).itemsize
    if any(size % itemsize for _, size in shape0):
        raise ValueError(f"fragment sizes must be multiples of {itemsize}")
    sizes = [size // itemsize for _, size in shape0]
    sizes += [0] * (n - len(shape0))
    k = len(plans)
    pad = bucket_pad(max(sizes)) if n > 1 else max(sizes)

    devices = mesh.devices.reshape(-1)
    shards = []
    for rank in range(n):
        buf = np.zeros(k * pad, dtype=dtype)
        if rank < len(shape0):
            for i, (_, frags) in enumerate(plans):
                frag = np.frombuffer(frags[rank], dtype=dtype)
                buf[i * pad : i * pad + sizes[rank]] = frag
        shards.append(jax.device_put(buf, devices[rank]))
    v = jax.make_array_from_single_device_arrays(
        (n * k * pad,), NamedSharding(mesh, P(axis)), shards
    )
    out = gather_tiles_batched(
        mesh, axis, tuple(sizes), tuple(range(n)), k, pad=pad
    )(v)
    return [out[i] for i in range(k)]
