"""Device-mesh construction and Assignment → pipeline-stage placement.

The TPU-native heart of the framework: the reference's ``Assignment``
(node → layers, ``/root/reference/distributor/node.go:174``) is exactly a
pipeline-parallel *stage placement* map — which node hosts which contiguous
layers for staged inference (SURVEY §2.3).  Here a node corresponds to a
slice of a ``jax.sharding.Mesh`` along the pipeline axis, and dissemination
lands each layer in the HBM of its stage's devices.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.config import MeshConf
from ..core.types import Assignment, LayerID, NodeID


def make_mesh(
    axis_sizes: Sequence[int],
    axis_names: Sequence[str],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over the available devices (row-major fill)."""
    devices = list(devices if devices is not None else jax.devices())
    need = int(np.prod(axis_sizes))
    if len(devices) < need:
        raise ValueError(
            f"mesh {tuple(axis_sizes)} needs {need} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[:need], dtype=object).reshape(tuple(axis_sizes))
    return Mesh(arr, tuple(axis_names))


def mesh_from_conf(conf: MeshConf, devices=None) -> Mesh:
    return make_mesh(conf.axis_sizes, conf.axis_names, devices)


@dataclasses.dataclass
class StagePlacement:
    """Node → (pipeline-stage index, devices) mapping derived from an
    Assignment.

    Nodes are ranked by their minimum assigned layer id so contiguous layer
    ranges land on consecutive stages — the staged-inference layout the
    reference's startup hook presumes (distributor/message.go:216-241).
    """

    mesh: Mesh
    pipeline_axis: str
    node_to_stage: Dict[NodeID, int]
    layer_to_stage: Dict[LayerID, int]
    # stage → sub-Mesh cache: staging an 80-layer model calls layer_sharding
    # per layer but there are only pp-axis-size distinct stage meshes.
    _stage_meshes: Dict[int, Mesh] = dataclasses.field(
        default_factory=dict, repr=False, compare=False
    )

    @property
    def num_stages(self) -> int:
        return self.mesh.shape[self.pipeline_axis]

    def stage_devices(self, stage: int) -> List[jax.Device]:
        axis = list(self.mesh.axis_names).index(self.pipeline_axis)
        take = np.take(self.mesh.devices, stage, axis=axis)
        return list(np.ravel(take))

    def devices_for_node(self, node_id: NodeID) -> List[jax.Device]:
        return self.stage_devices(self.node_to_stage[node_id])

    def devices_for_layer(self, layer_id: LayerID) -> List[jax.Device]:
        return self.stage_devices(self.layer_to_stage[layer_id])

    def stage_mesh(self, stage: int) -> Mesh:
        """Sub-mesh of one pipeline stage: the full mesh with the pipeline
        axis sliced away, keeping every other axis (tp/dp/...)."""
        cached = self._stage_meshes.get(stage)
        if cached is not None:
            return cached
        axis = list(self.mesh.axis_names).index(self.pipeline_axis)
        devs = np.take(self.mesh.devices, stage, axis=axis)
        names = tuple(n for n in self.mesh.axis_names if n != self.pipeline_axis)
        if not names:  # 1-axis mesh: np.take returned a bare Device scalar
            devs = np.asarray([devs], dtype=object)
            names = (self.pipeline_axis,)
        self._stage_meshes[stage] = Mesh(devs, names)
        return self._stage_meshes[stage]

    def layer_sharding(self, layer_id: LayerID, spec: P = P()) -> NamedSharding:
        """Sharding that lands a layer on *its stage's* devices only
        (default: replicated within the stage, absent everywhere else) —
        the HBM footprint the Assignment prescribes."""
        return NamedSharding(self.stage_mesh(self.layer_to_stage[layer_id]), spec)


def ranked_assignees(assignment: Assignment) -> List[NodeID]:
    """Assignees in pipeline-stage order: ranked by each node's minimum
    assigned layer id, so contiguous layer ranges land on consecutive
    stages — the staged-inference layout the reference's startup hook
    presumes (distributor/message.go:216-241)."""
    ranked: List[Tuple[int, NodeID]] = sorted(
        (min(layers) if layers else 0, node_id)
        for node_id, layers in assignment.items()
    )
    return [node_id for _, node_id in ranked]


def assignment_to_placement(
    assignment: Assignment, mesh: Mesh, pipeline_axis: str = "nodes"
) -> StagePlacement:
    """Map each assigned node to a pipeline stage of the mesh.

    Requires len(assignment) <= mesh.shape[pipeline_axis].  Stage order
    follows each node's minimum assigned layer, so Assignment
    {7: [0..7]} or a contiguous {1: [0-19], 2: [20-39], ...} both produce
    the natural stage order.
    """
    n_stages = mesh.shape[pipeline_axis]
    if len(assignment) > n_stages:
        raise ValueError(
            f"assignment has {len(assignment)} nodes but mesh axis "
            f"'{pipeline_axis}' has only {n_stages} stages"
        )
    node_to_stage = {
        node_id: stage
        for stage, node_id in enumerate(ranked_assignees(assignment))
    }
    layer_to_stage = {
        layer_id: node_to_stage[node_id]
        for node_id, layers in assignment.items()
        for layer_id in layers
    }
    return StagePlacement(
        mesh=mesh,
        pipeline_axis=pipeline_axis,
        node_to_stage=node_to_stage,
        layer_to_stage=layer_to_stage,
    )


def fabric_placement(
    node_ids,
    assignment: Assignment,
    mesh: Mesh,
    pipeline_axis: str = "nodes",
) -> StagePlacement:
    """Placement covering EVERY fabric participant, not just assignees.

    On a pod fabric (``parallel/fabric.py``) a seeder needs local stage
    devices too: its planned byte range enters the fabric through its own
    host→HBM link.  Assignees keep the assignment-derived stage order
    (contiguous layer ranges on consecutive stages, as
    ``assignment_to_placement``); the remaining nodes — seeders, the
    leader — fill the leftover stages in id order.  With more extra nodes
    than free stages they share stages round-robin: harmless under a
    single controller, but a multi-host deployment should size the mesh's
    pipeline axis to the cluster (stage ↔ host)."""
    placement = assignment_to_placement(assignment, mesh, pipeline_axis)
    extras = sorted(set(node_ids) - set(placement.node_to_stage))
    if not extras:
        return placement
    taken = set(placement.node_to_stage.values())
    free = [s for s in range(placement.num_stages) if s not in taken]
    slots = free or list(range(placement.num_stages))
    if len(extras) > len(free):
        import warnings

        warnings.warn(
            f"{len(extras)} non-assignee fabric nodes share "
            f"{len(slots)} stages; size the mesh pipeline axis to the "
            f"cluster for multi-host runs", stacklevel=2,
        )
    for i, node_id in enumerate(extras):
        placement.node_to_stage[node_id] = slots[i % len(slots)]
    return placement
