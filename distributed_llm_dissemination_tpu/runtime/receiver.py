"""Receiver state machines.

Re-design of the reference's receivers
(``/root/reference/distributor/node.go:1291-1589``):

- ``ReceiverNode`` (mode 0): announce initial layers to the leader, store
  received layers in RAM, ack, unblock ``ready()`` on startup.
- ``RetransmitReceiverNode`` (modes 1/2): additionally serves
  ``RetransmitMsg`` — forwards its copy of a layer to a named destination;
  client-held layers are piped cut-through from the external client.
- ``FlowRetransmitReceiverNode`` (mode 3): handles partial-layer commands
  and **really reassembles** byte ranges into one buffer at the right
  offsets — the reference only sums sizes and never copies the bytes
  (node.go:1545-1547), a measurement-harness shortcut this framework fixes.

Deviation: ``announce()`` includes each layer's ``SourceType`` so the
mode-3 flow graph can model per-source-class capacity; the reference drops
it on announce (node.go:1392-1403) which collapses all announced layers
into one source class.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Tuple

from ..core.types import (
    LayerIDs,
    LayerLocation,
    LayerMeta,
    LayerSrc,
    LayersSrc,
)
from ..transport.messages import (
    AckMsg,
    AnnounceMsg,
    FlowRetransmitMsg,
    LayerMsg,
    RetransmitMsg,
    StartupMsg,
)
from ..utils import intervals
from ..utils.buffers import alloc_recv_buffer
from ..utils.logging import log
from .checkpoint import LayerCheckpointStore
from .failure import HeartbeatSender
from .node import MessageLoop, Node
from .send import fetch_from_client, handle_flow_retransmit, send_layer


class ReceiverNode:
    """Mode 0 receiver (node.go:1299-1418).

    ``heartbeat_interval`` > 0 starts a liveness beacon to the leader on
    the first ``announce()`` — the receiver half of the failure detection
    the reference leaves TODO (node.go:218-220)."""

    def __init__(
        self,
        node: Node,
        layers: LayersSrc,
        storage_path: str = ".",
        start_loop: bool = True,
        heartbeat_interval: float = 0.0,
        stage_hbm: bool = False,
    ):
        """``stage_hbm``: stage each delivered layer into device HBM (a
        jax.Array) before acking — the TPU-native terminal state; the
        reference stops at host RAM (node.go:435-446)."""
        self.node = node
        self.layers = layers
        self.storage_path = storage_path
        self.stage_hbm = stage_hbm
        # Eager when enabled: handlers run on a 16-worker pool, so a lazy
        # check-then-set would race; raw byte blobs stage as uint8 so
        # odd-length layers round-trip exactly (bf16 would pad a byte).
        self._mover = None
        if stage_hbm:
            import numpy as _np

            from ..parallel.mover import WeightMover
            self._mover = WeightMover(dtype=_np.uint8)
        self._ready_q: "queue.Queue[object]" = queue.Queue()
        self._lock = threading.Lock()
        self.heartbeat = HeartbeatSender(
            node.transport, node.my_id, node.leader_id, heartbeat_interval
        )
        self.loop = MessageLoop(node.transport)
        self._register_handlers()
        if start_loop:
            self.loop.start()

    def _register_handlers(self) -> None:
        self.loop.register(LayerMsg, self.handle_layer)
        self.loop.register(StartupMsg, self.handle_startup)

    def announce(self) -> None:
        """Tell the leader what I already hold, routed via the next hop
        (node.go:1392-1415)."""
        with self._lock:
            layer_ids: LayerIDs = {
                lid: LayerMeta(
                    location=src.meta.location,
                    limit_rate=src.meta.limit_rate,
                    source_type=src.meta.source_type,
                    data_size=src.data_size,
                )
                for lid, src in self.layers.items()
            }
        next_hop = self.node.get_next_hop(self.node.leader_id)
        self.node.transport.send(
            next_hop,
            AnnounceMsg(self.node.my_id, layer_ids,
                        partial=self._announce_partial()),
        )
        self.heartbeat.start()

    def _announce_partial(self) -> dict:
        """Checkpointed in-progress coverage to include in the announce;
        the base receiver has none."""
        return {}

    def ready(self) -> "queue.Queue[object]":
        return self._ready_q

    def close(self) -> None:
        self.heartbeat.stop()
        self.loop.stop()

    def _stage_to_hbm(self, layer_id, src) -> "LayerLocation":
        """Move a completed layer host→HBM when enabled; returns the
        location to ack with.  jax is imported lazily so host-only nodes
        never pay for it."""
        if not self.stage_hbm:
            return LayerLocation.INMEM
        if src.meta.location == LayerLocation.HBM:
            return LayerLocation.HBM  # a re-plan duplicate: already staged
        try:
            self._mover.stage(src)
            log.info("layer staged to HBM", layerID=layer_id)
            return LayerLocation.HBM
        except Exception as e:  # noqa: BLE001 — delivery beats staging
            log.error("HBM staging failed; acking host RAM",
                      layerID=layer_id, err=repr(e))
            return LayerLocation.INMEM

    def handle_layer(self, msg: LayerMsg) -> None:
        """Store to RAM, ack the leader (node.go:1354-1384)."""
        with self._lock:
            src = msg.layer_src
            src.meta = LayerMeta(location=LayerLocation.INMEM)
            src.offset = 0
            self.layers[msg.layer_id] = src
        log.debug("saved layer in memory", layerID=msg.layer_id)
        loc = self._stage_to_hbm(msg.layer_id, src)
        try:
            self.node.transport.send(
                self.node.leader_id,
                AckMsg(self.node.my_id, msg.layer_id, loc),
            )
        except (OSError, KeyError) as e:
            log.error("failed to send ackMsg", err=repr(e))

    def handle_startup(self, msg: StartupMsg) -> None:
        """The inference-engine boot hook (node.go:1387-1389)."""
        self._ready_q.put(object())


class RetransmitReceiverNode(ReceiverNode):
    """Modes 1/2 receiver: can forward its layers on command
    (node.go:1421-1484)."""

    def _register_handlers(self) -> None:
        super()._register_handlers()
        self.loop.register(RetransmitMsg, self.handle_retransmit)

    def handle_retransmit(self, msg: RetransmitMsg) -> None:
        with self._lock:
            layer = self.layers.get(msg.layer_id)
        if layer is None:
            log.error("retransmit of unknown layer", layerID=msg.layer_id)
            return
        self.node.add_node(msg.dest_id)
        if layer.meta.location == LayerLocation.CLIENT:
            log.debug("loading layer from client", layer=msg.layer_id)
            fetch_from_client(self.node, msg.layer_id, msg.dest_id)
            return
        try:
            send_layer(self.node, msg.dest_id, msg.layer_id, layer)
        except (OSError, KeyError) as e:
            log.error("failed to send layer", dest=msg.dest_id, err=repr(e))


class FlowRetransmitReceiverNode(RetransmitReceiverNode):
    """Mode 3 receiver: partial-layer reassembly + flow-job execution
    (node.go:1487-1589)."""

    def __init__(self, node: Node, layers: LayersSrc, storage_path: str = ".",
                 start_loop: bool = True, heartbeat_interval: float = 0.0,
                 checkpoint_dir: str = "", stage_hbm: bool = False):
        """``checkpoint_dir``: when set, every fragment is journaled there
        and partial layers survive a process restart (resume support —
        absent in the reference, whose partial accounting dies with the
        process, node.go:1542-1554)."""
        # layer -> (reassembly buffer, disjoint covered [start, end) ranges)
        self._partial: Dict[int, Tuple[bytearray, list]] = {}
        self._partial_total: Dict[int, int] = {}
        self.ckpt = LayerCheckpointStore(checkpoint_dir) if checkpoint_dir else None
        if self.ckpt is not None:
            for lid, (buf, covered, total) in self.ckpt.load().items():
                if intervals.covered(covered) >= total:
                    # Crashed between assembly and journal cleanup: done.
                    layers[lid] = LayerSrc(
                        inmem_data=buf, data_size=total,
                        meta=LayerMeta(location=LayerLocation.INMEM),
                    )
                    self.ckpt.complete(lid)
                else:
                    self._partial[lid] = (buf, covered)
                    self._partial_total[lid] = total
        super().__init__(node, layers, storage_path, start_loop=start_loop,
                         heartbeat_interval=heartbeat_interval,
                         stage_hbm=stage_hbm)

    def _announce_partial(self) -> dict:
        with self._lock:
            return {
                lid: {
                    "Total": self._partial_total[lid],
                    "Covered": [list(iv) for iv in covered],
                }
                for lid, (_, covered) in self._partial.items()
                if lid in self._partial_total
            }

    def _register_handlers(self) -> None:
        super()._register_handlers()
        self.loop.register(FlowRetransmitMsg, self.handle_flow_retransmit)

    def handle_layer(self, msg: LayerMsg) -> None:
        """Write the fragment at its offset; ack when the layer is whole
        (node.go:1520-1567, with the real byte copy the reference skips).

        Coverage is tracked as an interval union, not a byte counter (the
        reference sums sizes, node.go:1542-1554) — so duplicate or
        overlapping fragments from a crash-triggered re-plan can never ack
        a layer full of holes."""
        with self._lock:
            if msg.layer_id in self.layers:
                # A re-plan duplicate of a finished layer: drop the bytes
                # but re-ack below — the re-send happened precisely because
                # the leader never saw our ack.
                complete = True
            else:
                entry = self._partial.get(msg.layer_id)
                if entry is None:
                    # Allocate lazily (an eager dict.get default would
                    # build a full layer-sized buffer on *every* fragment)
                    # and unzeroed (zero-fill would hold the GIL for
                    # hundreds of ms at real layer sizes; coverage is
                    # tracked by intervals, so unwritten bytes are never
                    # exposed).
                    entry = (alloc_recv_buffer(msg.total_size), [])
                buf, covered = entry
                frag = msg.layer_src
                data = frag.read_bytes()
                # memoryview: the one right-hand side both ndarray buffers
                # (which reject raw bytes) and checkpoint-restored
                # bytearrays (which reject ndarrays) accept.
                buf[frag.offset : frag.offset + frag.data_size] = memoryview(data)
                covered = intervals.insert(
                    covered, frag.offset, frag.offset + frag.data_size
                )
                self._partial[msg.layer_id] = (buf, covered)
                self._partial_total[msg.layer_id] = msg.total_size
                if self.ckpt is not None:
                    self.ckpt.write_fragment(
                        msg.layer_id, frag.offset, data, covered, msg.total_size
                    )
                received = intervals.covered(covered)
                log.info(
                    "layer fragment stored",
                    layerID=msg.layer_id, received=received, total=msg.total_size,
                )
                complete = received >= msg.total_size
                if complete:
                    self.layers[msg.layer_id] = LayerSrc(
                        inmem_data=buf,
                        data_size=msg.total_size,
                        meta=LayerMeta(location=LayerLocation.INMEM),
                    )
                    del self._partial[msg.layer_id]
                    self._partial_total.pop(msg.layer_id, None)
                    if self.ckpt is not None:
                        self.ckpt.complete(msg.layer_id)
                    log.info("layer fully received", layer=msg.layer_id,
                             total_bytes=msg.total_size)
        if not complete:
            return
        loc = self._stage_to_hbm(msg.layer_id, self.layers[msg.layer_id])
        try:
            self.node.transport.send(
                self.node.leader_id,
                AckMsg(self.node.my_id, msg.layer_id, loc),
            )
        except (OSError, KeyError) as e:
            log.error("failed to send ackMsg", err=repr(e))

    def handle_flow_retransmit(self, msg: FlowRetransmitMsg) -> None:
        import time as _time

        t0 = _time.monotonic()
        log.info(
            "start sending layer",
            layer=msg.layer_id, dest=msg.dest_id, size=msg.data_size, rate=msg.rate,
        )
        handle_flow_retransmit(
            self.node, self.layers, self._lock,
            lambda lid, dest: fetch_from_client(self.node, lid, dest), msg,
        )
        dur = _time.monotonic() - t0
        log.info(
            "finished sending layer",
            layer=msg.layer_id, dest=msg.dest_id,
            send_dur_ms=round(dur * 1000, 3),
            throughput_mibps=round(msg.data_size / max(dur, 1e-9) / (1 << 20), 2),
        )
