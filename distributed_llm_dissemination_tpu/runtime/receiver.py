"""Receiver state machines.

Re-design of the reference's receivers
(``/root/reference/distributor/node.go:1291-1589``):

- ``ReceiverNode`` (mode 0): announce initial layers to the leader, store
  received layers in RAM, ack, unblock ``ready()`` on startup.
- ``RetransmitReceiverNode`` (modes 1/2): additionally serves
  ``RetransmitMsg`` — forwards its copy of a layer to a named destination;
  client-held layers are piped cut-through from the external client.
- ``FlowRetransmitReceiverNode`` (mode 3): handles partial-layer commands
  and **really reassembles** byte ranges into one buffer at the right
  offsets — the reference only sums sizes and never copies the bytes
  (node.go:1545-1547), a measurement-harness shortcut this framework fixes.

Deviation: ``announce()`` includes each layer's ``SourceType`` so the
mode-3 flow graph can model per-source-class capacity; the reference drops
it on announce (node.go:1392-1403) which collapses all announced layers
into one source class.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time as _time
from typing import Dict, Tuple

from ..core.types import (
    LayerIDs,
    LayerLocation,
    LayerMeta,
    LayerSrc,
    LayersSrc,
    delta_base_digest,
    shard_covers,
    shard_range,
)
from ..transport.messages import (
    AckMsg,
    AnnounceMsg,
    BootHintMsg,
    BootReadyMsg,
    DevicePlanMsg,
    DrainMsg,
    FlowRetransmitMsg,
    GenerateReqMsg,
    GenerateRespMsg,
    GroupPlanMsg,
    JobRevokeMsg,
    JoinMsg,
    LayerDigestsMsg,
    LayerMsg,
    LayerNackMsg,
    LeaderLeaseMsg,
    MetricsReportMsg,
    PlanResendReqMsg,
    RetransmitMsg,
    ServeMsg,
    SourceDeadMsg,
    StartupMsg,
    SwapCommitMsg,
    TimeSyncMsg,
)
from ..utils import (
    env as env_util,
    hostmem,
    integrity,
    intervals,
    telemetry,
    threads as threads_util,
    trace,
)
from ..utils.buffers import alloc_recv_buffer
from ..utils.logging import log
from .checkpoint import LayerCheckpointStore
from .failure import HeartbeatSender
from .node import MessageLoop, Node
from .store import ContentStore
from .swap import SwapController
from .send import (
    NackRetransmitter,
    RevokeRegistry,
    contribute_device_plan,
    fetch_from_client,
    handle_flow_retransmit,
    release_upload_cache,
    reopen_upload_cache,
    send_layer,
)

# Max hinted held-sets a receiver keeps warm at once: hints are
# unauthenticated, and each warmup is a seconds-long XLA compile
# thread — a well-behaved run re-targets a handful of times.  Kept as
# an insertion-ordered window: when a new distinct set arrives at the
# cap, the OLDEST hinted set is evicted (superseded re-targets must
# not consume the budget forever — a long-lived receiver crossing
# many update()s still warms its newest target).
_PRECOMPILE_MAX_SETS = 4

# Integrity-plane bounds (docs/integrity.md).  NACKs per corrupt byte
# range: a persistently corrupt path must go quiet and fail loudly, not
# livelock the wire with retransmit requests.  Digest retries per layer:
# each mismatch re-opens the layer's intervals and re-announces (a full
# re-fetch), so a corrupt SOURCE is caught after a few rounds.
_NACK_MAX_PER_RANGE = 8
_DIGEST_MAX_RETRIES = 3
# Quiet-gap watchdog cadence (seconds; DLD_GAP_NACK_S overrides, 0
# disables): a partial mode-3 layer whose coverage sat unchanged — and
# claim-idle — across a full interval has lost frames SILENTLY (eaten
# retransmit, reset mid-flight, vanished sender): re-NACK its gaps to
# the last-seen sender so recovery never depends on one NACK round-trip
# surviving the same faulty path that ate the data.  Re-NACKs ride the
# same per-range budget as first NACKs, so a dead path still goes quiet.
_GAP_NACK_DEFAULT_S = 3.0
# Per-layer cap on journal CRC records (each journaled fragment appends
# one, and every meta write re-serializes the whole list): 1024 covers a
# 16 GiB layer at the 16 MiB fragment floor.  Past it the layer's CRC
# records are dropped (legacy journal — resume trusts the fsync
# ordering, loudly) rather than letting a tiny-fragment flood make the
# meta write O(n^2).
_JOURNAL_CRC_MAX_RECORDS = 1024



class ReceiverNode:
    """Mode 0 receiver (node.go:1299-1418).

    ``heartbeat_interval`` > 0 starts a liveness beacon to the leader on
    the first ``announce()`` — the receiver half of the failure detection
    the reference leaves TODO (node.go:218-220)."""

    # How long a fabric dest waits for a plan's contributions before
    # requesting a re-plan (class attribute: tests and deployments tune it).
    FABRIC_COLLECT_TIMEOUT = 120.0

    # How long a dest holds an incomplete plan batch before processing
    # the members that did arrive (a participant's dispatch failed and
    # its plan went host-path).
    FABRIC_BATCH_WAIT = 10.0

    # Serve-time request bounds (class attributes: deployments tune
    # them).  GenerateReqMsg is as unauthenticated as BootHintMsg, and
    # each request allocates a KV cache proportional to prompt+max_new
    # AND compiles one decode program per distinct (prompt_len,
    # max_new) shape — so both dimensions are hard-capped, mirroring
    # the _PRECOMPILE_MAX_SETS budget on the other unauthenticated
    # control path.  SERVE_MAX_CONCURRENT bounds simultaneous decodes
    # (each runs on its own daemon thread so the one-slot handler pool
    # stays free for control traffic); excess requests get an
    # immediate "busy" refusal — every outcome answers.
    SERVE_MAX_PROMPT = 4096
    SERVE_MAX_NEW = 1024
    SERVE_MAX_CONCURRENT = 2

    def __init__(
        self,
        node: Node,
        layers: LayersSrc,
        storage_path: str = ".",
        start_loop: bool = True,
        heartbeat_interval: float = 0.0,
        stage_hbm: bool = False,
        placement=None,
        boot_cfg=None,
        fabric=None,
        boot_codec: str = "raw",
        boot_generate: int = 0,
        codecs=None,
    ):
        """``boot_cfg``: a ``models.llama.ModelConfig``; when set, the
        startup message boots the model from the delivered layer blobs
        (``runtime.boot``) and reports a ``BootReadyMsg`` to the leader —
        the inference engine the reference's startup hook only gestures at
        (message.go:216-241).  ``boot_codec``: the transfer codec the
        blobs were encoded with (``models/quant.py``); ``boot_generate``
        > 0 additionally decodes that many tokens after a full boot (the
        KV-cached serving loop).

        ``stage_hbm``: stage each delivered layer into device HBM (a
        jax.Array) before acking — the TPU-native terminal state; the
        reference stops at host RAM (node.go:435-446).

        ``placement``: a ``parallel.mesh.StagePlacement`` (derived from the
        Assignment + the config's Mesh section).  With it, a delivered
        layer lands replicated on *its pipeline stage's* devices via the
        sharded-ingest path (1/n host→device traffic per device + one ICI
        all-gather) instead of on the default device — the staged-inference
        layout the reference's startup hook presumes
        (distributor/message.go:216-241).

        ``fabric``: a ``parallel.fabric.FabricPlane`` shared with the
        leader and the other nodes of the pod.  The node then serves
        ``DevicePlanMsg`` commands: it publishes its planned byte ranges
        onto its own stage devices (seeder half) and ingests plans
        addressed to it over the device fabric (dest half) — layer bytes
        never touch the transport (the reference's per-transfer TCP byte
        stream, transport.go:267-274, replaced by ICI).

        ``codecs``: the node's ``runtime.codec.WireCodecPlane``
        (docs/codec.md).  With it, this node ANNOUNCES its wire-codec
        decode capability (the leader may then choose quantized
        transfers for it over slow links), serves encoded byte ranges
        as a SENDER (flow jobs, NACK retransmits, mode-1/2 forwards),
        and accounts decoded-vs-wire bytes for the run report."""
        self.node = node
        self.layers = layers
        self.storage_path = storage_path
        self.stage_hbm = stage_hbm
        self.placement = placement
        self.boot_cfg = boot_cfg
        self.boot_codec = boot_codec
        self.boot_generate = boot_generate
        self.fabric = fabric
        self.boot_result = None  # BootResult after a successful boot
        self._boot_started = False
        self._boot_finished = threading.Event()  # set after _boot (any outcome)
        # Set when the _boot TASK has fully drained (report sent, any
        # -gen decode done) — the CLI must not exit the process while a
        # boot runs on this daemon pool (it would die silently and the
        # leader's boot wait would hang on the missing report).
        self._boot_drained = threading.Event()
        # (seconds, kind) of the boot outcome, for re-answering a
        # re-sent startup when the first BootReadyMsg was lost.
        self._boot_report = None
        # One hint-time warmup per DISTINCT held-set: repeat hints for
        # the same set (re-announce, update) are no-ops, while an
        # update() that changes this node's held-set shape warms the new
        # program too.  Hard-capped (_PRECOMPILE_MAX_SETS): hints are
        # unauthenticated control messages, and unbounded distinct sets
        # would mean unbounded concurrent XLA compile threads.
        # _precompile_done is set exactly when NO warmup is in flight
        # (an in-flight counter, not a per-thread pulse).
        # Insertion-ordered (dict keys): newest-N window, oldest evicted.
        self._precompiled_sets: dict = {}
        self._precompile_inflight = 0
        self._serve_active = 0
        self._precompile_done = threading.Event()
        self._precompile_done.set()
        # Startup marker for overlap accounting: precompiles and streamed
        # stagings that finish before this fires ran DURING the wire.
        self._startup_seen = threading.Event()
        # Integrity plane (docs/integrity.md): expected per-layer
        # self-describing
        # digests stamped by the leader (LayerDigestsMsg), this node's
        # own announced digests (cached — a re-announce must not
        # re-hash gigabytes), layers whose digest already verified (a
        # re-ack must not re-hash either), per-layer digest retry
        # counts, per-range NACK budgets, and the retransmit service
        # for NACKs this node receives as a SENDER.
        self.layer_digests: Dict[int, str] = {}
        # Sharded targets (docs/sharding.md): leader-stamped shard spec
        # per assigned layer (the interval set is complete — and acks —
        # at SHARD coverage), and the per-RANGE digest a shard verifies
        # against without ever holding the full layer.
        self._shard_specs: Dict[int, str] = {}
        self._range_digests: Dict[int, str] = {}
        # Fabric-assisted pod delivery (docs/fabric.md): leader-stamped
        # pod width per layer — this dest's shard target is one slice
        # of an n-way POD split; after the per-range digest gate the
        # shard feeds the on-mesh reconstruction and the FULL tree acks
        # once it verifies against the stamped full wire-form digest.
        self._pod_widths: Dict[int, int] = {}
        self._pod_collecting: set = set()
        self._pod_stager_obj = None
        # Versioned rollout targets (docs/swap.md): leader-stamped
        # version per assigned layer — stored holdings and acks carry
        # the tag, so a v2 delivery can never be mistaken for (or
        # clobbered by) an unversioned copy under the same id.
        self._layer_versions: Dict[int, str] = {}
        # Wire-codec targets (docs/codec.md): the node's codec plane
        # (capability + encoded serving), the leader-stamped codec per
        # assigned layer (interval accounting, journal, and NACK ranges
        # then live in ENCODED byte space, and the stamped digest is
        # codec-qualified), and the per-layer advisory frame tag — the
        # fallback identity when no stamp arrived (digests disabled),
        # so encoded bytes are never stored as raw.
        self.codec_plane = codecs
        self._layer_codecs: Dict[int, str] = {}
        self._frag_codec: Dict[int, str] = {}
        # Content-delta stamps (docs/codec.md): per assigned layer, the
        # canonical digest of the RECONSTRUCTED form of a delta
        # transfer (LayerDigestsMsg.full_digests) — the second gate a
        # delta pair passes (the first verifies the wire stream under
        # its codec-qualified identity).
        self._full_digests: Dict[int, str] = {}
        # The rollout version the serving params were assembled under
        # ("" until a swap commits here), and the per-blob version map
        # of the CURRENT serving tree — the per-step uniformity guard
        # of the token-granularity flip reads it (docs/rollout.md).
        self.serving_version = ""
        self._serving_tree_versions: Dict[int, str] = {}
        # This node's own modeled NIC rate (bytes/s, 0 = unknown),
        # carried on announces so a mode-3 leader admitting this seat
        # as a JOINER models the link honestly instead of pinning the
        # most conservative configured value (docs/membership.md).
        self.nic_bw = 0
        # Live-swap state machine (runtime/swap.py): stages v2 sets
        # concurrently with v1 serving and applies the epoch-fenced
        # commit flip.  Only serving-capable nodes carry one.
        self.swap = (SwapController(self) if boot_cfg is not None
                     else None)
        self._own_digests: Dict[int, str] = {}
        self._digest_ok: set = set()
        self._digest_retries: Dict[int, int] = {}
        self._nack_counts: Dict[Tuple[int, int], int] = {}
        self.nacker = NackRetransmitter()
        # Preemption revoke registry (docs/service.md): queued sends a
        # re-plan demoted; consulted by the flow-job executor.
        self.revokes = RevokeRegistry()
        # Content-addressed layer store (runtime/store.py,
        # docs/service.md): digest -> locally held layer ids, fed by
        # this node's own announce-time hashes and ack-gate verifies.
        # When a digest stamp names an ASSIGNED layer this node doesn't
        # hold but whose bytes it provably has under another id (a v2
        # rollout's unchanged layer), the store aliases the bytes and
        # acks instantly -- zero wire bytes.
        self.content_store = ContentStore()
        # Delta base lookup (docs/codec.md): the plane resolves a base
        # digest to this node's verified canonical holding — both for
        # RECONSTRUCTING a delivered delta and for ENCODING one when
        # this node is picked as a delta sender.  Only wired when no
        # role claimed the plane yet (a leader wires its own map).
        if (self.codec_plane is not None
                and self.codec_plane.base_resolver is None):
            self.codec_plane.base_resolver = self._resolve_delta_base
        # Per-layer streaming boot staging (runtime/stream_boot.py):
        # each completed blob's decode + host→device placement runs the
        # moment its interval set completes, concurrent with the
        # remaining transfers, and the startup boot assembles the staged
        # leaves with one concat per leaf.  Gated by DLD_STREAM_BOOT.
        self._boot_stager = None
        if boot_cfg is not None and env_util.stream_boot_enabled():
            from .stream_boot import StreamingBootStager

            self._boot_stager = StreamingBootStager(
                boot_cfg, codec=boot_codec, placement=placement,
                node_id=node.my_id,
                digest_lookup=self._expected_digest,
                digest_verified=self._digest_ok)
            self._boot_stager.on_gathered = self._on_pod_gathered
        # Multi-controller serving (runtime/pp_serve.py): startup said a
        # ServeMsg will follow; the CLI keeps the process alive until
        # serve_done() fires (or times out).
        self.expect_serve = False
        self.serve_started = threading.Event()  # a ServeMsg arrived
        self._serve_q: "queue.Queue[object]" = queue.Queue()
        # Eager when enabled: handlers run on a 16-worker pool, so a lazy
        # check-then-set would race; raw byte blobs stage as uint8 so
        # odd-length layers round-trip exactly (bf16 would pad a byte).
        self._mover = None
        if stage_hbm:
            import numpy as _np

            from ..parallel.mover import WeightMover
            self._mover = WeightMover(dtype=_np.uint8)
        self._ready_q: "queue.Queue[object]" = queue.Queue()
        self._lock = threading.Lock()
        self._spmd = getattr(fabric, "kind", "") == "spmd"
        if fabric is not None and hasattr(fabric, "bind_store"):
            # SPMD fabric: the executor reads this node's own byte ranges
            # straight from the layer store when serving plans.
            fabric.bind_store(self.layers, self._lock)
        if self._spmd:
            # Stall self-healing: a persistent seq gap (a DevicePlanMsg
            # this process never received) reports the missing seqs to
            # the leader, which re-sends its retained plan — or cancels
            # the seq — so the pod lockstep never waits forever on one
            # lost control message.
            fabric.on_gap = self._report_plan_gap
        # layer -> Event: staging-in-progress marker so a re-plan duplicate
        # completing concurrently never double-stages a multi-GB layer
        # (check-and-mark happens under self._lock; the duplicate waits).
        self._hbm_staging: Dict[int, threading.Event] = {}
        # Fabric dest pipeline state: the shared in-flight window (lazy —
        # only fabric dests pay for the retirement thread) and the
        # batch-accumulation groups for leader-stamped plan batches.
        self._plan_window = None
        self._plan_batches: Dict[str, dict] = {}
        # NOTE on fault injection: the old construction-gated
        # ``test_drop_plan_seqs`` receiver knob is gone — deterministic
        # fault injection now lives entirely in the transport wrapper
        # (``transport/faults.FaultyTransport``), which the CLI arms via
        # its explicit test flags.  Production receivers see a plain
        # transport; no environment variable can drop real plans.
        #
        # Control-plane HA (docs/failover.md): the highest leader epoch
        # seen (stale-epoch control traffic from a zombie ex-leader is
        # FENCED against it), the requeue buffer for leader-routed
        # messages that failed during a failover window (flushed on the
        # next lease), and the StandbyController's lease hook.
        self._leader_epoch = -1
        # Epoch at which the CURRENT leader claimed its seat: a worker
        # switches leaders only for a strictly better claim — higher
        # epoch, or same epoch from a lower node id (the deterministic
        # tiebreak for two concurrently-promoted standbys) — so
        # alternating equal-epoch leases can never flip-flop the
        # leader pointer.
        self._leader_claim_epoch = -1
        self._leader_pending: "collections.deque" = collections.deque(
            maxlen=256)
        self.on_leader_lease = None
        # Set by a promoting StandbyController: leader-bound swap
        # messages (confirm/query/error) forward to the promoted
        # leader's driver — the shared loop keeps THIS handler.
        self.on_swap_leader_msg = None
        # Hierarchical control (docs/hierarchy.md): an attached
        # SubLeaderController sets this to trigger intra-group fan-out
        # the moment one of this seat's own layers completes (fired at
        # the ack chokepoint, every completion path).
        self.on_layer_complete = None
        # Elastic membership (docs/membership.md): the join/drain
        # handshake latches — join() blocks on the admit notice,
        # request_drain() on the leader's done/refused answer.
        self._join_admitted = threading.Event()
        self._drain_done = threading.Event()
        self._drain_error = ""
        # Latched by close(): a closed receiver's still-draining daemon
        # work (a boot thread finishing late) must not emit leader-routed
        # messages — its seat's address may already belong to a NEW
        # incarnation's cluster, and a stale report would corrupt that
        # run's control state.
        self._closed_evt = threading.Event()
        # The heartbeat follows the CURRENT leader: after a takeover the
        # beacon must feed the successor's failure detector, not a dead
        # seat's queue.
        self.heartbeat = HeartbeatSender(
            node.transport, node.my_id, node.leader_id, heartbeat_interval,
            leader_fn=lambda: self.node.leader_id,
        )
        # Telemetry plane (docs/observability.md): periodic run-scoped
        # metric snapshots to the leader (MetricsReportMsg; cumulative,
        # so a lost report costs staleness, never skew), started with
        # the first announce; and the clock-offset estimate from the
        # announce-time TimeSyncMsg round trip (leader clock minus this
        # node's — logged so cli/trace.py aligns multi-host timelines).
        self.clock_offset_ms = None
        self._metrics_stop = threading.Event()
        self._metrics_thread = None
        interval = telemetry.metrics_interval()
        self._metrics_interval = interval if telemetry.enabled() else 0.0
        # Corrupt-fragment reports (a frame the transport dropped for a
        # failed CRC, an injected drop, or a TTL-pruned stripe group)
        # become bounded NACKs to the fragment's source.
        if hasattr(node.transport, "on_corrupt"):
            node.transport.on_corrupt = self._on_corrupt_fragment
        self.loop = MessageLoop(node.transport)
        self._register_handlers()
        if start_loop:
            self.loop.start()

    def _register_handlers(self) -> None:
        self.loop.register(LayerMsg, self.handle_layer)
        self.loop.register(StartupMsg, self.handle_startup)
        self.loop.register(DevicePlanMsg, self.handle_device_plan)
        self.loop.register(ServeMsg, self.handle_serve)
        self.loop.register(BootHintMsg, self.handle_boot_hint)
        self.loop.register(GenerateReqMsg, self.handle_generate_req)
        self.loop.register(LayerDigestsMsg, self.handle_layer_digests)
        self.loop.register(LeaderLeaseMsg, self.handle_leader_lease)
        self.loop.register(TimeSyncMsg, self.handle_time_sync)
        self.loop.register(SwapCommitMsg, self.handle_swap_commit)
        self.loop.register(GroupPlanMsg, self.handle_group_plan)
        self.loop.register(JoinMsg, self.handle_join)
        self.loop.register(DrainMsg, self.handle_drain)

    # ------------------------------------------------- control-plane HA

    def note_leader_epoch(self, epoch: int) -> None:
        """Raise the fencing watermark (a promoting StandbyController
        bumps its own worker past the old leader's epoch)."""
        with self._lock:
            if epoch > self._leader_epoch:
                self._leader_epoch = epoch

    def _fence_stale(self, msg) -> bool:
        """True when ``msg`` carries a leader epoch BELOW the highest
        seen — a zombie ex-leader's control traffic, which must be
        rejected, not raced (docs/failover.md).  Messages without an
        epoch (-1: HA off / legacy peer) always pass; higher epochs
        raise the watermark."""
        epoch = getattr(msg, "epoch", -1)
        if epoch < 0:
            return False
        with self._lock:
            cur = self._leader_epoch
            if epoch >= cur:
                self._leader_epoch = max(cur, epoch)
                return False
        trace.count("failover.fenced")
        log.warn("fencing stale-epoch control message",
                 kind=type(msg).__name__, src=getattr(msg, "src_id", None),
                 epoch=epoch, current=cur)
        return True

    def handle_leader_lease(self, msg: LeaderLeaseMsg) -> None:
        """The leader's liveness beacon.  A lease from a DIFFERENT node
        at a current-or-higher epoch is a completed takeover: re-point
        the leader, flush any messages requeued while the old leader was
        unreachable, and re-announce — the announce carries this node's
        authoritative inventory (checkpointed partials included), which
        is exactly the reconcile the new leader resumes delivery from."""
        if self._fence_stale(msg):
            return
        switched = False
        with self._lock:
            cur_leader = self.node.leader_id
            if msg.src_id == cur_leader:
                self._leader_claim_epoch = max(self._leader_claim_epoch,
                                               msg.epoch)
            elif (msg.epoch, -msg.src_id) > (self._leader_claim_epoch,
                                             -cur_leader):
                # Strictly better claim: higher epoch, or same epoch
                # from the lower node id (concurrent-promotion tiebreak).
                self._leader_claim_epoch = msg.epoch
                switched = True
        if switched:
            self.node.add_node(msg.src_id)
            self.node.update_leader(msg.src_id)
        hook = self.on_leader_lease
        if hook is not None:
            try:
                hook(msg)
            except Exception as e:  # noqa: BLE001 — standby hook is advisory
                log.error("leader-lease hook failed", err=repr(e))
        self._flush_leader_pending()
        if switched:
            trace.count("failover.leader_switch")
            log.warn("new leader lease observed; re-announcing to it",
                     leader=msg.src_id, epoch=msg.epoch)
            try:
                self.announce()
            except (OSError, KeyError) as e:
                log.error("re-announce to new leader failed", err=repr(e))

    def handle_group_plan(self, msg: "GroupPlanMsg") -> None:
        """Member half of hierarchical control (docs/hierarchy.md): a
        ``dissolve`` notice means this member's sub-leader was declared
        dead — re-point the control parent at the root and re-announce
        there (acks/heartbeats/metrics flow to the root; the group
        degrades to flat delivery).  A ``forward`` plan installs this
        member's chain relay roles (mode-3 receivers override the
        install; elsewhere it logs and the sub-leader's redrive covers
        the member by direct send).  A TARGETS plan is sub-leader
        business; a seat without an attached SubLeaderController (which
        replaces this handler) logs and ignores it."""
        if self._fence_stale(msg):
            return
        if not msg.dissolve:
            if msg.forward:
                self._install_forward_roles(msg)
                return
            log.warn("group plan received by a non-sub-leader seat; "
                     "ignoring", group=msg.group_id, src=msg.src_id)
            return
        trace.count("hier.dissolved_members")
        log.warn("group dissolved; re-pointing control parent at root",
                 group=msg.group_id, root=msg.src_id)
        self._clear_forward_roles()
        self.node.add_node(msg.src_id)
        with self._lock:
            self._leader_claim_epoch = max(self._leader_claim_epoch,
                                           msg.epoch)
        try:
            self.node.update_leader(msg.src_id)
        except KeyError:
            pass
        self._flush_leader_pending()
        try:
            self.announce()
        except (OSError, KeyError) as e:
            log.error("re-announce to root after dissolve failed",
                      err=repr(e))

    def _install_forward_roles(self, msg: "GroupPlanMsg") -> None:
        """Chain relay roles need the mode-3 reassembly plane; a plain
        receiver can't forward mid-flight bytes — the roles are advisory,
        so ignoring them is safe (the sub-leader's redrive converges
        this member by direct send)."""
        log.info("chain forward roles ignored (no relay plane at this "
                 "seat)", group=msg.group_id, layers=sorted(msg.forward))

    def _clear_forward_roles(self) -> None:
        """No relay plane, nothing to clear (mode 3 overrides)."""

    # ------------------------------------------------ elastic membership

    def join(self, want=None, timeout: float = 10.0,
             attempts: int = 3) -> bool:
        """Ask the leader to admit this UNCONFIGURED seat into the
        running cluster (docs/membership.md), then announce.  ``want``
        optionally names the layer ids to receive (empty = the current
        goal's layer universe).  Bounded retry: a request eaten by a
        fault window is re-sent; returns whether admission landed.
        The announce that follows carries this seat's local holdings
        (checkpointed partials + digests), so a COLD-BOOTING joiner
        refills only its missing bytes — mostly from peer holders."""
        self._join_admitted.clear()
        req = JoinMsg(self.node.my_id,
                      addr=self.node.transport.get_address(),
                      want=[int(l) for l in want or []])
        per_try = max(timeout / max(attempts, 1), 0.5)
        for _ in range(max(attempts, 1)):
            try:
                self.node.transport.send(self.node.leader_id, req)
            except (OSError, KeyError, ConnectionError) as e:
                log.warn("join request send failed; retrying",
                         err=repr(e))
            if self._join_admitted.wait(per_try):
                trace.count("membership.joined")
                self.announce()
                return True
        log.error("join request never admitted", leader=self.node.leader_id)
        return False

    def release_ready(self) -> None:
        """Release a ``ready()`` waiter without a StartupMsg: a DRAINED
        seat never receives one (it left the goal), so its driver calls
        this after a successful :meth:`request_drain` to unblock the
        normal exit path."""
        self._ready_q.put({})

    def request_drain(self, timeout: float = 30.0) -> bool:
        """Graceful leave (docs/membership.md): ask the leader to
        re-home this seat's unique holdings and release it.  Blocks
        until the DONE notice (True) or the timeout/refusal (False) —
        only a True return makes exiting crash-path-safe."""
        self._drain_done.clear()
        self._drain_error = ""
        trace.count("membership.drain_requested")
        self._send_to_leader(DrainMsg(self.node.my_id))
        if not self._drain_done.wait(timeout):
            log.error("drain request not answered within the timeout")
            return False
        if self._drain_error:
            log.error("drain refused", err=self._drain_error)
            return False
        return True

    def handle_join(self, msg: JoinMsg) -> None:
        """Receiver half of the JOIN vocabulary: the admit reply (this
        seat's own admission — re-point at the named parent and latch),
        roster notices (a peer joined: install its address), and
        re-point notices (a re-formed group moves this member back
        under its sub-leader)."""
        if not msg.admitted:
            return  # requests are leader business
        if self._fence_stale(msg):
            return
        subject = msg.node if msg.node >= 0 else msg.src_id
        if subject != self.node.my_id and msg.addr:
            # Roster notice: a peer joined — make it dialable.
            try:
                self.node.transport.addr_registry[subject] = msg.addr
            except (AttributeError, TypeError):
                pass
            self.node.add_node(subject)
        repoint = (msg.parent >= 0 and msg.parent != self.node.my_id
                   and (subject == self.node.my_id
                        or msg.parent == subject))
        if repoint and msg.parent != self.node.leader_id:
            # Admission placed (or a re-formed group moved) this seat
            # under a new control parent: announces, acks, heartbeats,
            # and metric reports flow there now.
            if msg.parent_addr:
                try:
                    self.node.transport.addr_registry[msg.parent] = \
                        msg.parent_addr
                except (AttributeError, TypeError):
                    pass
            self.node.add_node(msg.parent)
            try:
                self.node.update_leader(msg.parent)
            except KeyError:
                pass
            trace.count("membership.repointed")
            log.info("control parent re-pointed by membership notice",
                     parent=msg.parent)
            self._flush_leader_pending()
            if subject != self.node.my_id:
                # A re-point of an ALREADY-RUNNING member (group
                # re-form): re-announce to the new parent.  A joiner's
                # own admit skips this — join() announces once the
                # latch below releases it.
                try:
                    self.announce()
                except (OSError, KeyError) as e:
                    log.error("re-announce to new parent failed",
                              err=repr(e))
        if subject == self.node.my_id:
            self._join_admitted.set()

    def handle_drain(self, msg: DrainMsg) -> None:
        """The leader's answer to this seat's drain request (done or
        refused)."""
        if not msg.done and not msg.error:
            return  # requests are leader business
        if self._fence_stale(msg):
            return
        subject = msg.node if msg.node >= 0 else self.node.my_id
        if subject != self.node.my_id:
            return
        self._drain_error = msg.error
        self._drain_done.set()

    def _send_to_leader(self, msg) -> None:
        """Leader-routed send with failover-window requeue: a leader
        that just died must not eat acks/boot reports — they queue
        (bounded) and flush when the next lease names a live leader.
        A CLOSED receiver sends nothing: its late daemon work (a boot
        finishing after close) must not leak reports into whatever now
        owns its old address."""
        if self._closed_evt.is_set():
            log.debug("suppressing leader-routed send after close",
                      kind=type(msg).__name__)
            return
        try:
            self.node.transport.send(self.node.leader_id, msg)
        except (OSError, KeyError) as e:
            trace.count("failover.leader_requeued")
            with self._lock:
                self._leader_pending.append(msg)
            log.warn("leader unreachable; queued message for the "
                     "failover window", kind=type(msg).__name__,
                     err=repr(e))

    def _flush_leader_pending(self) -> None:
        while True:
            with self._lock:
                if not self._leader_pending:
                    return
                msg = self._leader_pending.popleft()
            try:
                self.node.transport.send(self.node.leader_id, msg)
            except (OSError, KeyError) as e:
                with self._lock:
                    self._leader_pending.appendleft(msg)
                log.warn("leader still unreachable; keeping queued "
                         "messages", err=repr(e))
                return

    def announce(self) -> None:
        """Tell the leader what I already hold, routed via the next hop
        (node.go:1392-1415)."""
        with self._lock:
            layer_ids: LayerIDs = {
                lid: LayerMeta(
                    location=src.meta.location,
                    limit_rate=src.meta.limit_rate,
                    source_type=src.meta.source_type,
                    data_size=src.data_size,
                    shard=src.meta.shard,
                    version=src.meta.version,
                    codec=src.meta.codec,
                )
                for lid, src in self.layers.items()
            }
        next_hop = self.node.get_next_hop(self.node.leader_id)
        if self.fabric is not None:
            # (Re)entering a distribution cycle: uploads may be retained
            # again until the next startup releases them.
            reopen_upload_cache()
        # Liveness BEFORE the digest hash: _announce_digests can run
        # seconds-to-minutes at physical sizes on a crc32-only host,
        # and the leader's failure-detector lease is already counting
        # down — heartbeats must flow while we hash.
        self.heartbeat.start()
        self.node.transport.send(
            next_hop,
            AnnounceMsg(self.node.my_id, layer_ids,
                        partial=self._announce_partial(),
                        digests=self._announce_digests(),
                        codecs=(self.codec_plane.decode_codecs()
                                if self.codec_plane is not None else []),
                        nic_bw=int(self.nic_bw or 0)),
        )
        # Telemetry plane: probe the leader's clock (request/response
        # midpoint → the offset cli/trace.py aligns timelines with) and
        # start the periodic metric reports.  Both advisory: a lost
        # probe or report costs observability, never delivery.
        try:
            self.node.transport.send(
                self.node.leader_id,
                TimeSyncMsg(self.node.my_id, _time.time() * 1000.0))
        except (OSError, KeyError) as e:
            log.debug("time-sync probe send failed", err=repr(e))
        self._start_metrics_reporter()

    # ------------------------------------------------------ telemetry plane

    def handle_time_sync(self, msg: TimeSyncMsg) -> None:
        """Both halves of the clock-offset probe.  A REQUEST is answered
        with this node's wall clock (any seat can answer; the leader's
        answer is the one that matters, and after a takeover the
        promoted worker answers with the new reference clock).  A REPLY
        closes this node's own probe: offset = t1 - (t0 + t2)/2, the
        NTP midpoint estimate, error-bounded by rtt/2 — both logged, so
        the offline trace tooling has what it needs."""
        now = _time.time() * 1000.0
        if not msg.reply:
            try:
                self.node.transport.send(
                    msg.src_id,
                    TimeSyncMsg(self.node.my_id, msg.t0_ms, t1_ms=now,
                                reply=True))
            except (OSError, KeyError) as e:
                log.debug("time-sync reply send failed", dest=msg.src_id,
                          err=repr(e))
            return
        rtt_ms = now - msg.t0_ms
        if rtt_ms < 0:
            return  # this process's own clock stepped mid-probe
        offset_ms = msg.t1_ms - (msg.t0_ms + now) / 2.0
        self.clock_offset_ms = offset_ms
        telemetry.gauge("clock_offset_ms", offset_ms)
        log.info("clock offset estimated", offset_ms=round(offset_ms, 3),
                 rtt_ms=round(rtt_ms, 3), reference=msg.src_id)

    def _start_metrics_reporter(self) -> None:
        if self._metrics_interval <= 0 or self._metrics_thread is not None:
            return
        self._metrics_thread = threading.Thread(
            target=self._metrics_loop, daemon=True,
            name=f"metrics-{self.node.my_id}")
        self._metrics_thread.start()

    def _metrics_loop(self) -> None:
        while not self._metrics_stop.wait(self._metrics_interval):
            self._send_metrics_report()

    def _send_metrics_report(self) -> None:
        """One cumulative run-scoped snapshot to the current leader.
        Best-effort by design (NOT the requeue path — a stale metric is
        worthless by the time a failover window drains): a failed send
        is simply superseded by the next interval's snapshot."""
        if self._closed_evt.is_set():
            return
        # Thread census by plane (docs/observability.md): refreshed
        # just before every snapshot, so the run report's
        # threads-by-plane table is per node and current.
        threads_util.publish_census()
        snap = telemetry.snapshot()
        gauges = dict(snap.get("gauges") or {})
        # Phase buckets ride as flat gauges so the leader's fold (and
        # the run report's cluster phase table) sees per-node phase
        # totals without a second wire vocabulary.
        for name, rec in (snap.get("phases") or {}).items():
            gauges[f"phase.{name}_ms"] = rec["ms"]
        with self._lock:
            epoch = self._leader_epoch
        msg = MetricsReportMsg(
            self.node.my_id, counters=snap.get("counters") or {},
            gauges=gauges, links=snap.get("links") or {},
            t_wall_ms=_time.time() * 1000.0, epoch=epoch,
            proc=snap.get("proc", ""),
            # Fixed-bucket histograms ride too (the rollout pipeline's
            # SLO guard reads per-replica serve latency from them,
            # docs/rollout.md).
            hists=snap.get("hists") or {},
            # Pair-lifecycle span ring (docs/observability.md):
            # cumulative like every section — the leader's fold is
            # replace-per-node.
            spans=snap.get("spans") or [])
        try:
            self.node.transport.send(self.node.leader_id, msg)
        except (OSError, KeyError) as e:
            log.debug("metrics report send failed", err=repr(e))

    # ------------------------------------------------------- integrity plane

    def _announce_digests(self) -> dict:
        """Self-describing digests (``integrity.layer_digest``) of this
        node's held full layers, cached (a
        re-announce must not re-hash gigabytes).  Runs PRE-TIMER for
        seeders (announce precedes the leader's start), so the hash cost
        never lands inside TTD."""
        if not integrity.digests_enabled():
            return {}
        with self._lock:
            # SHARD holdings never announce a layer digest: their buffer
            # is only real inside the shard's range, and hashing it as a
            # full layer would poison the leader's stamp collection
            # (docs/sharding.md).  CODEC holdings don't either: their
            # digest is the digest of the ENCODED form — presenting it
            # as the canonical layer digest would poison the stamp the
            # same way (docs/codec.md).  Both index their own key into
            # the content store at verify time instead.
            todo = [(lid, src) for lid, src in self.layers.items()
                    if lid not in self._own_digests
                    and not src.meta.shard and not src.meta.codec]
        for lid, src in todo:
            d = integrity.digest_layer_src(src)
            if d is not None:
                self._own_digests[lid] = d
                self.content_store.index(lid, d)
        with self._lock:
            return {lid: d for lid, d in self._own_digests.items()
                    if not (self.layers.get(lid) is not None
                            and (self.layers[lid].meta.shard
                                 or self.layers[lid].meta.codec))}

    def handle_layer_digests(self, msg: LayerDigestsMsg) -> None:
        """The leader's expected-digest stamp for this dest's layers;
        leader-authoritative (a re-stamp after update() overwrites).

        Handlers run on an unordered pool (and layer frames ride
        separate data sockets), so a small layer can land — and ack —
        BEFORE its stamp is processed.  Close the race by re-checking
        already-held layers against the newly stamped digests: a
        mismatch demotes the layer and re-announces so the leader
        re-plans it, exactly like a mismatch at the ack gate."""
        if self._fence_stale(msg):
            return
        widened = []
        recoded = []
        with self._lock:
            # A CHANGED stamp (a swap retry superseding a poisoned
            # digest, docs/swap.md) resets the layer's verification
            # state: the old verdict and the spent retry budget belong
            # to the old expectation — without the reset, a corrected
            # rollout gives up instantly on the exhausted counter.
            for lid, d in msg.digests.items():
                prior = self.layer_digests.get(lid)
                if prior is not None and prior != d:
                    self._digest_retries.pop(lid, None)
                    self._digest_ok.discard(lid)
            self.layer_digests.update(msg.digests)
            # Content-delta stamps (docs/codec.md): the canonical
            # identity a delta pair's RECONSTRUCTED bytes verify
            # against.  A changed stamp resets the verification state
            # exactly like a changed stream digest above.
            for lid, d in msg.full_digests.items():
                prior = self._full_digests.get(lid)
                if prior is not None and prior != d:
                    self._digest_retries.pop(lid, None)
                    self._digest_ok.discard(lid)
            self._full_digests.update(msg.full_digests)
            # Rollout version stamps (docs/swap.md): which version each
            # assigned layer belongs to — stored holdings and acks
            # carry the tag from here on.
            self._layer_versions.update(msg.versions)
            # Wire-codec stamps (docs/codec.md): which ENCODED form
            # each assigned layer arrives in.  Leader-authoritative per
            # dest: a pair whose stamped codec CHANGED (re-targeted to
            # raw after a takeover, or to a different codec) invalidates
            # any in-flight partial state — its interval accounting
            # lives in the OLD form's byte space, and mixing spaces
            # would assemble garbage — so those layers demote for a
            # clean redelivery.  A RAW holding under a codec stamp
            # stays: canonical bytes satisfy every target.
            for lid in sorted(set(msg.digests) | set(msg.codecs)):
                new_codec = msg.codecs.get(lid, "")
                old_codec = self._layer_codecs.get(lid, "")
                if new_codec == old_codec:
                    continue
                src = self.layers.get(lid)
                held = src.meta.codec if src is not None else ""
                partial = lid in self._partial_totals_locked()
                if (held and held != new_codec) or (partial and old_codec):
                    recoded.append(lid)
                if new_codec:
                    self._layer_codecs[lid] = new_codec
                else:
                    self._layer_codecs.pop(lid, None)
            # The stamp is leader-authoritative per dest: a layer
            # stamped with a FULL digest and no shard entry — or an
            # explicit ``""`` entry in the shards map (the digests-off
            # form) — had its target WIDENED (e.g. a second job wanting
            # a disjoint shard merged the pair to full); one stamped
            # with a DIFFERENT spec the held shard doesn't cover was
            # RE-TARGETED.  Either way the stale spec must not keep
            # completing (and re-acking) at the old shard's coverage,
            # and an already-promoted shard holding must reopen as a
            # partial so the redelivered remainder completes the new
            # target (the replan/re-ack livelock this breaks: the
            # leader plans the new range forever while the dest's
            # dup-done path re-acks the old shard forever).
            def _reconcile(lid, new_spec):
                src = self.layers.get(lid)
                if (src is not None and src.meta.shard
                        and not shard_covers(src.meta.shard, new_spec)):
                    widened.append(lid)

            for lid in msg.digests:
                if lid not in msg.shards:
                    self._shard_specs.pop(lid, None)
                    self._range_digests.pop(lid, None)
                    _reconcile(lid, "")
            for lid, spec in msg.shards.items():
                if not spec:
                    self._shard_specs.pop(lid, None)
                    self._range_digests.pop(lid, None)
                _reconcile(lid, spec)
            self._shard_specs.update(
                {l: s for l, s in msg.shards.items() if s})
            self._range_digests.update(msg.range_digests)
            # Pod-delivery stamps (docs/fabric.md): which shard targets
            # are pod slices owing a full on-mesh reconstruction.  A
            # stamped layer whose pod entry DISAPPEARED was degraded to
            # plain delivery — stop expecting (or driving) a gather.
            for lid in set(msg.digests) | set(msg.shards):
                if lid not in msg.pods:
                    self._pod_widths.pop(lid, None)
            self._pod_widths.update(
                {int(l): int(n) for l, n in msg.pods.items() if n > 1})
        log.debug("layer digests stamped", n=len(msg.digests),
                  shards=len(msg.shards), codecs=len(msg.codecs))
        for lid in recoded:
            log.warn("layer's wire codec re-stamped; dropping stale "
                     "form for clean redelivery", layerID=lid,
                     codec=msg.codecs.get(lid, ""))
            self._demote_corrupt_layer(lid)
        if widened:
            self._reopen_widened(widened)
        self._recheck_stamped(list(msg.digests))
        self._try_content_resolve(sorted(msg.digests))
        if msg.shards:
            # Fragments can land BEFORE their shard stamp: a layer whose
            # coverage already satisfies the just-learned shard must
            # promote now — no later fragment will re-run the check.
            self._on_shard_specs(sorted(msg.shards))
        if msg.pods:
            # So can POD stamps: a member already holding its slice (or
            # the full tree) publishes it now instead of leaving peers
            # to time out waiting (docs/fabric.md).
            self._pod_publish_existing(sorted(msg.pods))
        if msg.versions:
            # Version stamps can lose the race against small layers the
            # same way: a layer that landed (and acked, unversioned)
            # before its stamp re-acks with the tag — the leader's swap
            # fence needs the versioned ack, and nothing else re-runs it.
            self._reack_versioned(sorted(msg.versions))

    def _reack_versioned(self, lids) -> None:
        for lid in lids:
            with self._lock:
                src = self.layers.get(lid)
                stamped = self._layer_versions.get(lid, "")
            if src is None or src.meta.shard:
                continue
            if src.meta.version == stamped:
                # Already acked under this tag (the stamp is re-sent on
                # every admission/replan): a re-ack here would make
                # every long-lived dest volley acks per new job.
                continue
            if (self._expected_digest(lid) is not None
                    and lid not in self._digest_ok):
                continue  # the ack gate will stamp + ack when it passes
            self._send_ack(lid, src.meta.location)

    def _reopen_widened(self, lids) -> None:
        """Hook: these SHARD holdings' targets widened (or re-targeted
        to a shard the held one doesn't cover).  The flow receiver
        demotes them back to partial coverage (keeping the shard's
        landed bytes); the base receiver can't reassemble fragments, so
        it drops the holding — the re-plan re-ships the whole target."""
        for lid in lids:
            with self._lock:
                src = self.layers.get(lid)
                if src is None or not src.meta.shard:
                    continue
                del self.layers[lid]
                self._own_digests.pop(lid, None)
                self._digest_ok.discard(lid)
            self.content_store.forget(lid)
            log.warn("shard holding's target widened/re-targeted; "
                     "dropped for redelivery", layerID=lid)

    def _on_shard_specs(self, lids) -> None:
        """Hook: shard specs were (re)stamped for these layers.  The
        flow receiver re-checks completion; the base receiver has no
        partial state to promote."""

    def _partial_totals_locked(self) -> dict:
        """Lock held.  In-flight partial transfer totals ({layer:
        total}) — the flow receiver's reassembly state; the base
        receiver has none."""
        return {}

    def _recheck_stamped(self, lids) -> None:
        """Retroactive digest verification for layers that landed before
        their stamp arrived (no-op for already-verified ones)."""
        for lid in lids:
            with self._lock:
                src = self.layers.get(lid)
                done = lid in self._digest_ok
                stamped_codec = self._layer_codecs.get(lid, "")
            if src is None or done or src.inmem_data is None:
                continue
            if src.meta.shard:
                # A shard holding verified against its RANGE digest at
                # the shard gate; the full-layer stamp doesn't apply to
                # its buffer (only the shard's range is real).
                continue
            if src.meta.codec != stamped_codec:
                # A RAW holding under a codec stamp: the stamped digest
                # is the ENCODED form's — it can't verify canonical
                # bytes, and raw satisfies the target anyway
                # (docs/codec.md).  Mismatched encoded forms were
                # demoted at stamp time.  EXCEPT a raw holding under a
                # DELTA stamp with a FullDigests entry: that's a
                # reconstructed (or pre-held) canonical form, and the
                # full digest verifies it (_verify_layer_digest).
                if not (not src.meta.codec
                        and delta_base_digest(stamped_codec)
                        and self._full_digests.get(lid)):
                    continue
            if self._verify_layer_digest(lid, memoryview(src.inmem_data),
                                         codec=src.meta.codec):
                continue
            self._demote_corrupt_layer(lid)
            log.error("stamped digest failed for an already-held layer; "
                      "demoted", layerID=lid)
            if self._bump_digest_retry(lid):
                self._request_replan()

    def _resolve_pending_for_layer(self, lid) -> None:
        """A layer just COMMITTED to the store: it can be the DONOR a
        stamped-but-missing layer was waiting for (the stamp arrived
        before these bytes did).  Without this re-check the pair would
        wedge — the leader's content index learns the holding from the
        ack and skips shipping, while nothing else ever re-runs the
        resolve.  Must not be called under ``self._lock``."""
        with self._lock:
            if (self._shard_specs.get(lid)
                    or self._layer_codecs.get(lid)
                    or (self.layers.get(lid) is not None
                        and (self.layers[lid].meta.shard
                             or self.layers[lid].meta.codec))):
                # A shard or codec holding can't donate full-layer
                # canonical bytes (its digest keys a different form).
                return
            digest = (self._own_digests.get(lid)
                      or self.layer_digests.get(lid))
            pending = ([l for l, d in self.layer_digests.items()
                        if d == digest and l not in self.layers
                        and not self._shard_specs.get(l)
                        and not self._layer_codecs.get(l)]
                       if digest else [])
        if pending:
            self._try_content_resolve(sorted(pending))

    def _try_content_resolve(self, lids) -> None:
        """Content-addressed instant resolve (docs/service.md): for each
        stamped layer this node does NOT hold, check the content store
        for locally held bytes with the SAME digest (a v2 rollout's
        unchanged layer under a new id).  A hit aliases the held buffer
        under the new layer id and acks immediately — zero wire bytes —
        which is what lets a delta rollout ship only changed layers.
        The alias shares the donor's buffer (received layers are never
        mutated after commit) and inherits its verified digest."""
        for lid in lids:
            with self._lock:
                if lid in self.layers:
                    continue
                if self._shard_specs.get(lid):
                    # Sharded targets resolve by the (digest, range)
                    # key, which full-layer vouching doesn't carry —
                    # no content resolve for them (docs/sharding.md,
                    # honest limits).
                    continue
                if self._layer_codecs.get(lid):
                    # Codec targets resolve by the (digest, codec) key;
                    # full-layer raw vouching doesn't carry it — no
                    # content resolve (docs/codec.md, honest limits).
                    continue
                digest = self.layer_digests.get(lid)
            if not digest:
                continue
            donor_lid = self.content_store.lookup(digest)
            if donor_lid is None:
                continue
            with self._lock:
                if lid in self.layers:
                    continue
                donor = self.layers.get(donor_lid)
                # Only a delivered-grade donor (host bytes in RAM, or
                # HBM with the retained host buffer) can vouch: an ack
                # means "in memory", and a DISK-only copy isn't.
                if (donor is None or donor.inmem_data is None
                        or donor.meta.location not in
                        (LayerLocation.INMEM, LayerLocation.HBM)):
                    continue
                alias = LayerSrc(
                    inmem_data=donor.inmem_data, fp=donor.fp,
                    data_size=donor.data_size,
                    meta=LayerMeta(location=LayerLocation.INMEM,
                                   source_type=donor.meta.source_type),
                )
                self.layers[lid] = alias
                self._own_digests[lid] = digest
                self._digest_ok.add(lid)
            self.content_store.index(lid, digest)
            trace.count("store.resolved_layers")
            trace.count("store.resolved_bytes", alias.data_size)
            log.info("content store resolved layer from local bytes; "
                     "no wire transfer", layerID=lid, donor=donor_lid,
                     bytes=alias.data_size, digest=digest)
            # Streamed boot staging treats the alias like any completed
            # layer; then ack so the leader credits every job waiting
            # on the pair.
            self._boot_stream_submit(lid, alias)
            self._send_ack(lid, alias.meta.location)

    def _bump_digest_retry(self, lid) -> bool:
        """Count one digest-mismatch recovery round for a layer; False
        when the budget is spent — the layer stays undelivered and the
        failure is loud (a corrupt SOURCE must never converge to a
        successful run, and must not livelock retransmits either)."""
        with self._lock:
            n = self._digest_retries.get(lid, 0) + 1
            self._digest_retries[lid] = n
        if n > _DIGEST_MAX_RETRIES:
            log.error("digest retry budget exhausted; layer stays "
                      "undelivered", layerID=lid, tries=n)
            trace.count("integrity.digest_given_up")
            if self.swap is not None:
                # A versioned layer that can never verify here means the
                # swap can never complete on this replica: report it so
                # the leader aborts cluster-wide (v1 keeps serving)
                # instead of waiting out the rollout forever.
                self.swap.on_staging_failed(lid, "digest retries exhausted")
            return False
        return True

    def _demote_corrupt_layer(self, lid) -> None:
        """Remove a digest-failed layer from the store (the flow
        receiver extends this with journal/partial/ingest teardown).
        The cached own-digest drops with it: a later re-announce must
        hash the REDELIVERED bytes, not re-announce the corrupt copy's
        digest.  So does any streamed boot staging of the corrupt bytes
        (stamp-race: a small layer can land, ack, and stage before its
        digest stamp is processed) — the redelivered copy re-stages."""
        with self._lock:
            self.layers.pop(lid, None)
            self._own_digests.pop(lid, None)
            self._frag_codec.pop(lid, None)
        self.content_store.forget(lid)
        if self._boot_stager is not None:
            self._boot_stager.invalidate(lid)

    def _expected_digest(self, lid):
        """The leader-stamped digest for a layer, falling back to this
        node's own announced digest (a seeder re-verifying its copy).
        For a SHARDED target the expected digest is the RANGE digest —
        the digest of exactly the shard's bytes (docs/sharding.md);
        callers hash the shard's slice against it.  None when the
        sharded stamp carried no range digest (the shard then verifies
        by per-fragment CRC alone)."""
        with self._lock:
            if self._shard_specs.get(lid):
                return self._range_digests.get(lid)
            return self.layer_digests.get(lid) or self._own_digests.get(lid)

    def _on_corrupt_fragment(self, src_id, layer_id, offset, size,
                             total, reason) -> None:
        """Transport hook: a frame was dropped before delivery (bad CRC,
        injected drop, or a TTL-pruned stripe group).  NACK the source
        for a byte-range retransmit — bounded per range, so a
        persistently corrupt path fails loudly instead of livelocking."""
        key = (layer_id, offset)
        with self._lock:
            n = self._nack_counts.get(key, 0) + 1
            self._nack_counts[key] = n
        if n > _NACK_MAX_PER_RANGE:
            log.error("NACK budget exhausted for range; leaving recovery "
                      "to crash detection", layerID=layer_id, offset=offset,
                      size=size, reason=reason)
            trace.count("integrity.nack_suppressed")
            return
        if src_id is None or src_id == self.node.my_id:
            return
        self._send_nack(src_id, layer_id, offset, size, total, reason)

    def _send_nack(self, src_id, layer_id, offset, size, total,
                   reason) -> None:
        trace.count("integrity.nack_sent")
        telemetry.link_add(src_id, self.node.my_id, nacks=1)
        # Wire-codec transfers NACK in ENCODED byte space: the codec
        # rides the NACK so the serving holder retransmits ranges of
        # the same encoded form (docs/codec.md).
        with self._lock:
            codec = (self._layer_codecs.get(layer_id)
                     or self._frag_codec.get(layer_id, ""))
        log.warn("layer fragment NACKed", layerID=layer_id, src=src_id,
                 offset=offset, bytes=size, reason=reason,
                 codec=codec or None)
        try:
            self.node.add_node(src_id)
            self.node.transport.send(
                src_id,
                LayerNackMsg(self.node.my_id, layer_id, offset, size,
                             total_size=total, reason=reason,
                             codec=codec),
            )
        except (OSError, KeyError, ConnectionError) as e:
            log.error("NACK send failed", dest=src_id, layerID=layer_id,
                      err=repr(e))

    def _verify_layer_digest(self, lid, data, shard: str = "",
                             codec: str = "") -> bool:
        """Check ``data`` against the layer's expected digest; True when
        no digest is known or it matches (memoized — a re-ack never
        re-hashes).  Counts + logs the outcome; the CALLER owns
        recovery (drop/NACK for whole-layer frames, interval re-open +
        re-announce for assembled mode-3 layers).  ``shard``: the spec
        ``data`` spans (the caller sliced the shard's range; the
        expected digest is then the stamped RANGE digest, and the
        verified bytes are content-indexed under the (digest, shard)
        key — docs/sharding.md).  ``codec``: the wire-codec form the
        bytes are in — the expected digest is then codec-qualified
        (the stamp hashed exactly the encoded bytes), and the content
        index carries the codec so encoded bytes never vouch for a raw
        pair (docs/codec.md)."""
        expected = self._expected_digest(lid)
        if not codec and not shard:
            with self._lock:
                stamped_c = self._layer_codecs.get(lid, "")
                if delta_base_digest(stamped_c):
                    # RAW bytes under a delta stamp: a reconstructed
                    # form's identity is the canonical FullDigests
                    # entry, never the delta stream's codec-qualified
                    # digest (docs/codec.md).
                    expected = self._full_digests.get(lid)
        if expected is None:
            return True
        with self._lock:
            if lid in self._digest_ok:
                return True
        ok, dt, got = integrity.digest_check(data, expected)
        if ok is None:
            return True  # xxh3 stamp, no xxhash here: advisory skip
        trace.add_phase("integrity_digest", dt)
        if ok:
            with self._lock:
                self._digest_ok.add(lid)
                # The bytes now provably hash to the stamp: seed the
                # announce cache so a recovery re-announce (replan,
                # digest retry) never re-hashes gigabytes it already
                # verified on the handler thread.  (Shard and codec
                # holdings skip it — their cache entry would be a RANGE
                # or encoded-form digest the announce must not present
                # as a canonical layer digest.)
                if not shard and not codec:
                    self._own_digests[lid] = expected
            self.content_store.index(lid, expected, shard=shard,
                                     codec=codec)
            log.info("layer digest verified", layerID=lid,
                     digest_ms=round(dt * 1000, 1), bytes=len(data),
                     codec=codec or None)
            return True
        trace.count("integrity.digest_mismatch")
        log.error("layer digest MISMATCH", layerID=lid, expected=expected,
                  got=got, bytes=len(data))
        return False

    # ------------------------------------------------ content-delta plane

    def _resolve_delta_base(self, digest):
        """digest → this node's verified canonical holding (the codec
        plane's ``base_resolver``): a content-store hit with
        delivered-grade host bytes, full-layer raw form only — the same
        donor rules as the content resolve.  Runs lock-free relative to
        the plane (only this node's own lock, never held by plane
        callers)."""
        lid = self.content_store.lookup(digest)
        if lid is None:
            return None
        with self._lock:
            src = self.layers.get(lid)
            if (src is None or src.inmem_data is None
                    or src.meta.shard or src.meta.codec):
                return None
            return src

    def _delta_reconstruct_bytes(self, lid, data, codec):
        """Canonical bytes from a VERIFIED delta stream, gated against
        the stamped FullDigests identity.  None = refused (no plane, no
        stamp, base lost here, or the reconstruction mismatched) — the
        caller demotes/drops for a raw re-plan; corrupt state never
        acks (docs/codec.md)."""
        plane = self.codec_plane
        if plane is None:
            log.error("delta transfer without a codec plane; refused",
                      layerID=lid)
            return None
        full = self._full_digests.get(lid, "")
        if not full:
            # The leader only chooses delta with the integrity plane on
            # and always stamps the canonical identity alongside —
            # reconstructing unverifiable bytes would trade corruption
            # for byte savings.
            log.error("delta transfer without a FullDigests stamp; "
                      "refusing reconstruction", layerID=lid)
            return None
        raw = plane.delta_reconstruct(lid, data, codec)
        if raw is None:
            return None
        ok, dt, got = integrity.digest_check(memoryview(raw), full)
        trace.add_phase("integrity_digest", dt)
        if ok is False:
            trace.count("integrity.digest_mismatch")
            log.error("delta reconstruction failed the canonical "
                      "digest", layerID=lid, expected=full, got=got)
            return None
        return raw

    def _note_delta_reconstructed(self, lid, wire_bytes: int,
                                  raw_bytes: int) -> None:
        """Bookkeeping after a reconstructed delta holding COMMITTED:
        the canonical digest seeds the announce cache and the content
        store (this node now vouches for — and can base future deltas
        on — the reconstructed bytes), and the wire/raw byte split is
        counted so the run report shows the delta win explicitly."""
        full = self._full_digests.get(lid, "")
        if full:
            with self._lock:
                self._own_digests[lid] = full
                self._digest_ok.add(lid)
            self.content_store.index(lid, full)
        trace.count("codec.delta_wire_bytes", wire_bytes)
        trace.count("codec.delta_raw_bytes", raw_bytes)
        log.info("delta stream reconstructed to canonical form",
                 layerID=lid, wire_bytes=wire_bytes,
                 raw_bytes=raw_bytes)

    def _finalize_delta(self, lid, src):
        """A completed, stream-verified delta holding reconstructs to
        canonical bytes NOW — the store must never stage (or ack) the
        delta stream itself.  Returns the replaced holding (or ``src``
        unchanged for non-delta forms); None when reconstruction
        refused — the layer demoted for a re-plan."""
        codec = src.meta.codec
        if not delta_base_digest(codec) or src.meta.shard:
            return src
        if src.inmem_data is None:
            log.error("delta holding without host bytes; demoted",
                      layerID=lid)
            raw = None
        else:
            raw = self._delta_reconstruct_bytes(
                lid, memoryview(src.inmem_data), codec)
        if raw is None:
            self._demote_corrupt_layer(lid)
            if self._bump_digest_retry(lid):
                self._request_replan()
            return None
        wire = src.data_size
        with self._lock:
            new_src = LayerSrc(
                inmem_data=bytearray(raw), data_size=len(raw),
                meta=LayerMeta(location=LayerLocation.INMEM,
                               source_type=src.meta.source_type,
                               version=src.meta.version),
            )
            new_src.offset = 0
            self.layers[lid] = new_src
        self._note_delta_reconstructed(lid, wire, len(raw))
        return new_src

    def _announce_partial(self) -> dict:
        """Checkpointed in-progress coverage to include in the announce;
        the base receiver has none."""
        return {}

    def ready(self) -> "queue.Queue[object]":
        return self._ready_q

    def _boot_stream_submit(self, layer_id, src) -> None:
        """Hand a freshly completed layer to the streaming boot stager
        (idempotent; a late duplicate no-ops).  Advisory: any failure
        here only costs the overlap — the startup boot's bulk assembly
        still covers every blob."""
        stager = self._boot_stager
        if stager is None or src is None:
            return
        try:
            stager.submit(layer_id, src)
        except Exception as e:  # noqa: BLE001 — staging is an optimization
            log.warn("streamed boot submit failed", layerID=layer_id,
                     err=repr(e))

    # ------------------------------------ fabric-assisted pod delivery

    def _pod_stager(self):
        """The shard-gather driver (docs/fabric.md): the boot stager
        when one exists (its gather ALSO dequants + stages the decoded
        leaves on device), else a lazily-built cfg-less stager that
        exists purely to run ``submit_shard``/``gather_byte_shards``
        off the handler threads."""
        if self._boot_stager is not None:
            return self._boot_stager
        with self._lock:
            if self._pod_stager_obj is None:
                from .stream_boot import StreamingBootStager

                self._pod_stager_obj = StreamingBootStager(
                    None, node_id=self.node.my_id)
                self._pod_stager_obj.on_gathered = self._on_pod_gathered
            return self._pod_stager_obj

    def _pod_board(self):
        """The pod shard-exchange board — the single-controller
        ``FabricPlane``'s in-process stand-in for the ICI hop.  None
        when this node has no fabric (or an SPMD one: there the leader
        dispatches the reconstruction as a lockstep plan instead)."""
        if self._spmd or self.fabric is None:
            return None
        return self.fabric if hasattr(self.fabric, "pod_publish") else None

    def _start_pod_collect(self, lid: int, src) -> None:
        """A verified holding covering this dest's pod slice exists
        (usually the freshly completed SHARD; also a pre-existing full
        or shard holding when the pod stamp arrives after a restart or
        over seeded bytes): publish the slice to the pod board and
        start this layer's collect loop (once) — peers' shards feed
        ``submit_shard`` in ANY completion order, and the last arrival
        fires the on-mesh gather."""
        board = self._pod_board()
        with self._lock:
            n = self._pod_widths.get(lid)
            # The STAMPED target spec names this dest's slice; the
            # holding may be wider (a full tree publishes its slice so
            # peers' gathers don't wait out the timeout for it).
            spec = self._shard_specs.get(lid) or src.meta.shard
            codec = self._layer_codecs.get(lid, "")
            if board is None or n is None or lid in self._pod_collecting:
                return
            self._pod_collecting.add(lid)
        from ..core.types import parse_shard_spec

        parsed = parse_shard_spec(spec)
        if (parsed is None or parsed[0] != n
                or not shard_covers(src.meta.shard, spec)
                or src.meta.codec != codec
                or src.inmem_data is None):
            log.error("pod stamp disagrees with the held bytes; not "
                      "gathering", layerID=lid, spec=spec, pod_n=n,
                      held_shard=src.meta.shard or None,
                      held_codec=src.meta.codec or None)
            with self._lock:
                # Un-claim: a corrected re-stamp must be able to retry.
                self._pod_collecting.discard(lid)
            return
        rank = parsed[1]
        total = src.data_size
        s0, s_sz = shard_range(spec, total)
        key = (lid, n, codec)
        board.pod_publish(key, rank,
                          memoryview(src.inmem_data)[s0:s0 + s_sz])
        log.info("pod shard published for on-mesh gather", layerID=lid,
                 rank=rank, pod_n=n, bytes=s_sz, codec=codec or None)
        threading.Thread(
            target=self._pod_collect_loop, args=(lid, n, total, codec),
            daemon=True, name=f"pod-collect-{self.node.my_id}").start()

    def _pod_publish_existing(self, lids) -> None:
        """A pod stamp can name layers this dest ALREADY holds (restart
        re-announce, seeded replicas, a completed earlier pod round):
        publish the slice from the existing holding so peers' gathers
        never wait out the collect window for a member whose shard
        phase finished before the stamp."""
        if self._spmd:
            return  # the leader drives SPMD reconstruction explicitly
        for lid in lids:
            with self._lock:
                src = self.layers.get(lid)
                spec = self._shard_specs.get(lid, "")
                range_digest = self._range_digests.get(lid)
                verified = lid in self._digest_ok
            if src is None or src.inmem_data is None:
                continue
            if not verified and range_digest:
                # A pre-held full tree never crossed the shard gate:
                # its SLICE must verify against the stamped range
                # digest before it may enter peers' gathers.
                s0, s_sz = shard_range(spec, src.data_size)
                verified = integrity.digest_matches(
                    memoryview(src.inmem_data)[s0:s0 + s_sz],
                    range_digest)
                if not verified:
                    log.error("pre-held bytes fail the stamped range "
                              "digest; not publishing", layerID=lid)
                    continue
            elif not verified and range_digest is None:
                verified = True  # CRC-only regime (no digest stamped)
            self._start_pod_collect(lid, src)

    def _pod_collect_loop(self, lid: int, n: int, total: int,
                          codec: str) -> None:
        """Drain the board into the shard gather until all ``n`` shards
        arrived (the gather fires inside the stager's worker) or the
        collect window expires — bounded: a timeout leaves the shard
        holding acked as-is and the LEADER's pod watchdog degrades the
        (layer, pod) to host-path delivery; never a wedge."""
        board = self._pod_board()
        if board is None:
            return
        key = (lid, n, codec)
        stager = self._pod_stager()
        with self._lock:
            digest = self.layer_digests.get(lid, "")
        have: set = set()
        deadline = _time.monotonic() + self.FABRIC_COLLECT_TIMEOUT
        while len(have) < n:
            snap = board.pod_wait_new(key, len(have),
                                      deadline - _time.monotonic())
            if snap is None:
                trace.count("pod.collect_timeouts")
                log.error("pod delivery degraded to host path",
                          reason="peer shards never arrived",
                          layerID=lid, have=sorted(have), pod_n=n)
                board.pod_done(key, n, who=self.node.my_id)
                with self._lock:
                    # Un-claim so a redelivery can retry the collect.
                    self._pod_collecting.discard(lid)
                return
            for rank in sorted(set(snap) - have):
                have.add(rank)
                if not stager.submit_shard(
                        lid, f"1/{n}@{rank}", snap[rank], total,
                        expected_digest=digest, codec=codec):
                    # Closed stager / conflicting geometry: the gather
                    # can never fire here — fail LOUD and fast instead
                    # of draining the board as if it had.
                    trace.count("pod.collect_timeouts")
                    log.error("pod delivery degraded to host path",
                              reason="shard rejected by the gather "
                                     "driver", layerID=lid, rank=rank)
                    board.pod_done(key, n, who=self.node.my_id)
                    with self._lock:
                        self._pod_collecting.discard(lid)
                    return
        board.pod_done(key, n, who=self.node.my_id)

    def _on_pod_gathered(self, lid: int, out, codec: str) -> None:
        """Stager hook: this layer's on-mesh gather finished.  On
        success the FULL wire-form tree becomes the holding (exactly
        what a full host-path delivery at this codec would have
        stored — staging/boot/serving reuse every existing path) and
        the dest acks the full layer; on failure the shard holding
        stands and the leader's watchdog degrades the pair, loudly."""
        with self._lock:
            pod = lid in self._pod_widths
            self._pod_collecting.discard(lid)
        if not pod:
            return  # a plain sharded-delivery gather (harness-driven)
        if out is None:
            trace.count("pod.materialize_failed")
            log.error("pod gather failed; shard holding stands (leader "
                      "degrades the pair)", layerID=lid)
            return
        # The gather already verified the stamped full wire-form digest
        # (gather_byte_shards raises on mismatch).
        self._pod_store_full_tree(lid, out, codec, verified=True)

    def _pod_store_full_tree(self, lid: int, data, codec: str,
                             verified: bool, spmd: bool = False) -> None:
        """THE pod materialization chokepoint (docs/fabric.md): both
        reconstruction paths — the stager's board gather and the SPMD
        lockstep plan — funnel here so verify/store/stage/span/ack can
        never diverge.  ``verified``: the stamped full wire-form digest
        already checked upstream; otherwise it is checked now (directly
        — the per-lid memo and the range-digest lookup both describe
        the SHARD phase, not the gathered tree).  A mismatch keeps the
        shard holding (acked long ago); the leader's watchdog degrades
        the pair — corrupt bytes never ack."""
        if not verified:
            with self._lock:
                digest = self.layer_digests.get(lid, "")
            if digest:
                ok, dt, got = integrity.digest_check(
                    memoryview(data), digest)
                if ok is False:
                    trace.count("pod.materialize_failed")
                    log.error("pod-gathered tree failed the stamped "
                              "full wire digest; keeping the shard "
                              "holding", layerID=lid, expected=digest,
                              got=got)
                    return
                trace.add_phase("integrity_digest", dt)
        with self._lock:
            src = self.layers.get(lid)
            if src is not None and not src.meta.shard:
                return  # already full (host-path redelivery won)
            src = self.layers[lid] = LayerSrc(
                inmem_data=bytearray(data), data_size=len(data),
                meta=LayerMeta(location=LayerLocation.INMEM,
                               codec=codec))
            # Memoize the verdict so re-acks and stamp re-checks never
            # re-hash the full tree.
            if self.layer_digests.get(lid):
                self._digest_ok.add(lid)
        if codec:
            self._count_codec_delivery(lid, len(data), codec)
        loc = self._stage_to_hbm(lid, src)
        self._boot_stream_submit(lid, src)  # dedupes if pre-staged
        telemetry.span_event(
            telemetry.span_id(self.node.my_id, lid), "staged",
            node=self.node.my_id, dest=self.node.my_id, layer=lid,
            shard="", codec=codec)
        trace.count("pod.trees_materialized")
        log.info("pod delivery materialized full tree", layerID=lid,
                 bytes=len(data), codec=codec or None,
                 **({"spmd": True} if spmd else {}))
        self._send_ack(lid, loc)

    def close(self) -> None:
        self._closed_evt.set()
        self._metrics_stop.set()
        self.heartbeat.stop()
        self.loop.stop()
        if self._boot_stager is not None:
            self._boot_stager.close()
        if self._pod_stager_obj is not None:
            self._pod_stager_obj.close()
        with self._lock:
            window = self._plan_window
        if window is not None:
            # Let in-flight plans retire (their acks may still matter to
            # a live leader), then stop the retirement thread.
            window.drain(timeout=5.0)
            window.close()

    def _stage_to_hbm(self, layer_id, src, ingest=None) -> "LayerLocation":
        """Move a completed layer host→HBM when enabled; returns the
        location to ack with.  jax is imported lazily so host-only nodes
        never pay for it.  The HBM transition is check-and-marked under
        ``self._lock``: exactly one caller stages; a concurrent re-plan
        duplicate waits for that staging instead of double-allocating the
        layer on device.  ``ingest``: a completed incremental
        ``ShardedLayerIngest`` whose finalize collective replaces the bulk
        host→device transfer."""
        if not self.stage_hbm:
            return LayerLocation.INMEM
        with self._lock:
            if src.meta.location == LayerLocation.HBM:
                return LayerLocation.HBM  # a re-plan duplicate: already staged
            ev = self._hbm_staging.get(layer_id)
            if ev is not None:
                in_progress = ev
            else:
                in_progress = None
                ev = self._hbm_staging[layer_id] = threading.Event()
        if in_progress is not None:
            in_progress.wait()
            with self._lock:
                return src.meta.location
        try:
            t0 = _time.monotonic()
            self._stage_layer_device(layer_id, src, ingest)
            dt = _time.monotonic() - t0
            log.info("layer staged to HBM", layerID=layer_id,
                     via="incremental ingest" if ingest is not None else "bulk",
                     stage_ms=round(dt * 1000, 1),
                     gbps=round(src.data_size / max(dt, 1e-9) / 1e9, 3))
            return LayerLocation.HBM
        except Exception as e:  # noqa: BLE001 — delivery beats staging
            log.error("HBM staging failed; acking host RAM",
                      layerID=layer_id, err=repr(e))
            return LayerLocation.INMEM
        finally:
            ev.set()
            with self._lock:
                self._hbm_staging.pop(layer_id, None)

    def _stage_layer_device(self, layer_id, src, ingest=None) -> None:
        """The actual device landing (called once per layer, under the
        staging guard).  Priority: finalize an incremental ingest (the
        bytes are already on-mesh — one ICI all-gather remains); else a
        one-shot sharded ingest onto the stage's devices; else the plain
        single-device mover."""
        if ingest is not None:
            try:
                arr = ingest.finalize()
                arr.block_until_ready()
                with self._lock:
                    src.device_array = arr
                    src.meta.location = LayerLocation.HBM
                return
            except Exception as e:  # noqa: BLE001 — fall back to bulk path
                log.error("ingest finalize failed; bulk staging instead",
                          layerID=layer_id, err=repr(e))
        if (self.placement is not None
                and layer_id in self.placement.layer_to_stage):
            from ..parallel.ingest import ingest_bytes

            data = (src.inmem_data if src.inmem_data is not None
                    else src.read_bytes())
            arr = ingest_bytes(data, self.placement.devices_for_layer(layer_id))
            arr.block_until_ready()
            with self._lock:
                src.device_array = arr
                src.meta.location = LayerLocation.HBM
            return
        self._mover.stage(src)

    def handle_layer(self, msg: LayerMsg) -> None:
        """Store to RAM, ack the leader (node.go:1354-1384).  A re-plan
        duplicate keeps the existing (possibly already HBM-staged) entry —
        overwriting it would orphan the staged device array and leave the
        node acking HBM for a host-only copy.

        Integrity gate: a whole-layer frame verifies against the
        leader-stamped digest (the stamp names its own algorithm)
        BEFORE it is stored or acked — a
        mismatch (per-fragment CRC passed, so the SOURCE's bytes are
        bad) drops the frame and NACKs the sender for a retransmit."""
        with self._lock:
            src = self.layers.get(msg.layer_id)
        stored = False
        if src is None:
            fresh = msg.layer_src
            if 0 < fresh.data_size < msg.total_size:
                # A byte-range fragment (a shard-target send, or a
                # range retransmit) at a whole-layer receiver: this
                # class has no interval reassembly — storing it as the
                # layer would ack a buffer full of holes.  Flow-capable
                # receivers (mode 3's class) override this handler.
                log.error("byte-range fragment at a whole-layer "
                          "receiver; dropped (sharded targets need a "
                          "flow-capable receiver)", layerID=msg.layer_id,
                          offset=fresh.offset, size=fresh.data_size,
                          total=msg.total_size)
                return
            # Wire-codec identity (docs/codec.md): the leader's stamp is
            # authoritative; the frame's advisory tag is the fallback
            # when no stamp arrived (digests disabled) — encoded bytes
            # must never be stored as a raw holding.
            with self._lock:
                codec = self._layer_codecs.get(msg.layer_id, "")
            codec = codec or msg.codec
            # Digest-gate whole-layer frames only, and only when a
            # digest is stamped — no byte copy on the unstamped path.
            # For a codec transfer the stamped digest is the digest of
            # the ENCODED bytes — exactly what arrived.
            if (self._expected_digest(msg.layer_id) is not None
                    and fresh.data_size == msg.total_size):
                data = (memoryview(fresh.inmem_data)
                        if fresh.inmem_data is not None
                        else memoryview(fresh.read_bytes()))
                if not self._verify_layer_digest(msg.layer_id, data,
                                                 codec=codec):
                    # Budgeted like every digest recovery: a corrupt
                    # SOURCE re-serving the same bad bytes must go
                    # loud-and-quiet, not NACK-ping-pong forever.
                    if self._bump_digest_retry(msg.layer_id):
                        self._send_nack(msg.src_id, msg.layer_id, 0,
                                        msg.total_size, msg.total_size,
                                        "digest")
                    return
            delta_wire = 0
            if delta_base_digest(codec):
                # Content-delta frame (docs/codec.md): the verified
                # stream reconstructs to canonical bytes BEFORE the
                # store — the delta form itself must never be held,
                # staged, or acked.
                data = (memoryview(fresh.inmem_data)
                        if fresh.inmem_data is not None
                        else memoryview(fresh.read_bytes()))
                raw = self._delta_reconstruct_bytes(msg.layer_id, data,
                                                    codec)
                if raw is None:
                    if self._bump_digest_retry(msg.layer_id):
                        self._send_nack(msg.src_id, msg.layer_id, 0,
                                        msg.total_size, msg.total_size,
                                        "digest")
                    return
                delta_wire = fresh.data_size
                self._count_codec_delivery(msg.layer_id, delta_wire,
                                           codec)
                fresh = LayerSrc(
                    inmem_data=bytearray(raw), data_size=len(raw),
                    meta=LayerMeta(location=LayerLocation.INMEM))
                fresh.offset = 0
                codec = ""
            with self._lock:
                src = self.layers.get(msg.layer_id)
                if src is None:
                    src = fresh
                    src.meta = LayerMeta(location=LayerLocation.INMEM,
                                         codec=codec)
                    src.offset = 0
                    self.layers[msg.layer_id] = src
                    stored = True
            if stored:
                # Flight recorder: bytes COMMITTED to the store (a
                # re-plan duplicate records nothing), so per-link
                # delivered totals reconcile byte-exactly against the
                # goal state in the run report.
                telemetry.link_add(msg.src_id, self.node.my_id,
                                   job=msg.job_id,
                                   delivered_bytes=(delta_wire
                                                    or src.data_size))
                if delta_wire:
                    self._note_delta_reconstructed(
                        msg.layer_id, delta_wire, src.data_size)
                if codec:
                    self._count_codec_delivery(msg.layer_id,
                                               src.data_size, codec)
                # Pair-lifecycle span (docs/observability.md): a
                # whole-layer frame is first byte AND wire completion
                # in one event pair (and the digest gate above already
                # passed — the verify cost sits inside the frame walk).
                span = msg.span_id or telemetry.span_id(
                    self.node.my_id, msg.layer_id)
                telemetry.span_event(span, "first_byte",
                                     node=self.node.my_id,
                                     src=msg.src_id, dest=self.node.my_id,
                                     layer=msg.layer_id, job=msg.job_id,
                                     parent=msg.span_parent)
                telemetry.span_event(span, "wire_complete",
                                     node=self.node.my_id,
                                     src=msg.src_id, dest=self.node.my_id,
                                     layer=msg.layer_id, job=msg.job_id,
                                     bytes=src.data_size,
                                     parent=msg.span_parent)
        log.debug("saved layer in memory", layerID=msg.layer_id)
        loc = self._stage_to_hbm(msg.layer_id, src)
        # Streamed boot staging: this layer's decode + device placement
        # starts NOW, overlapping the remaining layers' transfers.
        self._boot_stream_submit(msg.layer_id, src)
        if stored:
            telemetry.span_event(
                telemetry.span_id(self.node.my_id, msg.layer_id),
                "staged", node=self.node.my_id, dest=self.node.my_id,
                layer=msg.layer_id)
        self._send_ack(msg.layer_id, loc)
        # The committed layer may be the donor a stamped-but-missing
        # layer was waiting for (stamp-before-donor race).
        self._resolve_pending_for_layer(msg.layer_id)

    # --------------------------------------------------- device-fabric plane

    def handle_device_plan(self, msg: DevicePlanMsg) -> None:
        """Serve one pod-fabric transfer command (``parallel/fabric.py``):
        contribute my planned byte ranges (seeder half, inline on the
        handler pool), then — when the plan is addressed to me — ingest
        every contribution over the device fabric on a dedicated thread.
        Dedicated because the ingest *waits* on other nodes' contributions:
        parked pool workers across many concurrent plans could otherwise
        starve the very contribution handlers they wait for."""
        if self._fence_stale(msg):
            return
        if self.fabric is None or self.placement is None:
            log.error("device plan but no fabric wired", plan=msg.plan_id)
            return
        if self._spmd:
            self._handle_spmd_plan(msg)
            return
        # Opportunistic GC: plans whose dest died before collecting would
        # otherwise pin full-layer device buffers forever.
        self.fabric.gc()
        contribute_device_plan(self.node, self.layers, self._lock,
                               self.fabric, self.placement, msg)
        if msg.dest_id == self.node.my_id:
            if self._batch_enqueue(msg):
                return  # a batch thread finishes the whole group
            threading.Thread(
                target=self._receive_device_plan, args=(msg,), daemon=True,
                name="fabric-recv",
            ).start()

    def _report_plan_gap(self, missing) -> None:
        """SpmdFabric ``on_gap`` hook: ask the leader to re-send the
        plans this process never received (executor thread; one call per
        gap_timeout window)."""
        log.warn("requesting re-send of missing spmd plans",
                 seqs=list(missing))
        try:
            self.node.transport.send(
                self.node.leader_id,
                PlanResendReqMsg(self.node.my_id, list(missing)),
            )
        except (OSError, KeyError) as e:
            log.error("plan re-send request failed", err=repr(e))

    def _handle_spmd_plan(self, msg: DevicePlanMsg) -> None:
        """Multi-controller fabric (``parallel/spmd_fabric.py``): enqueue
        the plan on this process's lockstep executor; when it is addressed
        to me, await the collective's result on a dedicated thread (the
        handler pool must stay free to enqueue later plans — the executor
        can only reach mine after running everything before it).

        (Lost-plan fault injection for the gap-recovery tests lives in
        ``transport/faults.FaultyTransport`` now — the CLI's
        ``-test-drop-plan-seqs`` wraps the transport; this handler only
        ever sees plans that "arrived".)"""
        mine = (msg.dest_id == self.node.my_id
                or self.node.my_id in (msg.pod or ()))
        try:
            res = self.fabric.submit(msg)
        except Exception as e:  # noqa: BLE001 — closed/duplicate races
            log.error("spmd fabric submit failed", plan=msg.plan_id,
                      err=repr(e))
            if mine and msg.layout:
                self._request_replan()
            return
        if not mine or not msg.layout:
            return
        threading.Thread(
            target=self._await_spmd_plan, args=(msg, res), daemon=True,
            name="spmd-await",
        ).start()

    def _await_spmd_plan(self, msg: DevicePlanMsg, res) -> None:
        from ..parallel.spmd_fabric import PlanFailed

        try:
            # Progress-aware: a deep plan queue (large startup) extends
            # the wait as long as the executor keeps retiring seqs.
            arr = self.fabric.wait_result(res)
        except PlanFailed as e:
            log.error("spmd fabric plan failed for dest; requesting "
                      "re-plan", plan=msg.plan_id, layerID=msg.layer_id,
                      err=repr(e))
            self._request_replan()
            return
        if arr is None:
            log.error("spmd fabric plan yielded no layer; requesting "
                      "re-plan", plan=msg.plan_id, layerID=msg.layer_id)
            self._request_replan()
            return
        if msg.pod:
            self._spmd_pod_store(msg, arr)
            return
        self._fabric_store(msg.layer_id, msg.total_size, device_arr=arr)
        # A duplicate plan for an already-held layer no-ops in the store:
        # ack whatever location the layer ACTUALLY has (a host-path copy
        # stays INMEM; claiming HBM would corrupt the leader's status).
        with self._lock:
            loc = self.layers[msg.layer_id].meta.location
        log.info("layer landed over device fabric", layerID=msg.layer_id,
                 plan=msg.plan_id, total_bytes=msg.total_size, spmd=True)
        self._send_ack(msg.layer_id, loc)

    def _spmd_pod_store(self, msg: DevicePlanMsg, arr) -> None:
        """A pod reconstruction plan's gathered tree landed on this
        member (docs/fabric.md): read the wire-form bytes back and run
        the shared verify/store/stage/ack chokepoint
        (``_pod_store_full_tree``)."""
        import numpy as _np

        lid = msg.layer_id
        try:
            import jax as _jax

            data = _np.asarray(
                _jax.device_get(arr)).tobytes()[:msg.total_size]
        except Exception as e:  # noqa: BLE001 — loud, never wedge
            log.error("pod gather readback failed", layerID=lid,
                      err=repr(e))
            return
        with self._lock:
            codec = self._layer_codecs.get(lid, "")
        self._pod_store_full_tree(lid, data, codec, verified=False,
                                  spmd=True)

    def _local_coverage(self, layer_id):
        """Byte ranges of an in-progress layer this node already holds
        (checkpoint-restored partials, mode 3): a resumed fabric plan ships
        only the gaps, so the ingest is seeded with these first.  The base
        receiver holds none."""
        return []

    def _fabric_store(self, layer_id, total: int, device_arr=None,
                      host_buf=None) -> None:
        """Record a fabric-delivered layer — HBM-resident when the ingest
        succeeded (the terminal state the Assignment prescribes; readers
        needing bytes pull them from the device array), else the
        host-assembled fallback buffer (delivery beats staging)."""
        with self._lock:
            if layer_id not in self.layers:
                self.layers[layer_id] = LayerSrc(
                    inmem_data=host_buf,
                    data_size=total,
                    meta=LayerMeta(location=LayerLocation.HBM
                                   if device_arr is not None
                                   else LayerLocation.INMEM),
                    device_array=device_arr,
                )
            src = self.layers[layer_id]
        # Fabric deliveries stream into the boot too: the landed layer's
        # decode overlaps the remaining plans.
        self._boot_stream_submit(layer_id, src)

    def _receive_device_plan(self, msg: DevicePlanMsg) -> None:
        """The dest half: pull every contribution into my stage's shard
        buffers as it arrives (device→device — ICI on real hardware),
        gather, store, ack.

        Liveness: a device-side failure (allocation, write, finalize) must
        not hang the run — the dest is alive and heartbeating, so the
        leader would never re-plan for it on its own.  On ingest failure
        the layer is assembled on host — already-written bytes salvaged
        from the shard buffers, later fragments kept as host copies — and
        acked INMEM, the same delivery-beats-staging fallback the host
        receive path has.  When even that can't complete (collect timeout
        from a dead seeder, or a device fault so deep the salvage read
        fails too), the dest RE-ANNOUNCES: the leader's re-announce path
        re-plans its missing layers, so the transfer is retried instead
        of stranded."""
        res = self._collect_plan(msg)
        if res is None:
            return
        kind, payload = res
        if kind == "ingest":
            self._finalize_one(msg, *payload)
        else:
            self._fabric_host_assemble(msg, *payload)

    def _fabric_window(self):
        """The dest's shared in-flight window: finalize collectives from
        successive plans stay dispatched together (upload and collective
        phases overlap across plans) instead of round-tripping per plan;
        acks fire at retirement, once the device work really finished."""
        with self._lock:
            if self._plan_window is None:
                from ..parallel.fabric import PlanWindow

                self._plan_window = PlanWindow()
            return self._plan_window

    def _batch_enqueue(self, msg: DevicePlanMsg) -> bool:
        """Admit a batch-stamped plan into its accumulation group; when
        the group is complete (or ``FABRIC_BATCH_WAIT`` expires with
        members missing — a participant's dispatch failed), one thread
        finishes the WHOLE group as a single batched gather.  Returns
        False for unbatched plans (the solo path handles them)."""
        if self._spmd or msg.batch_n <= 1 or not msg.batch_id:
            return False
        timer = None
        msgs = None
        with self._lock:
            rec = self._plan_batches.get(msg.batch_id)
            if rec is not None and rec["fired"]:
                # Late member of an already-processed batch: straight to
                # the solo path, NOT another batch wait.  Fired records
                # stay as tombstones precisely for this check.
                return False
            if rec is None:
                rec = self._plan_batches[msg.batch_id] = {
                    "msgs": [], "fired": False, "timer": None}
                timer = threading.Timer(
                    self.FABRIC_BATCH_WAIT, self._flush_batch,
                    args=(msg.batch_id,))
                timer.daemon = True
                rec["timer"] = timer
            rec["msgs"].append(msg)
            if len(rec["msgs"]) >= msg.batch_n:
                rec["fired"] = True
                msgs = rec["msgs"]
                rec["msgs"] = []  # tombstone keeps no message refs
                if rec["timer"] is not None:
                    rec["timer"].cancel()
                    rec["timer"] = None
                self._prune_batches_locked()
        if msgs is not None:
            threading.Thread(
                target=self._receive_device_batch, args=(msgs,), daemon=True,
                name="fabric-batch",
            ).start()
        elif timer is not None:
            timer.start()
        return True

    def _prune_batches_locked(self) -> None:
        """Bound the tombstone map (fired batch records are kept so late
        members skip the batch wait); oldest fired records drop first.
        Caller holds ``self._lock``."""
        fired = [b for b, r in self._plan_batches.items() if r["fired"]]
        for b in fired[:max(0, len(fired) - 256)]:
            del self._plan_batches[b]

    def _flush_batch(self, batch_id: str) -> None:
        """Batch-wait expiry: some member plans never arrived (their
        dispatch failed and went host-path) — process what did, so the
        present plans aren't stranded behind the absent ones."""
        with self._lock:
            rec = self._plan_batches.get(batch_id)
            if rec is None or rec["fired"]:
                return
            rec["fired"] = True
            msgs = rec["msgs"]
            rec["msgs"] = []
            rec["timer"] = None
            self._prune_batches_locked()
        log.warn("fabric plan batch incomplete; processing present plans",
                 batch=batch_id, got=len(msgs))
        threading.Thread(
            target=self._receive_device_batch, args=(msgs,), daemon=True,
            name="fabric-batch",
        ).start()

    def _receive_device_batch(self, msgs) -> None:
        """Finish a batch of same-size plans with ONE batched gather
        (``parallel.ingest.finalize_many``): collect each plan's
        contributions into its own ingest, then a single collective
        replicates every layer — per-plan dispatch latency amortizes
        over the batch.  Any plan that can't ride the batch (duplicate,
        dead ingest, tiling mismatch) takes its usual solo path."""
        # Collect members CONCURRENTLY (matching the solo path's
        # per-plan threads): one member whose contributions never come
        # must cost the batch one FABRIC_COLLECT_TIMEOUT, not one per
        # member — the healthy members' collects complete in parallel.
        ordered = sorted(msgs, key=lambda m: m.layer_id)
        results: Dict[int, object] = {}

        def collect_one(i, m):
            results[i] = self._collect_plan(m)

        threads = [threading.Thread(target=collect_one, args=(i, m),
                                    daemon=True, name=f"fabric-collect-{i}")
                   for i, m in enumerate(ordered)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ready = []  # (msg, ingest, host_frags, local, upload_s)
        for i, msg in enumerate(ordered):
            res = results.get(i)
            if res is None:
                continue
            kind, payload = res
            if kind == "ingest":
                ready.append((msg,) + payload)
            else:
                self._fabric_host_assemble(msg, *payload)
        if not ready:
            return
        arrs = None
        if len(ready) > 1:
            from ..parallel.ingest import finalize_many

            try:
                arrs = finalize_many([ing for _, ing, _, _, _ in ready])
            except Exception as e:  # noqa: BLE001 — solo finalize still works
                log.warn("batched finalize unavailable; per-plan gathers",
                         batch=msgs[0].batch_id, err=repr(e))
        if arrs is not None:
            for (msg, ing, host_frags, local, up_s), arr in zip(ready, arrs):
                self._submit_fabric_result(msg, ing, host_frags, local,
                                           arr, up_s, batched=len(ready))
        else:
            for msg, ing, host_frags, local, up_s in ready:
                self._finalize_one(msg, ing, host_frags, local, up_s)

    def _collect_plan(self, msg: DevicePlanMsg):
        """Collect one plan's contributions into a fresh ingest.

        Returns ``None`` when the plan is fully handled here (a re-plan
        duplicate drained + re-acked, or a collect failure that already
        requested a re-plan); ``("ingest", (ingest, host_frags, local,
        upload_s))`` when the ingest holds every contribution; or
        ``("host", (local, ingest, host_frags))`` when device staging
        died and the caller must assemble on host."""
        with self._lock:
            existing = self.layers.get(msg.layer_id)
        if existing is not None:
            # A re-plan duplicate of a delivered layer: drain the plan's
            # contributions (seeders may publish AFTER this discard would
            # run — an immediate discard leaves their device buffers
            # pinned in the registry) and re-ack (the leader missed our
            # ack).  The drain is bounded and off the handler pool.
            try:
                for _ in self.fabric.collect(
                    msg.plan_id, len(msg.layout),
                    timeout=min(30.0, self.FABRIC_COLLECT_TIMEOUT),
                ):
                    pass
            except TimeoutError:
                pass
            finally:
                self.fabric.discard(msg.plan_id)
            self._send_ack(msg.layer_id, existing.meta.location)
            return None

        local = self._local_coverage(msg.layer_id)
        ingest = None
        try:
            from ..parallel.ingest import ShardedLayerIngest

            devices = self.placement.devices_for_node(self.node.my_id)
            ingest = ShardedLayerIngest(msg.total_size, devices)
            for off, data in local:
                ingest.write(off, data)
        except Exception as e:  # noqa: BLE001 — fall through to host path
            log.error("fabric ingest unavailable; will assemble on host",
                      layerID=msg.layer_id, err=repr(e))
            ingest = None
        # Fragments are NOT retained while the ingest is healthy (a
        # full-layer extra pin of seeder HBM); the fallback recovers
        # already-written bytes from the dest's own shard buffers
        # (ingest.salvage) and keeps HOST copies only of fragments that
        # arrive after a failure.
        ingest_alive = ingest is not None
        host_frags: list = []
        upload_s = 0.0
        try:
            try:
                for off, arr in self.fabric.collect(
                    msg.plan_id, len(msg.layout),
                    timeout=self.FABRIC_COLLECT_TIMEOUT,
                ):
                    if ingest_alive:
                        try:
                            t_up = _time.monotonic()
                            ingest.write(off, arr)
                            upload_s += _time.monotonic() - t_up
                            continue
                        except Exception as e:  # noqa: BLE001
                            log.error("fabric ingest write failed; will "
                                      "assemble on host",
                                      layerID=msg.layer_id, err=repr(e))
                            ingest_alive = False
                    import jax
                    import numpy as np

                    host_frags.append(
                        (off, np.asarray(jax.device_get(arr)).tobytes())
                    )
            finally:
                self.fabric.discard(msg.plan_id)
        except Exception as e:  # noqa: BLE001 — bytes missing: can't deliver
            log.error("fabric collect failed; requesting re-plan",
                      layerID=msg.layer_id, plan=msg.plan_id, err=repr(e))
            self._request_replan()
            return None
        if upload_s:
            from ..utils import trace as _trace

            _trace.add_phase("upload", upload_s)
        if ingest_alive:
            return "ingest", (ingest, host_frags, local, upload_s)
        return "host", (local, ingest, host_frags)

    def _finalize_one(self, msg: DevicePlanMsg, ingest, host_frags, local,
                      upload_s: float = 0.0) -> None:
        """Dispatch one plan's finalize gather and hand it to the shared
        in-flight window (the ack fires at retirement)."""
        try:
            device_arr = ingest.finalize()
        except Exception as e:  # noqa: BLE001
            log.error("fabric finalize failed; assembling on host",
                      layerID=msg.layer_id, err=repr(e))
            self._fabric_host_assemble(msg, local, ingest, host_frags)
            return
        self._submit_fabric_result(msg, ingest, host_frags, local,
                                   device_arr, upload_s, batched=1)

    def _submit_fabric_result(self, msg, ingest, host_frags, local,
                              device_arr, upload_s: float,
                              batched: int) -> None:
        """Queue a dispatched finalize on the in-flight window: the next
        plan's staging overlaps this collective; store + phase log + ack
        happen at retirement (bytes proven on device), and a device-side
        failure falls back to the host assembly path."""

        def on_ready(arr, collective_s):
            self._fabric_store(msg.layer_id, msg.total_size, device_arr=arr)
            log.info("layer landed over device fabric", layerID=msg.layer_id,
                     plan=msg.plan_id, total_bytes=msg.total_size,
                     upload_ms=round(upload_s * 1000, 1),
                     collective_ms=round(collective_s * 1000, 1),
                     batched=batched)
            self._send_ack(msg.layer_id, LayerLocation.HBM)

        def on_error(e):
            log.error("fabric collective failed; assembling on host",
                      layerID=msg.layer_id, plan=msg.plan_id, err=repr(e))
            self._fabric_host_assemble(msg, local, ingest, host_frags)

        try:
            self._fabric_window().submit(
                msg.plan_id, device_arr, msg.total_size, on_ready, on_error)
        except Exception as e:  # noqa: BLE001 — window closed: sync path
            log.error("plan window rejected submit; blocking inline",
                      plan=msg.plan_id, err=repr(e))
            try:
                device_arr.block_until_ready()
            except Exception as e2:  # noqa: BLE001
                on_error(e2)
                return
            on_ready(device_arr, 0.0)

    def _fabric_host_assemble(self, msg: DevicePlanMsg, local, ingest,
                              host_frags) -> None:
        """Delivery-beats-staging fallback: assemble the layer on host
        from checkpointed local bytes + salvaged shard buffers + host
        fragment copies, ack INMEM — or re-announce when even that can't
        complete."""
        buf = bytearray(msg.total_size)
        covered: list = []

        def place(off, data):
            nonlocal covered
            buf[off : off + len(data)] = data
            covered = intervals.insert(covered, off, off + len(data))

        for off, data in local:
            place(off, data)
        if ingest is not None:
            try:
                for off, data in ingest.salvage():
                    place(off, data)
            except Exception as e:  # noqa: BLE001
                log.error("shard-buffer salvage failed",
                          layerID=msg.layer_id, err=repr(e))
        for off, data in host_frags:
            place(off, data)
        if intervals.covered(covered) < msg.total_size:
            log.error("host fallback incomplete; requesting re-plan",
                      layerID=msg.layer_id, plan=msg.plan_id,
                      have=intervals.covered(covered),
                      total=msg.total_size)
            self._request_replan()
            return
        if not self._verify_layer_digest(msg.layer_id, memoryview(buf)):
            # Salvaged/host-copied bytes failed the end-to-end digest:
            # never store or ack them — re-plan re-fetches the layer.
            log.error("host-assembled fabric layer failed digest; "
                      "requesting re-plan", layerID=msg.layer_id,
                      plan=msg.plan_id)
            self._request_replan()
            return
        self._fabric_store(msg.layer_id, msg.total_size, host_buf=buf)
        log.warn("layer assembled on host after fabric failure",
                 layerID=msg.layer_id, plan=msg.plan_id)
        self._send_ack(msg.layer_id, LayerLocation.INMEM)

    def _request_replan(self) -> None:
        """A delivery this node could not complete (failed fabric plan)
        would otherwise be stranded forever — the node is alive and
        heartbeating, so the failure detector never fires for it.
        Re-announcing is the recovery channel: the leader treats a known
        node's announce as authoritative inventory and re-plans every
        still-missing layer (leader.handle_announce)."""
        try:
            self.announce()
        except (OSError, KeyError) as e:
            log.error("re-announce for re-plan failed", err=repr(e))

    def _count_codec_delivery(self, layer_id, wire_bytes: int,
                              codec: str) -> None:
        """Account one quantized delivery's wire-vs-decoded bytes
        (docs/codec.md): the telemetry link table reconciles against
        ENCODED wire bytes, and these counters carry the decoded side
        so the run report shows both columns without conflating them."""
        trace.count("codec.wire_deliveries")
        trace.count("codec.wire_bytes", wire_bytes)
        if self.codec_plane is not None:
            dec = self.codec_plane.decoded_nbytes(layer_id)
            if dec:
                trace.count("codec.decoded_bytes", dec)

    def _send_ack(self, layer_id, loc, shard: str = "") -> None:
        """THE ack chokepoint: every completion path (whole-layer
        frames, flow reassembly, fabric delivery, content resolve,
        re-acks) funnels here so the version tag (docs/swap.md) is
        stamped exactly once — onto the stored holding (announce after
        a restart keeps it) and onto the wire ack (the leader's swap
        fence counts versioned acks) — and the live-swap controller
        sees every completed layer.  The wire ack also carries the
        holding's CODEC form (docs/codec.md): the leader records it,
        so a quantized copy can never satisfy — or be planned as a
        source for — a raw pair."""
        version = self._layer_versions.get(layer_id, "")
        with self._lock:
            src = self.layers.get(layer_id)
            if version and src is not None:
                src.meta.version = version
            codec = src.meta.codec if src is not None else ""
        self._send_to_leader(AckMsg(self.node.my_id, layer_id, loc,
                                    shard=shard, version=version,
                                    codec=codec,
                                    span_id=telemetry.span_id(
                                        self.node.my_id, layer_id)))
        if self.swap is not None and version:
            self.swap.on_layer(layer_id)
        hook = self.on_layer_complete
        if hook is not None:
            # Sub-leader fan-out trigger (docs/hierarchy.md): advisory —
            # a hook failure must never break the ack path.
            try:
                hook(layer_id)
            except Exception as e:  # noqa: BLE001
                log.error("layer-complete hook failed", layerID=layer_id,
                          err=repr(e))

    def handle_generate_req(self, msg: GenerateReqMsg) -> None:
        """Serve an inference request from this node's RESIDENT booted
        params — the startup hook's engine, reachable over the same
        transport that delivered its weights.  Full boots only (a stage
        boot alone can't produce logits; pod serving is the ServeMsg
        lockstep path).  Every outcome ANSWERS — the requester's timeout
        is for lost messages, not policy.  ALWAYS decodes on its own
        daemon thread: the handler pool has one slot, and a decode (or
        a boot wait) parked there would serialize every other control
        message — re-sent Startup re-answers, ServeMsg, concurrent
        generate requests — for the full decode duration.  Concurrent
        decodes are bounded (SERVE_MAX_CONCURRENT): each holds a KV
        cache, so an unauthenticated flood must hit an immediate
        "busy" refusal, not an unbounded thread/HBM pile-up."""
        with self._lock:
            if self._serve_active >= self.SERVE_MAX_CONCURRENT:
                busy = True
            else:
                busy = False
                self._serve_active += 1
        if busy:
            try:
                self.node.transport.send(
                    msg.src_id,
                    GenerateRespMsg(self.node.my_id, msg.req_id, [],
                                    f"busy: {self.SERVE_MAX_CONCURRENT} "
                                    "decodes already in flight"),
                )
            except (OSError, KeyError, ConnectionError) as e:
                log.error("busy refusal send failed",
                          requester=msg.src_id, req=msg.req_id, err=repr(e))
            return

        def _run():
            try:
                self._serve_generate_req(msg)
            finally:
                with self._lock:
                    self._serve_active -= 1

        threading.Thread(
            target=_run, daemon=True,
            name=f"genreq-{self.node.my_id}-{msg.req_id}",
        ).start()

    def _serve_generate_req(self, msg: GenerateReqMsg) -> None:
        t0 = _time.monotonic()
        me = self.node.my_id

        def reply(tokens=None, error=""):
            # Telemetry (docs/rollout.md): per-REPLICA request latency
            # and failure counters — the metric names carry the node id
            # because co-resident nodes share one registry, and the SLO
            # guard needs per-replica p99, not a process blur.  The
            # reply send is INSIDE the timed window on purpose: a
            # replica whose answers crawl out a congested NIC is slow
            # as far as its users (and its SLO) are concerned.
            try:
                self.node.transport.send(
                    msg.src_id,
                    GenerateRespMsg(self.node.my_id, msg.req_id,
                                    tokens or [], error),
                )
            except (OSError, KeyError, ConnectionError) as e:
                log.error("generate response send failed",
                          requester=msg.src_id, req=msg.req_id, err=repr(e))
            telemetry.observe_ms(f"serve.latency_ms.n{me}",
                                 (_time.monotonic() - t0) * 1000.0)
            telemetry.count(f"serve.requests.n{me}")
            if error:
                telemetry.count(f"serve.failures.n{me}")

        if self.boot_cfg is None:
            reply(error="no booted model at this node (no boot config)")
            return
        # Requests can race the boot; wait for it, bounded (a physical-
        # size boot compiles + first-forwards in seconds — minutes only
        # when the precompile overlap was lost).
        if not self._boot_finished.wait(timeout=300.0):
            reply(error="no booted model at this node "
                        "(boot still in flight)")
            return
        res = self.boot_result
        if res is None or res.kind != "full" or res.params is None:
            reply(error="no booted model at this node "
                        f"(kind={getattr(res, 'kind', None)})")
            return
        cfg = self.boot_cfg
        if msg.max_new <= 0:
            reply(error=f"max_new must be positive, got {msg.max_new}")
            return
        if msg.max_new > self.SERVE_MAX_NEW:
            reply(error=f"max_new {msg.max_new} exceeds this node's serve "
                        f"limit {self.SERVE_MAX_NEW}")
            return
        if not msg.prompt:
            reply(error="empty prompt")
            return
        if len(msg.prompt) > self.SERVE_MAX_PROMPT:
            reply(error=f"prompt length {len(msg.prompt)} exceeds this "
                        f"node's serve limit {self.SERVE_MAX_PROMPT}")
            return
        bad = [t for t in msg.prompt if t < 0 or t >= cfg.vocab]
        if bad:
            reply(error=f"prompt tokens outside vocab [0, {cfg.vocab}): "
                        f"{bad[:8]}")
            return
        import math

        # NOT `< 0`: NaN compares False both ways and would reach the
        # sampler keyless (garbage tokens as a "success"), and NaN/inf
        # also defeat the decode-program cache (NaN != NaN) — re-jitting
        # per request.
        if not (math.isfinite(msg.temperature) and msg.temperature >= 0):
            reply(error="temperature must be finite and >= 0, "
                        f"got {msg.temperature}")
            return
        try:
            import jax
            import jax.numpy as jnp

            from ..models.generate import (
                ensure_uniform_version,
                generate,
                generate_stepwise,
            )

            temp = float(msg.temperature)
            prompt_arr = jnp.asarray([list(msg.prompt)], jnp.int32)
            prng = jax.random.key(int(msg.seed)) if temp > 0 else None
            if os.environ.get("DLD_TOKEN_FLIP", "0") == "1":
                # Per-TOKEN flip granularity (docs/rollout.md): re-read
                # the serving tree before every decode step, so an
                # in-flight generation picks a freshly committed
                # version up at the NEXT token instead of finishing a
                # long request on the old one.  Guarded per step: the
                # tree's blob-version map must be uniform, or the step
                # refuses (a mixed tree can't happen through the
                # atomic flip — this is the invariant made executable).
                def params_fn():
                    with self._lock:
                        cur = self.boot_result
                        version = self.serving_version
                        tree = dict(self._serving_tree_versions)
                    ensure_uniform_version(tree, version)
                    return cur.params, version

                toks = generate_stepwise(
                    params_fn, prompt_arr, cfg, int(msg.max_new),
                    temperature=temp, key=prng)
            else:
                toks = generate(
                    res.params, prompt_arr, cfg, int(msg.max_new),
                    temperature=temp, key=prng)
            out = [int(t) for t in jax.device_get(toks)[0]]
        except Exception as e:  # noqa: BLE001 — must answer, not vanish
            log.error("generation request failed", requester=msg.src_id,
                      req=msg.req_id, err=repr(e))
            reply(error=f"decode failed: {e!r}")
            return
        dt = _time.monotonic() - t0
        log.info("served generation request", requester=msg.src_id,
                 req=msg.req_id, prompt_tokens=len(msg.prompt),
                 new_tokens=len(out), decode_ms=round(dt * 1000, 1),
                 tokens_per_s=round(len(out) / max(dt, 1e-9), 1))
        reply(tokens=out)

    # ---------------------------------------------- zero-downtime swap

    def handle_swap_commit(self, msg: SwapCommitMsg) -> None:
        """The live-swap control channel (docs/swap.md): prepare
        notices, the epoch-fenced commit flip, and aborts — all routed
        to the SwapController.  Confirm/query/error roles are
        leader-bound; one that reaches a receiver (misroute) is
        ignored.  A node with no serving engine answers with an error
        so the leader aborts instead of re-sending the fence forever."""
        if self._fence_stale(msg):
            return
        if msg.applied or msg.query or msg.error:
            # Leader-bound roles.  On a shared loop (this worker was
            # PROMOTED to leader) the receiver owns the handler — hand
            # the message to the promoted leader's swap driver so
            # confirms/queries keep flowing across a takeover.
            fwd = self.on_swap_leader_msg
            if fwd is not None:
                fwd(msg)
            return
        if self.swap is None:
            log.error("swap commit at a node with no serving engine",
                      version=msg.version)
            try:
                self._send_to_leader(SwapCommitMsg(
                    self.node.my_id, msg.version,
                    error="no serving engine at this node"))
            except Exception as e:  # noqa: BLE001 — advisory
                log.error("swap refusal send failed", err=repr(e))
            return
        if msg.prepare:
            self.swap.on_prepare(msg.version, msg.swap_base)
            return
        self.swap.on_commit(msg)

    def _apply_swap_result(self, version: str, params) -> None:
        """The atomic flip: replace the serving params pointer under the
        receiver lock.  In-flight decodes finish on the v1 tree they
        captured (the old params object is immutable and refcounted);
        every request admitted after this line decodes on v2 — no
        request is ever dropped, and no forward spans both versions."""
        from .boot import BootResult

        from ..models import serde

        cfg = self.boot_cfg
        res = BootResult(kind="full", seconds=0.0,
                         layer_ids=list(range(cfg.n_layers)),
                         params=params)
        with self._lock:
            self.boot_result = res
            self.serving_version = version
            # Every blob of the flipped-in tree carries THIS version:
            # the per-step guard of the token-granularity flip asserts
            # this map stays uniform (docs/rollout.md).
            self._serving_tree_versions = {
                slot: version
                for slot in range(serde.head_blob_id(cfg) + 1)}
            self._boot_started = True
            self._boot_report = (0.0, "full")
        # A swap can land on a node that never booted v1 (it joined the
        # fleet mid-rollout): the flip IS its boot — serve waiters
        # proceed.
        self._boot_finished.set()
        self._boot_drained.set()

    def handle_boot_hint(self, msg: BootHintMsg) -> None:
        """Overlap the boot's XLA compiles with the dissemination: the
        leader says what this node will hold, and shapes are all the
        compiler needs — so by the time the bytes land and startup asks
        for the boot, its jit calls hit warm caches.  Runs on its OWN
        daemon thread: a compile takes seconds and must not occupy a
        handler-pool slot that fragment delivery needs."""
        if self._fence_stale(msg):
            return
        if self.boot_cfg is None or not msg.blob_ids:
            return
        hinted = frozenset(int(b) for b in msg.blob_ids)
        with self._lock:
            if hinted in self._precompiled_sets:
                return
            # The window re-admits evicted sets, so the eviction alone
            # no longer bounds CONCURRENT warmups — an attacker cycling
            # distinct sets faster than compiles finish would otherwise
            # spawn a compile thread per hint.  Saturated = boot cold.
            if self._precompile_inflight >= _PRECOMPILE_MAX_SETS:
                log.warn("precompile warmups saturated; hinted set "
                         "boots cold", inflight=self._precompile_inflight)
                return
            while len(self._precompiled_sets) >= _PRECOMPILE_MAX_SETS:
                evicted = next(iter(self._precompiled_sets))
                del self._precompiled_sets[evicted]
                log.info("precompile window full; evicting oldest hinted "
                         "set (its jit caches stay warm until XLA drops "
                         "them)", evicted=sorted(evicted))
            self._precompiled_sets[hinted] = True
            self._precompile_inflight += 1
            self._precompile_done.clear()
        threading.Thread(
            target=self._precompile_boot, args=(sorted(hinted),),
            daemon=True, name=f"boot-precompile-{self.node.my_id}",
        ).start()

    def _precompile_boot(self, blob_ids) -> None:
        from .boot import precompile_boot

        try:
            t0 = _time.monotonic()
            rec = precompile_boot(
                self.boot_cfg, blob_ids,
                placement=self.placement, node_id=self.node.my_id,
                codec=self.boot_codec, device_blobs=self.stage_hbm,
            )
            # Compile-overlap accounting: the whole warmup counts into
            # the precompile bucket; the run overlapped the wire exactly
            # when it finished before startup arrived.
            dt = _time.monotonic() - t0
            overlapped = not self._startup_seen.is_set()
            trace.add_phase("boot_precompile", dt)
            if overlapped:
                trace.add_phase("boot_precompile_in_wire", dt)
            log.info("boot programs precompiled during dissemination",
                     in_wire=overlapped, **rec)
        except Exception as e:  # noqa: BLE001 — advisory: boot compiles cold
            log.warn("boot precompile failed; boot will compile at "
                     "startup instead", err=repr(e))
        finally:
            with self._lock:
                self._precompile_inflight -= 1
                if self._precompile_inflight == 0:
                    self._precompile_done.set()

    def handle_startup(self, msg: StartupMsg) -> None:
        """The inference-engine boot hook (node.go:1387-1389) — with
        ``boot_cfg`` it actually boots the engine: ``ready()`` unblocks
        immediately (delivery is done), the boot runs on the handler pool,
        and its completion is reported to the leader as a BootReadyMsg.

        The LEADER's boot decision (``msg.boot``) governs: with it off,
        nobody boots; with it on, a receiver that locally opted out
        (``-boot none``) reports a "skipped" BootReadyMsg instead of
        silence — the leader's boot wait can never deadlock on a flag
        mismatch."""
        if self._fence_stale(msg):
            return
        self.expect_serve = msg.serve  # before ready(): the CLI reads it
        # Delivery is done: flush a FINAL cumulative metrics snapshot
        # now (the periodic cadence could lag a fast run by a whole
        # interval, and the leader's -report fold wants completion-time
        # totals, not the last tick's).
        if self._metrics_interval > 0:
            self._send_metrics_report()
        # Overlap accounting: precompiles/streamed stagings that finish
        # after this point no longer ran during the wire.
        self._startup_seen.set()
        if self._boot_stager is not None:
            self._boot_stager.mark_startup()
        # Latch the boot decision BEFORE ready() fires: the CLI's
        # exit-time wait_boot_drain reads _boot_started the moment
        # ready() returns, and a latch set after the put would race it —
        # the process could exit before the boot task was ever submitted
        # (killing it silently: the hang class this latch exists for).
        boot_pending = False
        prior_report = None
        if msg.boot and self.boot_cfg is not None:
            with self._lock:
                if self._boot_started:
                    # A re-sent startup must not re-boot — but it MUST
                    # re-answer (below, outside the lock): the leader
                    # re-sends startup precisely when it suspects the
                    # first exchange was lost, and a completed boot whose
                    # BootReadyMsg send failed would otherwise be
                    # unrecoverable.  A boot still in flight (report
                    # None) reports when it finishes.
                    prior_report = self._boot_report
                else:
                    self._boot_started = True
                    boot_pending = True
        self._ready_q.put(object())
        if self.fabric is not None:
            # Dissemination is over: the cached fabric uploads' HBM now
            # belongs to whatever boots next.
            release_upload_cache()
        if not msg.boot:
            return
        if self.boot_cfg is None:
            # No latch ON PURPOSE: the report is idempotent and cheap,
            # and a leader that re-sends startup (after an update/re-plan,
            # or because this send failed) must get it again.
            log.info("startup asked for boot but this node opted out; "
                     "reporting skipped")
            self._send_to_leader(BootReadyMsg(self.node.my_id, 0.0,
                                              "skipped"))
            return
        if boot_pending:
            self.loop.submit(self._boot)
        elif prior_report is not None:
            self._send_to_leader(
                BootReadyMsg(self.node.my_id, *prior_report))

    def _boot(self) -> None:
        try:
            self._boot_inner()
        finally:
            self._boot_drained.set()

    def wait_boot_drain(self, timeout: float) -> bool:
        """Block until any started boot task has fully drained (report
        sent, -gen decode done).  True immediately when no boot started.
        The CLI calls this before process exit: the boot runs on daemon
        threads, and exiting mid-boot kills it silently — the leader
        then hangs waiting for a BootReadyMsg that never comes."""
        with self._lock:
            started = self._boot_started
        if not started:
            return True
        return self._boot_drained.wait(timeout=timeout)

    def _boot_inner(self) -> None:
        from .boot import boot_from_layers

        try:
            res = boot_from_layers(
                self.boot_cfg, self.layers,
                placement=self.placement, node_id=self.node.my_id,
                codec=self.boot_codec, stager=self._boot_stager,
                digest_lookup=self._expected_digest,
                digest_verified=self._digest_ok,
            )
            # Assign BEFORE the finally sets the event: _serve() waits on
            # _boot_finished and then reads boot_result, so the event must
            # guarantee the assignment is visible.  A swap FLIP can race
            # a slow v1 boot (the v2 delta verified + committed while v1
            # was still compiling): the flipped tree wins — a late v1
            # boot must never overwrite the serving v2 params while
            # serving_version says v2 (docs/swap.md).
            with self._lock:
                if not self.serving_version:
                    self.boot_result = res
                else:
                    log.warn("boot finished after a swap flip; keeping "
                             "the swapped serving params",
                             serving=self.serving_version)
        except Exception as e:  # noqa: BLE001 — boot failure must be loud but non-fatal
            log.error("model boot failed", err=repr(e))
            # The failure must still REPORT: the leader's TTFT wait gates
            # on every assignee's BootReadyMsg, and silence would hang it
            # (found live: a physical-size boot OOM left the leader
            # blocked in boot_ready().get() forever).
            with self._lock:
                self._boot_report = (0.0, "failed")
            self._send_to_leader(BootReadyMsg(self.node.my_id, 0.0,
                                              "failed"))
            return
        finally:
            self._boot_finished.set()  # serve waiters proceed either way
        with self._lock:
            self._boot_report = (res.seconds, res.kind)
        self._send_to_leader(
            BootReadyMsg(self.node.my_id, res.seconds, res.kind))
        if self.boot_generate > 0:
            # Decode AFTER reporting: the leader's TTFT clock stops at
            # the last BootReadyMsg, and serving time must not
            # contaminate it.
            from .boot import decode_after_boot

            try:
                decode_after_boot(self.boot_cfg, res, self.boot_generate)
            except Exception as e:  # noqa: BLE001 — serving is best-effort here
                log.error("post-boot decode failed", err=repr(e))

    # ------------------------------------------------- pod serving (spmd)

    def serve_done(self) -> "queue.Queue[object]":
        """Fires once after a ServeMsg is handled: the member's
        (logits, seconds), or None (not a member / serve failed)."""
        return self._serve_q

    def handle_serve(self, msg: ServeMsg) -> None:
        """Multi-controller serving: every member enters the pipelined
        forward across the stages (runtime/pp_serve.py).  An EMPTY
        members list is the leader's cancellation (the pod became
        unservable) — waiters are released immediately.  Runs on a
        dedicated thread — the collective blocks until all members are
        in, which must not starve the message pool."""
        if self._fence_stale(msg):
            return
        self.serve_started.set()
        threading.Thread(
            target=self._serve, args=(msg,), daemon=True, name="serve"
        ).start()

    def _serve(self, msg: ServeMsg) -> None:
        from .pp_serve import spmd_pod_decode, spmd_pod_forward

        out = None
        try:
            if self.node.my_id not in msg.members:
                return
            if self.boot_cfg is None or self.placement is None:
                log.error("serveMsg but no boot_cfg/placement")
                return
            self._boot_finished.wait(timeout=300.0)
            res = self.boot_result
            if res is None or res.kind != "stage" or res.params is None:
                log.error("serveMsg but no stage boot to serve from",
                          kind=getattr(res, "kind", None))
                return
            counts = msg.counts or None
            if msg.gen > 0:
                # Pod generation: every member enters the lockstep
                # KV-cached greedy decode and emits IDENTICAL token ids.
                out = spmd_pod_decode(
                    self.boot_cfg, self.placement, msg.members,
                    self.node.my_id, res.params, self.layers,
                    max_new=msg.gen, codec=self.boot_codec,
                    batch=msg.batch, prompt_len=msg.seq_len,
                    member_counts=counts,
                )
                if out is not None:
                    toks, _ = out
                    log.info("pod generated token ids",
                             tokens=[int(t) for t in toks[0]])
            else:
                out = spmd_pod_forward(
                    self.boot_cfg, self.placement, msg.members,
                    self.node.my_id, res.params, self.layers,
                    codec=self.boot_codec, batch=msg.batch,
                    seq_len=msg.seq_len, member_counts=counts,
                )
        except Exception as e:  # noqa: BLE001 — serve failure is loud, non-fatal
            log.error("pod serve failed", err=repr(e))
        finally:
            self._serve_q.put(out)


class RetransmitReceiverNode(ReceiverNode):
    """Modes 1/2 receiver: can forward its layers on command
    (node.go:1421-1484)."""

    def _register_handlers(self) -> None:
        super()._register_handlers()
        self.loop.register(RetransmitMsg, self.handle_retransmit)
        # Retransmit-capable receivers SERVE layers, so they also serve
        # NACKs for fragments a peer's transport dropped as corrupt —
        # and honor preemption revokes for their queued sends.
        self.loop.register(LayerNackMsg, self.handle_layer_nack)
        self.loop.register(JobRevokeMsg, self.handle_job_revoke)

    def handle_layer_nack(self, msg: LayerNackMsg) -> None:
        self.nacker.handle(self.node, self.layers, self._lock, msg,
                           codecs=self.codec_plane)

    def handle_job_revoke(self, msg: JobRevokeMsg) -> None:
        """Preemption revoke (docs/service.md): a re-plan demoted this
        job's tier — queued sends for the named pairs must not start
        (and in-flight ones stop between fragments)."""
        if self._fence_stale(msg):
            return
        n = self.revokes.add(msg.job_id, msg.pairs,
                             gen=getattr(msg, "gen", 0))
        log.info("preemption revoke registered", job=msg.job_id,
                 pairs=len(msg.pairs), registry=n)

    def handle_retransmit(self, msg: RetransmitMsg) -> None:
        if self._fence_stale(msg):
            return
        with self._lock:
            layer = self.layers.get(msg.layer_id)
        if layer is None:
            log.error("retransmit of unknown layer", layerID=msg.layer_id)
            return
        self.node.add_node(msg.dest_id)
        if layer.meta.location == LayerLocation.CLIENT:
            log.debug("loading layer from client", layer=msg.layer_id)
            fetch_from_client(self.node, msg.layer_id, msg.dest_id)
            return
        try:
            send_layer(self.node, msg.dest_id, msg.layer_id, layer,
                       job_id=msg.job_id, shard=msg.shard,
                       codec=msg.codec, codecs=self.codec_plane)
        except (OSError, KeyError) as e:
            log.error("failed to send layer", dest=msg.dest_id, err=repr(e))


class FlowRetransmitReceiverNode(RetransmitReceiverNode):
    """Mode 3 receiver: partial-layer reassembly + flow-job execution
    (node.go:1487-1589)."""

    def __init__(self, node: Node, layers: LayersSrc, storage_path: str = ".",
                 start_loop: bool = True, heartbeat_interval: float = 0.0,
                 checkpoint_dir: str = "", stage_hbm: bool = False,
                 placement=None, boot_cfg=None, fabric=None,
                 boot_codec: str = "raw", boot_generate: int = 0,
                 codecs=None):
        """``checkpoint_dir``: when set, every fragment is journaled there
        and partial layers survive a process restart (resume support —
        absent in the reference, whose partial accounting dies with the
        process, node.go:1542-1554)."""
        # layer -> (reassembly buffer, ClaimedCoverage): fragment byte
        # copies run OUTSIDE self._lock (a 16 MiB memcpy under the lock
        # serializes every other handler) under the claim/commit
        # discipline shared with parallel/ingest.ShardedLayerIngest —
        # completion and coverage readers see only committed bytes.
        self._partial: Dict[int, Tuple[bytearray,
                                       intervals.ClaimedCoverage]] = {}
        self._partial_total: Dict[int, int] = {}
        # layer -> DURABLY-covered ranges: only ranges whose .part write has
        # fsync'd merge in (under self._lock), so the journal can never
        # claim bytes another handler thread hasn't landed on disk yet.
        self._durable: Dict[int, list] = {}
        # layer -> [(offset, len, crc32), ...] of journaled fragments —
        # recorded in the meta journal so resume re-VERIFIES the disk
        # bytes (runtime/checkpoint.py): a corrupted disk can never
        # resume as "covered".
        self._durable_crcs: Dict[int, list] = {}
        # layer -> ShardedLayerIngest: incremental device staging, fed per
        # fragment so HBM ingest overlaps the network receive (the
        # reference-analogous alternative — one synchronous device_put
        # after full host assembly — serializes ingest behind the ack).
        # Guarded by its own lock so creation/teardown never holds the main
        # receiver lock during device work.
        self._ingests: Dict[int, object] = {}
        self._ingests_lock = threading.Lock()
        # layer -> whether its ingest shares the reassembly buffer
        # (zero-copy CPU arm); memoized so only the first fragment pays
        # the share attempt.
        self._ingest_share: Dict[int, bool] = {}
        # layer -> phase accumulators (first-fragment wall time, summed
        # assembly-copy and ingest-write seconds): the per-layer phase
        # breakdown the completion log emits, so a physical-size run's
        # TTD decomposes into wire / copy / device time from the logs
        # alone (VERDICT r4: "nothing decomposes where the 19.6 s goes").
        self._phase: Dict[int, dict] = {}
        self._ingest_dead: set = set()  # layers whose ingest failed: fall back
        self._ingest_done: set = set()  # completed: late creation is a leak
        self.ckpt = LayerCheckpointStore(checkpoint_dir) if checkpoint_dir else None
        if self.ckpt is not None:
            for lid, (buf, covered, total) in self.ckpt.load().items():
                if intervals.covered(covered) >= total:
                    # Crashed between assembly and journal cleanup: done.
                    layers[lid] = LayerSrc(
                        inmem_data=buf, data_size=total,
                        meta=LayerMeta(location=LayerLocation.INMEM),
                    )
                    self.ckpt.complete(lid)
                else:
                    self._partial[lid] = (
                        buf, intervals.ClaimedCoverage(covered))
                    self._partial_total[lid] = total
                    self._durable[lid] = list(covered)  # restored = on disk
                    # Re-seed the journal CRC records from the VERIFIED
                    # restored ranges: the next meta write replaces the
                    # whole journal, and ranges without a CRC would fail
                    # verification on the resume after next.
                    self._durable_crcs[lid] = [
                        (s, e - s,
                         integrity.fragment_crc(memoryview(buf)[s:e]))
                        for s, e in covered
                    ]
        # Loop start is deferred past the checkpoint replay below so no
        # handler races the ingest reconstruction.
        super().__init__(node, layers, storage_path, start_loop=False,
                         heartbeat_interval=heartbeat_interval,
                         stage_hbm=stage_hbm, placement=placement,
                         boot_cfg=boot_cfg, fabric=fabric,
                         boot_codec=boot_codec, boot_generate=boot_generate,
                         codecs=codecs)
        # Replay checkpoint-restored coverage into device ingests so a
        # resumed transfer's already-held bytes are on-mesh too.
        if self.stage_hbm:
            for lid, (buf, cov) in self._partial.items():
                ing = self._get_or_create_ingest(lid, self._partial_total[lid])
                if ing is None:
                    continue
                try:
                    for s, e in cov.committed():
                        ing.write(s, memoryview(buf)[s:e])
                except Exception as err:  # noqa: BLE001
                    self._ingest_write_failed(lid, ing, err)
        # Zero-copy receive: let the transport land fragment bytes
        # straight in the reassembly buffers (TcpTransport.layer_sink).
        # Registered after super().__init__ — the sink uses locks the
        # base constructor creates; fragments racing the registration
        # just take the bounce path.
        if hasattr(node.transport, "layer_sink"):
            node.transport.layer_sink = self._layer_sink
        # Quiet-gap watchdog (docs/integrity.md): last sender seen per
        # in-flight layer, and a ticker that re-NACKs gaps whose
        # coverage sat silent for a full interval — silent frame loss
        # (an eaten retransmit, a reset mid-flight) becomes a bounded
        # re-request instead of a stall until crash detection.
        self._frag_src: Dict[int, int] = {}
        self._frag_t: Dict[int, float] = {}
        # Chain relay roles (docs/hierarchy.md): per-layer forward hops
        # installed by the sub-leader's chain plan — each role is
        # {"lo","hi","next","sent"} with ``sent`` the interval list of
        # wire bytes already forwarded downstream, so every committed
        # byte forwards exactly once no matter how fragments split.
        self._fwd_roles: Dict[int, list] = {}
        self._fwd_dispatched: set = set()  # (lid, next): relay span filed
        self._gap_stop = threading.Event()
        self._gap_thread = None
        try:
            gap_s = float(
                os.environ.get("DLD_GAP_NACK_S", _GAP_NACK_DEFAULT_S))
        except ValueError:
            gap_s = _GAP_NACK_DEFAULT_S
        if gap_s > 0:
            self._gap_thread = threading.Thread(
                target=self._gap_watchdog, args=(gap_s,),
                daemon=True, name="gap-nack")
            self._gap_thread.start()
        if start_loop:
            self.loop.start()

    def _layer_sink(self, layer_id, total_size, offset, size):
        """Transport hook: claim the fragment's byte range and expose it
        as a writable view into the reassembly buffer, so ``recv_into``
        lands the bytes IN PLACE — socket→assembly in one copy, no
        bounce buffer, no handler memcpy.  Returns None (bounce path)
        for duplicates, overlaps, or anything unusual — correctness
        never depends on the sink engaging."""
        end = offset + size
        if size <= 0 or offset < 0 or end > total_size:
            return None
        with self._lock:
            if layer_id in self.layers:
                return None  # finished layer: bounce path re-acks dups
            entry = self._partial.get(layer_id)
            if entry is None:
                entry = (alloc_recv_buffer(total_size),
                         intervals.ClaimedCoverage())
            buf, cov = entry
            tok, claims = cov.claim(offset, end)
            if tok is None:
                return None  # full duplicate
            if claims != [(offset, end)]:
                # Partial overlap: a contiguous recv target would clobber
                # committed bytes — hand it to the claim-splitting path.
                cov.abort(tok)
                return None
            self._partial[layer_id] = entry
            self._partial_total[layer_id] = total_size
            # Phase accounting happens at COMMIT time in handle_layer
            # for both paths — an aborted recv must not skew the
            # fragment counts or the span the breakdown reports.

        def abort():
            with self._lock:
                cov.abort(tok)

        return memoryview(buf)[offset:end], tok, abort

    def _gap_watchdog(self, gap_s: float) -> None:
        """Quiet-gap re-NACK ticker (docs/integrity.md): a partial layer
        whose coverage sat still — and claim-idle — for a full interval
        has lost frames SILENTLY (an eaten retransmit, a reset
        mid-flight), so its uncovered gaps are re-requested from the
        last sender seen for it.  Re-NACKs ride the same
        ``_NACK_MAX_PER_RANGE`` budget as first NACKs (via
        ``_on_corrupt_fragment``), so a dead path still goes quiet
        instead of livelocking; each round also re-arms the quiet timer
        so a slow retransmit gets a full interval to land."""
        while not self._gap_stop.wait(gap_s):
            now = _time.monotonic()
            stale = []
            spent = []
            with self._lock:
                for lid, (_, cov) in self._partial.items():
                    total = self._partial_total.get(lid)
                    src = self._frag_src.get(lid)
                    last = self._frag_t.get(lid)
                    if (total is None or src is None or last is None
                            or now - last < gap_s or not cov.idle()):
                        continue
                    s0, s_sz = shard_range(
                        self._shard_specs.get(lid, ""), total)
                    all_gaps = intervals.uncovered(cov.committed(),
                                                   s0, s0 + s_sz)
                    gaps = [(s, e) for s, e in all_gaps
                            if self._nack_counts.get((lid, s), 0)
                            < _NACK_MAX_PER_RANGE]
                    if gaps:
                        stale.append((lid, src, total, gaps))
                        self._frag_t[lid] = now
                    elif all_gaps:
                        # Every remaining gap's NACK budget is spent:
                        # stand down for this layer — recovery belongs
                        # to crash detection now, not a per-interval
                        # error line for the rest of the process.
                        self._frag_src.pop(lid, None)
                        self._frag_t.pop(lid, None)
                        spent.append(lid)
            for lid in spent:
                trace.count("integrity.gap_standdown")
                log.error("gap watchdog standing down: NACK budget "
                          "exhausted for every remaining gap; "
                          "re-announcing so the leader re-plans",
                          layerID=lid)
            if spent:
                # The NACK path is dead (a partitioned or crashed
                # holder); the re-announce carries this node's partial
                # coverage, so the leader re-plans ONLY the gaps — from
                # any surviving source.
                self._request_replan()
            for lid, src, total, gaps in stale:
                trace.count("integrity.gap_renack")
                log.warn("layer coverage quiet past watchdog interval; "
                         "re-NACKing gaps", layerID=lid, src=src,
                         gaps=len(gaps),
                         missing=sum(e - s for s, e in gaps))
                for s, e in gaps:
                    self._on_corrupt_fragment(src, lid, s, e - s, total,
                                              "stale")

    def _on_corrupt_fragment(self, src_id, layer_id, offset, size,
                             total, reason) -> None:
        # Arm the gap watchdog even when the FIRST frame of a layer is
        # the corrupt one: no successful store may ever happen for it,
        # and the re-NACK path needs a last-seen source + quiet timer.
        # Re-arming the timer on every drop is right — a NACK just went
        # out, so the retransmit gets a full quiet interval to land.
        if src_id is not None and src_id != self.node.my_id:
            with self._lock:
                self._frag_src[layer_id] = src_id
                self._frag_t[layer_id] = _time.monotonic()
        super()._on_corrupt_fragment(src_id, layer_id, offset, size,
                                     total, reason)

    # ------------------------------------------------ chain relay plane

    def _install_forward_roles(self, msg: GroupPlanMsg) -> None:
        """Install (REPLACE, per layer) this member's chain relay roles
        (docs/hierarchy.md).  A re-installed identical (lo, hi, next)
        hop keeps its ``sent`` coverage — re-chains after a member death
        must not re-ship ranges the survivor already forwarded — while
        a changed hop starts clean.  Bytes that landed BEFORE the roles
        arrived forward immediately via the backlog scan: role install
        and data arrival race freely."""
        installed = []
        with self._lock:
            for lid, hops in msg.forward.items():
                lid = int(lid)
                prior = {(r["lo"], r["hi"], r["next"]): r
                         for r in self._fwd_roles.get(lid, [])}
                fresh = []
                for lo, hi, nxt in hops:
                    key = (int(lo), int(hi), int(nxt))
                    old = prior.get(key)
                    fresh.append(old if old is not None else
                                 {"lo": key[0], "hi": key[1],
                                  "next": key[2], "sent": []})
                if fresh:
                    self._fwd_roles[lid] = fresh
                else:
                    self._fwd_roles.pop(lid, None)
                installed.append(lid)
        trace.count("hier.relay_roles")
        log.info("chain forward roles installed", group=msg.group_id,
                 layers=sorted(installed))
        for lid in installed:
            self._forward_committed(lid, None, None)

    def _clear_forward_roles(self) -> None:
        with self._lock:
            self._fwd_roles.clear()

    def _forward_committed(self, lid, ranges, total, job: str = "") -> None:
        """Relay hop of the chain (docs/hierarchy.md): forward the
        freshly committed ``ranges`` (None = backlog scan of everything
        already landed) downstream per this member's roles, the moment
        they land — cut-through at fragment granularity, never waiting
        for layer completion.  ``sent`` interval accounting dedups, so
        retransmitted duplicates forward nothing."""
        sends = []
        with self._lock:
            roles = self._fwd_roles.get(lid)
            if not roles:
                return
            layer = self.layers.get(lid)
            if layer is not None:
                buf = layer.inmem_data
                total = layer.data_size
                codec = layer.meta.codec
                # A completed SHARD holding's buffer is only real inside
                # its range (roles never exceed it by construction, but
                # the clip keeps a malformed plan from shipping zeros).
                s0, s_sz = shard_range(layer.meta.shard, total)
                committed = [(s0, s0 + s_sz)]
            else:
                entry = self._partial.get(lid)
                if entry is None:
                    return
                buf, cov = entry
                total = self._partial_total.get(lid, total)
                codec = (self._layer_codecs.get(lid)
                         or self._frag_codec.get(lid, ""))
                committed = cov.committed()
            if ranges is None:
                ranges = committed
            if total is None or buf is None:
                return
            for role in roles:
                for s, e in ranges:
                    cs, ce = max(s, role["lo"]), min(e, role["hi"])
                    if cs >= ce:
                        continue
                    for a, b in intervals.uncovered(role["sent"], cs, ce):
                        role["sent"] = intervals.insert(role["sent"], a, b)
                        sends.append((role["next"], a, b))
        for nxt, a, b in sends:
            threads_util.tx_pool().submit(
                self._forward_one, nxt, lid, buf, a, b - a, total, codec,
                job)

    def _forward_one(self, nxt, lid, buf, off, size, total, codec,
                     job) -> None:
        """One relay send — a byte-range LayerMsg whose offset indexes
        the SAME wire space the bytes landed in (the reassembly buffer
        is the full-size wire blob, so the landing offset IS the
        forwarding offset).  Failures are non-fatal: the downstream
        member's gap-NACK watchdog re-requests what never arrived, and
        the sub-leader's redrive star-sends around a dead hop."""
        try:
            self.node.add_node(nxt)
            span = telemetry.span_id(nxt, lid)
            with self._lock:
                first = (lid, nxt) not in self._fwd_dispatched
                if first:
                    self._fwd_dispatched.add((lid, nxt))
            if first:
                telemetry.span_event(
                    span, "dispatched", node=self.node.my_id,
                    src=self.node.my_id, dest=nxt, layer=lid, job=job,
                    codec=codec,
                    parent=telemetry.span_id(self.node.my_id, lid))
                log.info("relaying layer downstream", layerID=lid,
                         next=nxt)
            trace.count("hier.relay_frags")
            trace.count("hier.relay_bytes", size)
            src = LayerSrc(
                inmem_data=buf, data_size=size, offset=off,
                meta=LayerMeta(location=LayerLocation.INMEM))
            self.node.transport.send(
                nxt, LayerMsg(self.node.my_id, lid, src, total,
                              job_id=job, codec=codec, span_id=span,
                              span_parent=telemetry.span_id(
                                  self.node.my_id, lid)))
        except (OSError, KeyError, ConnectionError) as e:
            log.warn("relay forward failed (downstream gap-NACK / "
                     "sub-leader redrive recovers it)", layerID=lid,
                     next=nxt, err=repr(e))

    def handle_layer_nack(self, msg: LayerNackMsg) -> None:
        """Mode-3 NACK service: a mid-chain member can be asked to
        retransmit a range of a layer it is ITSELF still receiving (its
        downstream lost a relayed fragment) — serve fully-committed
        ranges straight from the reassembly buffer, and fall back to
        the completed-holdings retransmitter otherwise."""
        with self._lock:
            held = msg.layer_id in self.layers
        if not held and self._serve_nack_from_partial(msg):
            return
        super().handle_layer_nack(msg)

    def _serve_nack_from_partial(self, msg: LayerNackMsg) -> bool:
        """True when the NACK was handled here (served, or suppressed by
        the shared retry budget).  Only byte-for-byte certain ranges
        qualify: fully committed, inside the wire total, and in the SAME
        codec byte space the transfer runs in — anything else falls
        through to the holding path's loud refusals."""
        lid = msg.layer_id
        end = msg.offset + msg.size
        with self._lock:
            entry = self._partial.get(lid)
            total = self._partial_total.get(lid)
            if (entry is None or total is None or msg.size <= 0
                    or msg.offset < 0 or end > total):
                return False
            buf, cov = entry
            if intervals.uncovered(cov.committed(), msg.offset, end):
                return False  # not all landed here yet
            codec = (self._layer_codecs.get(lid)
                     or self._frag_codec.get(lid, ""))
        if (getattr(msg, "codec", "") or "") != (codec or ""):
            return False
        n = self.nacker.admit(msg.src_id, lid, msg.offset, msg.size)
        if not n:
            return True  # budget spent: suppressed, not re-servable
        self.node.add_node(msg.src_id)
        log.warn("NACK served from in-flight partial coverage",
                 layerID=lid, dest=msg.src_id, offset=msg.offset,
                 bytes=msg.size, reason=msg.reason, attempt=n,
                 codec=codec or None)
        trace.count("integrity.retransmit_frags")
        trace.count("integrity.retransmit_bytes", msg.size)
        telemetry.link_add(self.node.my_id, msg.src_id,
                           retransmit_frames=1, retransmit_bytes=msg.size)
        src = LayerSrc(inmem_data=buf, data_size=msg.size,
                       offset=msg.offset,
                       meta=LayerMeta(location=LayerLocation.INMEM))
        self.node.transport.send(
            msg.src_id,
            LayerMsg(self.node.my_id, lid, src, total, codec=codec,
                     span_id=telemetry.span_id(msg.src_id, lid)))
        return True

    def close(self) -> None:
        self._gap_stop.set()
        if self._gap_thread is not None:
            self._gap_thread.join(timeout=2.0)
        super().close()

    def _get_or_create_ingest(self, layer_id, total_size):
        """The layer's incremental device ingest, created on first use;
        None when device staging doesn't apply (no -hbm / no placement for
        this layer / a previous device failure on it / already completed).
        Must NOT be called while holding ``self._lock`` — creation
        dispatches device allocations under ``self._ingests_lock``."""
        if not self.stage_hbm:
            return None
        if (self.placement is None
                or layer_id not in self.placement.layer_to_stage):
            return None  # no stage mapping: stage whole at completion
        with self._ingests_lock:
            if layer_id in self._ingest_dead or layer_id in self._ingest_done:
                return None
            ing = self._ingests.get(layer_id)
            if ing is None:
                try:
                    from ..parallel.ingest import ShardedLayerIngest

                    ing = ShardedLayerIngest(
                        total_size, self.placement.devices_for_layer(layer_id)
                    )
                except Exception as e:  # noqa: BLE001 — delivery beats staging
                    log.error("device ingest unavailable for layer",
                              layerID=layer_id, err=repr(e))
                    self._ingest_dead.add(layer_id)
                    return None
                self._ingests[layer_id] = ing
            return ing

    def _ingest_write_failed(self, layer_id, ing, err) -> None:
        """A device write failed: poison the ingest (wakes any finalize
        waiter into the bulk-staging fallback) and stop feeding it."""
        log.error("incremental device ingest failed; will stage at "
                  "completion", layerID=layer_id, err=repr(err))
        ing.fail()
        with self._ingests_lock:
            self._ingest_dead.add(layer_id)
            self._ingests.pop(layer_id, None)

    def _partial_totals_locked(self) -> dict:
        return self._partial_total

    def _announce_partial(self) -> dict:
        """Partial coverage for the announce — EXCLUDING in-flight copy
        claims, exactly like ``_local_coverage``: a range announced as
        held is a range the leader won't re-plan, so it must only ever
        name bytes that have really landed in the buffer."""
        with self._lock:
            return {
                lid: {
                    "Total": self._partial_total[lid],
                    "Covered": [list(iv) for iv in cov.committed()],
                }
                for lid, (_, cov) in self._partial.items()
                if lid in self._partial_total
            }

    def _reopen_widened(self, lids) -> None:
        """A promoted SHARD holding whose target widened (or
        re-targeted to a non-covered shard) demotes back to PARTIAL
        coverage — the shard's landed bytes stay (the buffer already is
        the full-size reassembly buffer), and the re-planned remainder
        completes the new target through the normal fragment path."""
        for lid in lids:
            with self._lock:
                src = self.layers.get(lid)
                if src is None or not src.meta.shard:
                    continue
                total = src.data_size
                s0, s_sz = shard_range(src.meta.shard, total)
                del self.layers[lid]
                self._own_digests.pop(lid, None)
                self._digest_ok.discard(lid)
                self._partial[lid] = (
                    src.inmem_data,
                    intervals.ClaimedCoverage([(s0, s0 + s_sz)]))
                self._partial_total[lid] = total
            self.content_store.forget(lid)
            with self._ingests_lock:
                self._ingest_done.discard(lid)
            log.warn("shard holding's target widened/re-targeted; "
                     "reopened as partial coverage", layerID=lid,
                     kept_bytes=s_sz, total=total)

    def _on_shard_specs(self, lids) -> None:
        """Shard specs just (re)stamped: promote any layer whose
        existing coverage already satisfies its shard — fragments can
        land before the stamp, and no later fragment would re-run the
        completion check (docs/sharding.md)."""
        for lid in lids:
            with self._lock:
                total = self._partial_total.get(lid)
            if total is None:
                continue
            # commit(None) is a no-op: this reuses the promotion gate
            # without releasing anyone's claim.
            if self._commit_fragment(lid, None, total):
                self._ack_completed(lid)

    def _local_coverage(self, layer_id):
        """Checkpoint-restored bytes seed a resumed fabric ingest: the
        leader's plan covers only the gaps (leader.assign_jobs), so what
        this node already holds must enter the shard buffers locally.
        Ranges whose copy is still in flight (claimed, not committed) are
        excluded — their buffer bytes aren't real yet."""
        with self._lock:
            entry = self._partial.get(layer_id)
            if entry is None:
                return []
            buf, cov = entry
            return [(s, bytes(memoryview(buf)[s:e]))
                    for s, e in cov.committed()]

    def _fabric_store(self, layer_id, total: int, device_arr=None,
                      host_buf=None) -> None:
        """A fabric completion supersedes any partial-transfer state: the
        host buffer and the durable journal for this layer are done."""
        super()._fabric_store(layer_id, total, device_arr=device_arr,
                              host_buf=host_buf)
        with self._lock:
            self._partial.pop(layer_id, None)
            self._partial_total.pop(layer_id, None)
            self._durable.pop(layer_id, None)
            self._durable_crcs.pop(layer_id, None)
        if self.ckpt is not None:
            self.ckpt.complete(layer_id)

    def _register_handlers(self) -> None:
        super()._register_handlers()
        self.loop.register(FlowRetransmitMsg, self.handle_flow_retransmit)
        self.loop.register(SourceDeadMsg, self.handle_source_dead)

    def handle_source_dead(self, msg: SourceDeadMsg) -> None:
        """Range-level salvage (docs/failover.md): the leader declared a
        mid-transfer SOURCE crashed.  Re-request ONLY this layer's
        uncovered byte ranges from the surviving ``alt_id`` holder via
        the PR-4 NACK retransmit plane — the committed bytes the dead
        source (and everyone else) already delivered stay; recovery
        costs exactly the unsent remainder.  The gap watchdog re-arms
        against the alt holder, so a lost NACK round is re-requested
        instead of stalling."""
        if self._fence_stale(msg):
            return
        lid = msg.layer_id
        with self._lock:
            done = lid in self.layers
            entry = self._partial.get(lid)
            total = self._partial_total.get(lid)
        if done:
            # Completed while the notice was in flight: the leader
            # missed our ack — re-ack instead of re-fetching anything.
            self._ack_completed(lid)
            return
        if entry is None or total is None:
            # No coverage at all: nothing to salvage — re-announce so
            # the leader re-plans the whole layer.
            log.warn("source dead but no partial coverage; requesting "
                     "whole-layer re-plan", layerID=lid, dead=msg.dead_id)
            self._request_replan()
            return
        _, cov = entry
        with self._lock:
            self._frag_src[lid] = msg.alt_id
            self._frag_t[lid] = _time.monotonic()
            # committed() on purpose: ranges with an in-flight claim are
            # re-requested too.  A claim can still ABORT (failed copy),
            # and a range requested twice is absorbed by interval
            # reassembly — a range never requested is a stall until the
            # gap watchdog notices.  Slightly over-counts salvage_bytes;
            # never under-recovers.  Sharded targets salvage only their
            # shard's range (docs/sharding.md).
            s0, s_sz = shard_range(self._shard_specs.get(lid, ""), total)
            gaps = intervals.uncovered(cov.committed(), s0, s0 + s_sz)
        missing = sum(e - s for s, e in gaps)
        trace.count("failover.salvage_ranges", len(gaps))
        trace.count("failover.salvage_bytes", missing)
        log.warn("source declared dead mid-layer; NACKing uncovered "
                 "ranges to the surviving holder", layerID=lid,
                 dead=msg.dead_id, alt=msg.alt_id, ranges=len(gaps),
                 missing_bytes=missing, total=total)
        for s, e in gaps:
            self._on_corrupt_fragment(msg.alt_id, lid, s, e - s, total,
                                      "source-dead")

    def handle_layer(self, msg: LayerMsg) -> None:
        """Write the fragment at its offset; ack when the layer is whole
        (node.go:1520-1567, with the real byte copy the reference skips).

        Coverage is tracked as an interval union, not a byte counter (the
        reference sums sizes, node.go:1542-1554) — so duplicate or
        overlapping fragments from a crash-triggered re-plan can never ack
        a layer full of holes.

        The byte copy runs OUTSIDE ``self._lock`` under a claim/commit
        discipline (``utils.intervals.ClaimedCoverage``): the lock is
        held only to claim the
        fragment's uncovered ranges and, after the copy, to commit —
        concurrent senders' fragments assemble in parallel instead of
        serializing a 16 MiB memcpy each behind one lock, which matters
        exactly at physical layer sizes.  Completion (promote + ack) fires
        at the commit that sees full coverage with no copy in flight.

        Device staging is incremental: each fragment is also written to its
        span's device through the layer's ``ShardedLayerIngest`` as it
        arrives, so HBM ingest overlaps the network receive; completion
        runs one ICI all-gather instead of a full-layer device_put."""
        lid = msg.layer_id
        frag = msg.layer_src
        if (frag.offset < 0
                or frag.offset + frag.data_size > msg.total_size):
            # A malformed fragment must fail loudly BEFORE any claim: the
            # memmove assembly below has no implicit bounds check (the
            # old numpy slice assignment raised; ctypes.memmove corrupts).
            log.error("fragment outside layer; dropped", layerID=lid,
                      offset=frag.offset, size=frag.data_size,
                      total=msg.total_size)
            return
        with self._lock:
            already_done = lid in self.layers
        # Ingest creation dispatches device allocations — do it before
        # (and outside) the main critical section.
        ing = None
        if not already_done:
            ing = self._get_or_create_ingest(lid, msg.total_size)
        placed = frag.placed_token is not None
        # Materialize the fragment's bytes BEFORE claiming (one zero-copy
        # view for every consumer below; read_bytes would duplicate the
        # buffer per use): a read failure here must leave no claim behind
        # — a leaked claim wedges the layer forever (no commit can ever
        # see an empty in-flight set again).  A PLACED fragment's bytes
        # are already in the reassembly buffer (the transport sink
        # landed them there, claim held) — there is nothing to read.
        raw = None
        if not placed:
            raw = (frag.inmem_data if frag.inmem_data is not None
                   else frag.read_bytes())
        data_mv = memoryview(raw) if raw is not None else None
        claims: list = []
        tok = None
        journal = False
        dup_done = False
        foreign = False
        first_frag = False
        with self._lock:
            if lid in self.layers:
                # A re-plan duplicate of a finished layer: drop the bytes
                # but re-ack below — the re-send happened precisely because
                # the leader never saw our ack.  (A placed fragment can't
                # get here: its in-flight claim blocks completion.)
                dup_done = True
            elif placed and self._partial.get(lid) is None:
                # A placed fragment whose claim belongs to a PREVIOUS
                # incarnation's sink: a receiver replaced on a live
                # transport (declared-dead revival) drains its
                # predecessor's queued fragments, whose bytes live in
                # the DEAD incarnation's buffers.  Our sink never
                # claimed this range (the sink creates the _partial
                # entry at claim time), so drop it — the leader's
                # re-plan re-sends the range into OUR buffers.
                foreign = True
            else:
                entry = self._partial.get(lid)
                if entry is None:
                    # Allocate lazily (an eager dict.get default would
                    # build a full layer-sized buffer on *every* fragment)
                    # and unzeroed (zero-fill would hold the GIL for
                    # hundreds of ms at real layer sizes; coverage is
                    # tracked by intervals, so unwritten bytes are never
                    # exposed).
                    entry = (alloc_recv_buffer(msg.total_size),
                             intervals.ClaimedCoverage())
                buf, cov = entry
                if placed:
                    # The sink already claimed exactly this range and the
                    # bytes are in ``buf``; this handler owns the commit.
                    tok = frag.placed_token
                    claims = [(frag.offset, frag.offset + frag.data_size)]
                else:
                    tok, claims = cov.claim(
                        frag.offset, frag.offset + frag.data_size)
                ph = self._phase.setdefault(lid, {
                    "t0": _time.monotonic(), "copy_s": 0.0,
                    "ingest_s": 0.0, "frags": 0, "placed": 0})
                ph["frags"] += 1
                first_frag = ph["frags"] == 1
                if placed:
                    # Zero-copy receive: the transport landed this
                    # fragment (possibly one STRIPE of a striped
                    # transfer) directly in the reassembly buffer.
                    ph["placed"] = ph.get("placed", 0) + 1
                self._partial[lid] = (buf, cov)
                self._partial_total[lid] = msg.total_size
                # Gap-watchdog bookkeeping: who last fed this layer, and
                # when — ANY fragment counts as progress (even a
                # duplicate proves the path is alive).
                self._frag_src[lid] = msg.src_id
                self._frag_t[lid] = _time.monotonic()
                # Advisory codec tag (docs/codec.md): remembered so the
                # promotion (and NACKs) know the transfer's encoded
                # form even when the leader's stamp never arrived
                # (digests disabled).
                if msg.codec:
                    self._frag_codec[lid] = msg.codec
                # Journaled OUTSIDE the lock below (two fsyncs per
                # fragment must not serialize every other handler), and
                # only for fragments that landed NEW bytes — a full
                # re-plan duplicate's ranges were journaled by their
                # claim-holders already.
                journal = self.ckpt is not None and bool(claims)
                log.info(
                    "layer fragment stored",
                    layerID=lid, offset=frag.offset, size=frag.data_size,
                    received=cov.covered_bytes(),
                    total=msg.total_size,
                )
        if first_frag:
            # Pair-lifecycle span (docs/observability.md): the wire is
            # live — dispatched→first_byte is the transfer's startup
            # latency, first_byte→wire_complete its streaming window.
            telemetry.span_event(
                msg.span_id or telemetry.span_id(self.node.my_id, lid),
                "first_byte", node=self.node.my_id, src=msg.src_id,
                dest=self.node.my_id, layer=lid, job=msg.job_id,
                parent=msg.span_parent)
        if dup_done:
            self._ack_completed(lid)
            return
        if foreign:
            trace.count("failover.foreign_placed_dropped")
            log.warn("dropping placed fragment claimed by a previous "
                     "incarnation's sink", layerID=lid,
                     offset=frag.offset, size=frag.data_size)
            return
        if placed:
            # The fragment's bytes live in the reassembly buffer; every
            # consumer below (ingest, journal) reads them from there.
            data_mv = memoryview(buf)[
                frag.offset : frag.offset + frag.data_size]
        # Zero-copy CPU arm: the ingest adopts the reassembly buffer
        # itself (first fragment pays the attempt; memoized).  The
        # assembly write then IS the ingest — only coverage accounting
        # remains (``mark`` below, after the bytes are really in place).
        shared = False
        if ing is not None:
            shared = self._ingest_try_share(lid, ing, buf)
        # Ingest first: on an accelerator this dispatches the async DMA,
        # which then overlaps the host-side assembly copy right below.
        if ing is not None and not shared:
            try:
                t_ing = _time.monotonic()
                ing.write(frag.offset, data_mv)
                t_ing = _time.monotonic() - t_ing
                with self._lock:
                    ph = self._phase.get(lid)
                    if ph is not None:
                        ph["ingest_s"] += t_ing
            except Exception as e:  # noqa: BLE001 — delivery beats staging
                self._ingest_write_failed(lid, ing, e)
                ing = None
            else:
                telemetry.link_add(msg.src_id, self.node.my_id,
                                   place_s=t_ing)
        if tok is not None and not placed:
            try:
                t_cp = _time.monotonic()
                for lo, hi in claims:
                    # memmove-grade copy (GIL released): concurrent
                    # senders' fragments really assemble in parallel.
                    hostmem.copy_into(
                        buf, lo, data_mv[lo - frag.offset : hi - frag.offset])
                t_cp = _time.monotonic() - t_cp
                with self._lock:
                    ph = self._phase.get(lid)
                    if ph is not None:
                        ph["copy_s"] += t_cp
                telemetry.link_add(msg.src_id, self.node.my_id,
                                   place_s=t_cp)
            except Exception:
                with self._lock:
                    cov.abort(tok)
                raise
        if ing is not None and shared and tok is not None:
            # Bytes are in the shared buffer now (copied above, or placed
            # by the transport): record the coverage with the ingest.
            for lo, hi in claims:
                ing.mark(lo, hi)
        if tok is not None and claims:
            # Flight recorder: exactly the NEW bytes this fragment's
            # claims landed (duplicates and overlaps claim nothing), so
            # per-link delivered totals reconcile byte-exactly against
            # delivered layer bytes in the run report.
            telemetry.link_add(
                msg.src_id, self.node.my_id, job=msg.job_id,
                delivered_bytes=sum(hi - lo for lo, hi in claims))
        complete = self._commit_fragment(lid, tok, msg.total_size)
        if tok is not None and claims:
            # Chain relay (docs/hierarchy.md): the fragment's bytes are
            # committed — forward them downstream NOW, not at layer
            # completion (cut-through pipelining at hop granularity).
            self._forward_committed(lid, claims, msg.total_size,
                                    job=msg.job_id)
        if journal and not complete:
            # (The completing fragment skips the journal: its completion
            # already deleted the checkpoint files.)  Bytes first,
            # fsync'd; then merge ONLY this fragment's range into the
            # durable-coverage union under the lock — the meta can never
            # claim ranges whose .part writes are still pending in sibling
            # handler threads (which a crash would restore as zeros).
            off, data, total = frag.offset, bytes(data_mv), msg.total_size
            self.ckpt.write_bytes(lid, off, data, total)
            # The journaled range's crc32 rides the meta journal so
            # resume re-verifies the DISK bytes (integrity hardening).
            frag_crc = integrity.fragment_crc(data)
            with self._lock:
                raced_completion = lid in self.layers
                if not raced_completion:
                    durable = intervals.insert(
                        self._durable.get(lid, []), off, off + len(data)
                    )
                    self._durable[lid] = durable
                    crcs = self._durable_crcs.setdefault(lid, [])
                    if crcs is not None:
                        crcs.append((off, len(data), frag_crc))
                        if len(crcs) > _JOURNAL_CRC_MAX_RECORDS:
                            log.warn("journal CRC record cap hit; this "
                                     "layer's journal falls back to the "
                                     "un-verified legacy format",
                                     layerID=lid)
                            crcs = self._durable_crcs[lid] = None
                    crcs_snapshot = list(crcs) if crcs is not None else None
            if not raced_completion:
                self.ckpt.write_meta(lid, durable, total,
                                     frag_crcs=crcs_snapshot)
                with self._lock:
                    raced_completion = lid in self.layers
            if raced_completion:
                # Another thread completed the layer while we journaled;
                # drop the files our writes just resurrected.
                self.ckpt.complete(lid)
                with self._lock:
                    self._durable.pop(lid, None)
                    self._durable_crcs.pop(lid, None)
        if complete:
            self._ack_completed(lid)

    def _ingest_try_share(self, lid, ing, buf) -> bool:
        """Once per layer: try to make the ingest adopt the reassembly
        buffer (``ShardedLayerIngest.share_host_buffer``).  Memoized —
        only the first fragment pays the attempt; all later fragments
        read the cached verdict."""
        with self._ingests_lock:
            cached = self._ingest_share.get(lid)
            if cached is not None:
                return cached
            try:
                ok = bool(ing.share_host_buffer(buf))
            except Exception:  # noqa: BLE001 — sharing is an optimization
                ok = False
            self._ingest_share[lid] = ok
            return ok

    def _commit_fragment(self, lid, tok, total: int) -> bool:
        """Release this fragment's copy claim; promote the layer when
        coverage is full AND no sibling copy is in flight.  Returns
        whether THIS commit performed the promotion (exactly one does —
        the caller then stages + acks).

        SHARDED targets (docs/sharding.md) promote at SHARD coverage:
        the stamped spec's byte range is all this dest was ever promised
        — the holding is recorded shard-qualified (``meta.shard``), its
        buffer real only inside the range (the rest is unfaulted pages,
        so host RAM stays ≈ the shard fraction)."""
        with self._lock:
            entry = self._partial.get(lid)
            if entry is not None:
                entry[1].commit(tok)
            if lid in self.layers:
                return False  # a sibling already promoted (and acked)
            if entry is None:
                return False
            buf, cov = entry
            spec = self._shard_specs.get(lid, "")
            if spec:
                s0, s_sz = shard_range(spec, total)
                if not cov.complete_range(s0, s0 + s_sz):
                    return False
            elif not cov.complete(total):
                return False
            # Wire-codec identity (docs/codec.md): the stamp is
            # authoritative, the frames' advisory tag the fallback —
            # the promoted holding (and its ack) must carry the form
            # its bytes are actually in.
            codec = (self._layer_codecs.get(lid)
                     or self._frag_codec.pop(lid, ""))
            self.layers[lid] = LayerSrc(
                inmem_data=buf, data_size=total,
                meta=LayerMeta(location=LayerLocation.INMEM, shard=spec,
                               codec=codec),
            )
            del self._partial[lid]
            self._partial_total.pop(lid, None)
            self._durable.pop(lid, None)
            self._durable_crcs.pop(lid, None)
            frag_src = self._frag_src.pop(lid, None)
            self._frag_t.pop(lid, None)
            ph = self._phase.pop(lid, None)
        if codec:
            self._count_codec_delivery(lid, total, codec)
        if self.ckpt is not None:
            self.ckpt.complete(lid)
        extra = {}
        if ph is not None:
            span = _time.monotonic() - ph["t0"]
            extra = {
                "recv_span_ms": round(span * 1000, 1),
                "copy_ms": round(ph["copy_s"] * 1000, 1),
                "ingest_ms": round(ph["ingest_s"] * 1000, 1),
                "fragments": ph["frags"],
                "placed_fragments": ph.get("placed", 0),
                "gbps": round(total / max(span, 1e-9) / 1e9, 3),
            }
        telemetry.span_event(
            telemetry.span_id(self.node.my_id, lid), "wire_complete",
            node=self.node.my_id, src=frag_src, dest=self.node.my_id,
            layer=lid, bytes=total, codec=codec, shard=spec)
        log.info("layer fully received", layer=lid, total_bytes=total,
                 **extra)
        return True

    def _ack_completed(self, lid) -> None:
        """Stage (finalizing any incremental ingest) + ack a completed
        layer; also the re-ack path for a re-plan duplicate.

        Integrity gate FIRST: the assembled layer verifies against the
        leader-stamped digest BEFORE any device placement, streamed boot
        staging, or ack — a mismatch re-opens the covered intervals
        (the layer demotes back to "missing" and the node re-announces,
        so the leader re-plans the bytes) instead of acking corruption
        into the goal state."""
        with self._lock:
            src = self.layers.get(lid)
        if src is None:
            return
        if not self._digest_gate(lid, src):
            return
        # Content-delta (docs/codec.md): a stream-verified delta
        # holding reconstructs to canonical bytes before staging/ack.
        src = self._finalize_delta(lid, src)
        if src is None:
            return
        # Pair-lifecycle span (docs/observability.md): the integrity
        # gate passed — wire_complete→verified is the digest cost (zero
        # when no digest was stamped; the phase collapses in the walk).
        span = telemetry.span_id(self.node.my_id, lid)
        telemetry.span_event(span, "verified", node=self.node.my_id,
                             dest=self.node.my_id, layer=lid)
        with self._ingests_lock:
            self._ingest_done.add(lid)
            ing = self._ingests.pop(lid, None)
            self._ingest_share.pop(lid, None)
        shard = src.meta.shard
        if shard:
            # A SHARD holding stays host-resident and un-booted: its
            # buffer is only real inside the shard's range — the full
            # layer materializes on-mesh via the shard gather when the
            # target sharding demands it (docs/sharding.md).
            loc = LayerLocation.INMEM
        else:
            loc = self._stage_to_hbm(lid, src, ingest=ing)
            # Mid-wire boot staging: this layer's decode/upload overlaps
            # the layers still on the wire (runtime/stream_boot.py).
            self._boot_stream_submit(lid, src)
        telemetry.span_event(span, "staged", node=self.node.my_id,
                             dest=self.node.my_id, layer=lid,
                             shard=shard)
        self._send_ack(lid, loc, shard=shard)
        if shard:
            # Fabric-assisted pod delivery (docs/fabric.md): a verified
            # pod slice enters the on-mesh reconstruction — the FULL
            # tree acks separately once the gather verifies.  No-op for
            # plain sharded targets (no pod stamp).
            self._start_pod_collect(lid, src)
        # Stamp-before-donor race: this completed layer may be the
        # donor a stamped-but-missing layer was waiting for.
        self._resolve_pending_for_layer(lid)

    def _demote_corrupt_layer(self, lid) -> None:
        """Mode-3 demotion: beyond the store entry, also re-open the
        layer's intervals (partial state), wipe its journal, and poison
        any incremental device ingest — a re-delivery starts clean."""
        super()._demote_corrupt_layer(lid)
        with self._lock:
            self._partial.pop(lid, None)
            self._partial_total.pop(lid, None)
            self._durable.pop(lid, None)
            self._durable_crcs.pop(lid, None)
            self._frag_src.pop(lid, None)
            self._frag_t.pop(lid, None)
        with self._ingests_lock:
            self._ingest_done.discard(lid)
            self._ingest_share.pop(lid, None)
            ing = self._ingests.pop(lid, None)
        if ing is not None:
            try:
                ing.fail()
            except Exception:  # noqa: BLE001 — poison is best-effort
                pass
        if self.ckpt is not None:
            self.ckpt.complete(lid)

    def _digest_gate(self, lid, src) -> bool:
        """Verify a completed layer's digest; on mismatch DEMOTE it
        (``_demote_corrupt_layer``) and re-announce so the leader
        re-plans the whole layer (mode-3 fragments come from several
        senders, so there is no one peer to NACK).  Bounded: after
        ``_DIGEST_MAX_RETRIES`` rounds the layer stays un-acked and the
        failure is loud — corrupt SOURCE data must never converge to a
        successful run."""
        if src.inmem_data is None:
            return True  # no host bytes to hash (fabric HBM delivery)
        shard = src.meta.shard
        if shard:
            # A shard verifies over EXACTLY its range's bytes, against
            # the stamped RANGE digest — the full layer never has to be
            # held here (docs/sharding.md).
            s0, s_sz = shard_range(shard, src.data_size)
            view = memoryview(src.inmem_data)[s0:s0 + s_sz]
        else:
            view = memoryview(src.inmem_data)
        if self._verify_layer_digest(lid, view, shard=shard,
                                     codec=src.meta.codec):
            return True
        self._demote_corrupt_layer(lid)
        if self._bump_digest_retry(lid):
            log.error("re-opening layer after digest mismatch; "
                      "re-announcing for a re-plan", layerID=lid)
            self._request_replan()
        return False

    def handle_flow_retransmit(self, msg: FlowRetransmitMsg) -> None:
        import time as _time

        if self._fence_stale(msg):
            return
        t0 = _time.monotonic()
        log.info(
            "start sending layer",
            layer=msg.layer_id, dest=msg.dest_id, size=msg.data_size, rate=msg.rate,
        )
        # Elastic membership (docs/membership.md): a flow command for a
        # JUST-JOINED dest can overtake the roster notice carrying its
        # address (the handler pool doesn't order across message
        # types).  An unknown-peer failure here waits briefly for the
        # address to land instead of dropping the pair — re-sent
        # fragments from a mid-way retry are absorbed by interval
        # reassembly like any duplicate.
        for attempt in range(40):
            try:
                handle_flow_retransmit(
                    self.node, self.layers, self._lock,
                    lambda lid, dest: fetch_from_client(self.node, lid,
                                                        dest), msg,
                    revokes=self.revokes, codecs=self.codec_plane,
                )
                break
            except (ConnectionError, KeyError) as e:
                # Only the MISSING-ADDRESS case retries; a failure with
                # the dest already in the registry is a real defect (or
                # a dead peer) and must surface, not be masked by a 2 s
                # busy-wait.
                try:
                    unknown = (msg.dest_id
                               not in self.node.transport.addr_registry)
                except (AttributeError, TypeError):
                    unknown = False
                if not unknown:
                    raise
                if attempt == 39:
                    log.error("flow send dest unreachable past the "
                              "roster-wait budget; dropping (a re-plan "
                              "re-dispatches)", dest=msg.dest_id,
                              layerID=msg.layer_id, err=repr(e))
                    break
                trace.count("membership.roster_waits")
                _time.sleep(0.05)
        dur = _time.monotonic() - t0
        log.info(
            "finished sending layer",
            layer=msg.layer_id, dest=msg.dest_id,
            send_dur_ms=round(dur * 1000, 3),
            throughput_mibps=round(msg.data_size / max(dur, 1e-9) / (1 << 20), 2),
        )
