"""Failure detection: heartbeats + timeout-based crash detection.

The reference declares failure handling and leaves it TODO — ``crash(n
node)`` is an empty interface stub (``/root/reference/distributor/
node.go:218-220``) and there are no timeouts or retries anywhere.  This
module fills that gap:

- :class:`HeartbeatSender` — a receiver-side thread beaconing
  ``HeartbeatMsg`` to the leader on a fixed interval.
- :class:`FailureDetector` — a leader-side monitor; any message from a
  node refreshes its lease (heartbeats just guarantee traffic during long
  silences), and a node silent past the timeout is declared crashed, once,
  via the leader's ``crash()`` hook.

Both are opt-in (interval/timeout 0 disables them), preserving exact
reference behavior by default.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ..core.types import NodeID
from ..transport.base import Transport
from ..transport.messages import HeartbeatMsg
from ..utils.logging import log


class HeartbeatSender:
    """Beacons ``HeartbeatMsg(my_id)`` to the leader every ``interval``
    seconds until stopped.  Send failures are logged, not raised — a
    temporarily unreachable leader must not kill the beacon."""

    def __init__(
        self,
        transport: Transport,
        my_id: NodeID,
        leader_id: NodeID,
        interval: float,
        leader_fn: Optional[Callable[[], NodeID]] = None,
    ):
        """``leader_fn``: live leader lookup, re-read every beat — after
        an epoch-fenced failover (docs/failover.md) the beacon must
        follow the NEW leader, not keep feeding a dead seat's queue."""
        self._transport = transport
        self._my_id = my_id
        self._leader_fn = leader_fn if leader_fn is not None else (
            lambda: leader_id)
        self._interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._interval <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"heartbeat-{self._my_id}")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self._transport.send(self._leader_fn(),
                                     HeartbeatMsg(self._my_id))
            except (OSError, KeyError) as e:
                log.warn("heartbeat send failed", err=repr(e))

    def stop(self) -> None:
        self._stop.set()


class FailureDetector:
    """Leader-side liveness monitor.

    ``touch(node_id)`` refreshes a node's lease (call it for *every*
    message received from the node); a monitor thread scans every
    ``timeout / 4`` seconds and reports nodes silent for longer than
    ``timeout`` to ``on_crash``, exactly once per node.
    """

    def __init__(self, timeout: float, on_crash: Callable[[NodeID], None]):
        self._timeout = timeout
        self._on_crash = on_crash
        self._last_seen: Dict[NodeID, float] = {}
        self._dead: "set[NodeID]" = set()
        # Cleanly-departed nodes (elastic membership, docs/membership.md):
        # unlike forget(), a removed node's in-flight heartbeats must not
        # re-arm a lease — a drained node that then exits would be
        # declared crashed by the very beacon it sent while draining.
        self._removed: "set[NodeID]" = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        """Start (or RESTART) the monitor.  Restartable on purpose: a
        stood-down sub-leader whose group re-forms (docs/membership.md)
        re-arms member liveness on the same detector instance.  A
        restart during the stop window arms a FRESH stop event — the
        prior thread captured the old one and exits on its own, so a
        re-arm can never be silently swallowed by a still-draining
        monitor."""
        if self._timeout <= 0:
            return
        with self._lock:
            if (self._thread is not None and self._thread.is_alive()
                    and not self._stop.is_set()):
                return  # already monitoring
            if self._stop.is_set():
                self._stop = threading.Event()
            thread = threading.Thread(target=self._run,
                                      args=(self._stop,), daemon=True,
                                      name="detector")
            self._thread = thread
        thread.start()

    def touch(self, node_id: NodeID) -> None:
        with self._lock:
            if node_id not in self._dead and node_id not in self._removed:
                self._last_seen[node_id] = time.monotonic()

    def forget(self, node_id: NodeID) -> None:
        """Stop monitoring a node (e.g. after its assignment was dropped)."""
        with self._lock:
            self._last_seen.pop(node_id, None)

    def silent_ages(self) -> Dict[NodeID, float]:
        """Seconds of silence per monitored (not dead/removed) node —
        the autonomy engine's death-suspicion signal: a node silent for
        a large fraction of ``timeout`` gets its unique holdings
        proactively re-homed BEFORE the crash path fires
        (docs/autonomy.md).  Read-only; never mutates leases."""
        now = time.monotonic()
        with self._lock:
            return {n: max(0.0, now - t)
                    for n, t in self._last_seen.items()}

    @property
    def timeout(self) -> float:
        return self._timeout

    def remove(self, node_id: NodeID) -> None:
        """Permanently stop monitoring a cleanly-departed node: the
        lease is dropped AND later touches (straggler heartbeats, a
        queued announce) are ignored until :meth:`revive` — a clean
        leave can never race a false ``crash()``."""
        with self._lock:
            self._last_seen.pop(node_id, None)
            self._removed.add(node_id)

    def revive(self, node_id: NodeID) -> None:
        """Re-admit a declared-dead node (a restarted process announcing
        again) and restart its lease.  If the announce was actually a stale
        queued message, the fresh lease simply expires again."""
        with self._lock:
            self._dead.discard(node_id)
            self._removed.discard(node_id)
            self._last_seen[node_id] = time.monotonic()

    def _run(self, stop: threading.Event) -> None:
        scan = self._timeout / 4
        while not stop.wait(scan):
            now = time.monotonic()
            with self._lock:
                expired = [
                    nid
                    for nid, seen in self._last_seen.items()
                    if now - seen > self._timeout
                ]
                for nid in expired:
                    del self._last_seen[nid]
                    self._dead.add(nid)
            for nid in expired:
                log.error("node declared crashed", node=nid,
                          timeout_s=self._timeout)
                try:
                    self._on_crash(nid)
                except Exception as e:  # noqa: BLE001 — keep monitoring
                    log.error("crash handler failed", node=nid, err=repr(e))

    def is_dead(self, node_id: NodeID) -> bool:
        with self._lock:
            return node_id in self._dead

    def stop(self) -> None:
        with self._lock:
            self._stop.set()
