"""Closed-loop fleet autonomy: the leader-side policy engine (docs/autonomy.md).

Every sensor and every actuator the platform grew over PRs 12-15
already exists — the health timeline flags straggler links, per-replica
serve p99 rides the metrics plane, joins/drains/repairs/rollouts are
all leader chokepoints — but an operator still connects them.  This
module closes the loop: declarative rules (the config ``Policies``
block) are evaluated against the already-folded cluster signals on
every metrics interval, and fire the SAME internal chokepoints the CLI
verbs use.  Nothing here invents a new actuator; the engine is a
disciplined operator that never sleeps.

The four rule kinds (the full signal→action table is docs/autonomy.md):

- ``grow_on_serve_pressure``: a serving replica whose interval p99
  sustains above the bar for ``Sustain`` consecutive intervals gets its
  replica set grown — a join+refill job copies its holdings onto a
  placeable spare (``membership.spares``), origin avoided.
- ``replan_straggler``: a ``straggler_link`` health event demotes that
  link's modeled rate in the flow solver's inputs and re-plans — the
  solver prices alternatives and routes in-flight pairs around the slow
  path.  ``link_recovered`` lifts the demotion (hysteresis: only after
  the recovery event, never mid-flap).
- ``quarantine_breacher``: ``Breaches`` consecutive SLO-breaching
  intervals quarantine the replica from serving rotation — the
  leader-side serve-rotation mask honored by the rollout A/B split and
  soak baselining.  Quarantine is a mask, not a prune: the replica's
  bytes stay planned and its lease stays live.
- ``rehome_on_loss``: a node silent for ``SuspectFrac`` of the failure
  timeout gets its unique holdings proactively re-homed BEFORE the
  crash path fires — the repair job races the detector, so a real
  death costs re-sent bytes already moving instead of starting cold.
  (A planned drain re-homes synchronously through the membership
  plane's own chokepoint — PR 12 — and needs no rule.)

Every decision — fired, skipped, or completed — is a first-class
audited record; the engine's whole state (armed rules, cooldowns,
quarantine mask, in-flight actions, audit ring) REPLACE-replicates via
``ControlDeltaMsg`` kind ``policy`` + the snapshot's ``Policy`` section
so a promoted standby inherits armed rules and completes in-flight
actions at the bumped epoch instead of double-firing or dropping them.
Every fired action stamps a ``policy:<id>`` span so RUN_REPORT
attributes what the fleet did to itself and why.

Kill-switches (docs/autonomy.md): ``DLD_POLICY=0`` (env, hard — the
engine stops acting immediately, mid-action included; in-flight JOBS
keep moving because the job plane owns them, but no new action fires)
and the token-gated ``PolicyCtlMsg`` disable verb (soft — rules keep
sensing so streaks/cooldowns stay warm, actions hold).

Lock discipline: the engine lock is LEAF-most, same rule as
``RolloutDriver`` — no leader/membership/job call is ever made while
holding it.  Decisions are computed under the lock and EXECUTED
outside it.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..utils import telemetry, trace
from ..utils.logging import log
from .rollout import serve_view

# The action vocabulary: every audited record's "Action" field is one
# of these, and the tier-1 static drift check pins each to a live
# execution site in this module and a row in docs/autonomy.md — the
# audit trail can never silently diverge from what the engine can do.
POLICY_ACTIONS = ("grow", "replan", "quarantine", "rehome")

AUDIT_RING = 128

# Rule grammar: each Policies entry is {"Rule": <kind>, ...params}.
# _RULE_PARAMS maps kind -> {param: (validator, default)}; admission
# refuses unknown kinds, unknown params, and out-of-range values
# LOUDLY (ValueError) — a bad rule must fail at config parse, never at
# fire time (docs/autonomy.md).


def _pos(x):
    v = float(x)
    if v <= 0:
        raise ValueError("must be > 0")
    return v


def _nonneg(x):
    v = float(x)
    if v < 0:
        raise ValueError("must be >= 0")
    return v


def _count(x):
    v = int(x)
    if v < 1:
        raise ValueError("must be >= 1")
    return v


def _nonneg_int(x):
    v = int(x)
    if v < 0:
        raise ValueError("must be >= 0")
    return v


def _frac(x):
    v = float(x)
    if not 0 < v <= 1:
        raise ValueError("must be in (0, 1]")
    return v


def _open_frac(x):
    v = float(x)
    if not 0 < v < 1:
        raise ValueError("must be in (0, 1)")
    return v


_RULE_PARAMS: Dict[str, Dict[str, tuple]] = {
    "grow_on_serve_pressure": {
        "P99Ms": (_pos, None),          # required: the latency bar
        "Sustain": (_count, 2),         # consecutive breaching intervals
        "CooldownS": (_nonneg, 30.0),
        "MaxGrows": (_nonneg_int, 1),   # grows per replica (0 = unlimited)
    },
    "replan_straggler": {
        "FloorFrac": (_frac, 0.1),      # demotion floor vs modeled rate
        "CooldownS": (_nonneg, 10.0),
        "LiftOnRecovery": (bool, True),
    },
    "quarantine_breacher": {
        "P99Ms": (_pos, None),          # required: the latency bar
        "Breaches": (_count, 2),        # consecutive breaching intervals
        "CooldownS": (_nonneg, 60.0),
    },
    "rehome_on_loss": {
        "SuspectFrac": (_open_frac, 0.5),  # of the failure timeout
        "CooldownS": (_nonneg, 30.0),
    },
}

_REQUIRED = {kind: {p for p, (_, d) in params.items() if d is None}
             for kind, params in _RULE_PARAMS.items()}


def validate_policies(raw) -> List[dict]:
    """Admission-time validation: raw config ``Policies`` list →
    normalized rule dicts (defaults filled, numbers coerced).  Raises
    ``ValueError`` naming the offending rule index and reason — a bad
    rule is refused LOUDLY at config parse, never deferred to fire
    time."""
    if raw is None:
        return []
    if not isinstance(raw, (list, tuple)):
        raise ValueError("Policies must be a list of rule objects")
    out: List[dict] = []
    for i, entry in enumerate(raw):
        where = f"Policies[{i}]"
        if not isinstance(entry, dict):
            raise ValueError(f"{where}: not an object")
        kind = entry.get("Rule")
        if kind not in _RULE_PARAMS:
            raise ValueError(
                f"{where}: unknown rule {kind!r} "
                f"(known: {sorted(_RULE_PARAMS)})")
        params = _RULE_PARAMS[kind]
        unknown = set(entry) - set(params) - {"Rule"}
        if unknown:
            raise ValueError(
                f"{where} ({kind}): unknown params {sorted(unknown)}")
        missing = _REQUIRED[kind] - set(entry)
        if missing:
            raise ValueError(
                f"{where} ({kind}): missing required {sorted(missing)}")
        rule = {"Rule": kind}
        for p, (conv, dflt) in params.items():
            if p in entry:
                try:
                    rule[p] = conv(entry[p])
                except (TypeError, ValueError) as e:
                    raise ValueError(f"{where} ({kind}).{p}: {e}")
            else:
                rule[p] = dflt
        out.append(rule)
    return out


def env_enabled() -> bool:
    """The hard kill-switch: ``DLD_POLICY`` unset/1/on = armed; 0/false/
    off = manual mode, checked on EVERY tick so flipping the env var
    mid-run drops the fleet to manual at the next interval."""
    return os.environ.get("DLD_POLICY", "1").lower() not in (
        "0", "false", "off")


class PolicyEngine:
    """The leader's autonomy state machine.  All sensing enters through
    :meth:`tick` (the metrics-interval callback); all acting leaves
    through the leader's own chokepoints (``submit_job``,
    ``policy_demote_link``, the serve-rotation mask read back via
    ``quarantined()``)."""

    def __init__(self, leader):
        self.leader = leader
        self._lock = threading.Lock()  # LEAF lock: no leader calls under it
        self._rules: List[dict] = []
        self._enabled = True           # the soft (operator) switch
        self._seq = 0
        self._cooldowns: Dict[str, float] = {}   # "rule|target" -> mono t
        self._streaks: Dict[str, int] = {}       # "rule|target" -> count
        self._quarantined: Set[int] = set()
        self._demoted: Dict[str, dict] = {}      # "s->d" -> {"Bps", "Frac"}
        self._inflight: Dict[str, dict] = {}     # action id -> record
        self._grown: Dict[str, int] = {}         # target node -> grow count
        self._audit: List[dict] = []
        self._last_serve: Dict[int, dict] = {}   # node -> serve_view snap

    # --------------------------------------------------------------- arming

    def arm(self, policies) -> List[dict]:
        """Install validated rules (idempotent REPLACE) and replicate.
        Raises ValueError on a bad block — admission, not fire time."""
        rules = validate_policies(policies)
        with self._lock:
            self._rules = rules
        if rules:
            log.info("policy rules armed",
                     rules=[r["Rule"] for r in rules])
        self._publish()
        return rules

    def set_enabled(self, on: bool) -> None:
        """The soft switch (PolicyCtlMsg enable/disable — token-gated
        at the leader handler).  Sensing continues either way; actions
        hold while disabled."""
        with self._lock:
            self._enabled = bool(on)
        log.warn("policy actioning " + ("ENABLED" if on else
                                        "DISABLED (manual mode)"))
        self._publish()

    def active(self) -> bool:
        with self._lock:
            armed = bool(self._rules) and self._enabled
        return armed and env_enabled()

    def quarantined(self) -> Set[int]:
        """The serve-rotation mask (docs/autonomy.md): replicas the A/B
        split and rollout soak baselining must route around."""
        with self._lock:
            return set(self._quarantined)

    def demotions(self) -> Dict[Tuple[int, int], int]:
        """Installed link demotions as the flow solver's
        ``link_demotions`` input: (src, dest) -> modeled bytes/s."""
        with self._lock:
            out = {}
            for key, rec in self._demoted.items():
                s, _, d = key.partition("->")
                out[(int(s), int(d))] = int(rec["Bps"])
            return out

    # -------------------------------------------------------------- sensing

    def tick(self, node_id: int, snap: dict, events) -> None:
        """One metrics interval: fold this reporter's serve signals,
        react to new health events, sweep suspicion and in-flight
        actions.  Called on the leader's message loop with NO leader
        lock held."""
        with self._lock:
            if not self._rules:
                return
        self._complete_inflight()
        acting = self.active()
        decisions: List[dict] = []
        with self._lock:
            now = time.monotonic()
            for rule in self._rules:
                kind = rule["Rule"]
                if kind in ("grow_on_serve_pressure",
                            "quarantine_breacher"):
                    d = self._sense_serve_locked(rule, node_id, snap, now)
                    if d:
                        decisions.append(d)
                elif kind == "replan_straggler":
                    decisions.extend(self._sense_links_locked(
                        rule, events or (), now))
                elif kind == "rehome_on_loss":
                    pass  # swept below, outside the per-report sensing
            # Serve-view snapshot advances exactly once per report,
            # AFTER every serve-keyed rule diffed against the old one.
            view = serve_view(snap, int(node_id))
            if view["requests"] or view["hist"]:
                self._last_serve[int(node_id)] = view
        decisions.extend(self._sense_loss())
        if not acting:
            # Manual mode (env or operator switch): streaks/cooldowns
            # stayed warm above, but nothing fires — each held decision
            # is audited as such so the kill-switch leaves a trail.
            for dec in decisions:
                self._audit_add(dict(dec, Outcome="held_manual"))
            if decisions:
                self._publish()
            return
        for dec in decisions:
            self._execute(dec)

    def _sense_serve_locked(self, rule: dict, node: int, snap: dict,
                            now: float) -> Optional[dict]:
        """Interval p99 vs the rule's bar for ONE serving replica; a
        sustained breach streak crosses the rule's threshold into a
        grow or quarantine decision.  Lock held."""
        node = int(node)
        view = serve_view(snap, node)
        prev = self._last_serve.get(node)
        if prev is None:
            return None
        d_req = view["requests"] - prev["requests"]
        delta = telemetry.hist_delta(view["hist"], prev["hist"])
        p99 = telemetry.percentile_from_hist(delta, 0.99)
        if d_req <= 0 and p99 is None:
            return None  # not serving this interval: no verdict
        kind = rule["Rule"]
        key = f"{kind}|{node}"
        breach = p99 is not None and p99 > rule["P99Ms"]
        if not breach:
            self._streaks.pop(key, None)
            return None
        streak = self._streaks.get(key, 0) + 1
        self._streaks[key] = streak
        bar = rule["Sustain"] if kind == "grow_on_serve_pressure" \
            else rule["Breaches"]
        if streak < bar:
            return None
        if self._cooldowns.get(key, 0.0) > now:
            return None
        if kind == "quarantine_breacher" and node in self._quarantined:
            return None
        if kind == "grow_on_serve_pressure":
            cap = rule["MaxGrows"]
            if cap and self._grown.get(str(node), 0) >= cap:
                return None
        self._cooldowns[key] = now + rule["CooldownS"]
        self._streaks.pop(key, None)
        action = ("grow" if kind == "grow_on_serve_pressure"
                  else "quarantine")
        return {"Action": action, "Rule": kind, "Target": node,
                "Reason": f"p99 {p99}ms > {rule['P99Ms']}ms "
                          f"sustained {streak} intervals"}

    def _sense_links_locked(self, rule: dict, events,
                            now: float) -> List[dict]:
        """``straggler_link`` → demote+replan decision, debounced by the
        rule cooldown (a flapping link is re-planned once, not toggled
        every interval); ``link_recovered`` → lift decision.  Lock
        held."""
        out: List[dict] = []
        for ev in events:
            kind = ev.get("kind")
            link = str(ev.get("link") or "")
            if not link:
                continue
            key = f"replan_straggler|{link}"
            if kind == "straggler_link":
                if link in self._demoted:
                    continue  # already routed around — flap absorbed
                if self._cooldowns.get(key, 0.0) > now:
                    continue
                self._cooldowns[key] = now + rule["CooldownS"]
                modeled = int(ev.get("modeled_bps") or 0)
                achieved = int(ev.get("achieved_bps") or 0)
                floor = int(modeled * rule["FloorFrac"])
                bps = max(achieved, floor, 1)
                out.append({
                    "Action": "replan", "Rule": rule["Rule"],
                    "Target": link, "Bps": bps,
                    "Reason": f"straggler frac={ev.get('frac')} "
                              f"intervals={ev.get('intervals')}; "
                              f"demote {modeled}->{bps} B/s"})
            elif kind == "link_recovered" and rule["LiftOnRecovery"]:
                if link not in self._demoted:
                    continue
                # Lift is NOT cooled down (recovery is the hysteresis:
                # the health plane only emits it after the measured rate
                # held above threshold), but the re-demote of a flapping
                # link IS, via the straggler branch above.
                out.append({
                    "Action": "replan", "Rule": rule["Rule"],
                    "Target": link, "Lift": True,
                    "Reason": f"recovered frac={ev.get('frac')} after "
                              f"{ev.get('intervals')} breach intervals"})
        return out

    def _sense_loss(self) -> List[dict]:
        """Death suspicion: nodes silent for ``SuspectFrac`` of the
        failure timeout get a proactive re-home decision.  Reads the
        detector WITHOUT the engine lock (leaf discipline), then takes
        it only to stamp cooldowns."""
        with self._lock:
            rule = next((r for r in self._rules
                         if r["Rule"] == "rehome_on_loss"), None)
        if rule is None:
            return []
        det = getattr(self.leader, "detector", None)
        if det is None or det.timeout <= 0:
            return []
        bar = rule["SuspectFrac"] * det.timeout
        ages = det.silent_ages()
        me = self.leader.node.my_id
        out: List[dict] = []
        with self._lock:
            now = time.monotonic()
            for node, age in ages.items():
                if age < bar or int(node) == int(me):
                    continue
                key = f"rehome_on_loss|{node}"
                if self._cooldowns.get(key, 0.0) > now:
                    continue
                self._cooldowns[key] = now + rule["CooldownS"]
                out.append({
                    "Action": "rehome", "Rule": rule["Rule"],
                    "Target": int(node),
                    "Reason": f"silent {age:.1f}s > "
                              f"{rule['SuspectFrac']:.2f}x timeout "
                              f"({det.timeout:.1f}s)"})
        return out

    # --------------------------------------------------------------- acting

    def _execute(self, dec: dict) -> None:
        """Fire one decision through the leader's chokepoints.  Engine
        lock NOT held around any leader call."""
        with self._lock:
            self._seq += 1
            action_id = f"{dec['Action']}-{self._seq}"
        rec = dict(dec, ID=action_id, Epoch=int(self.leader.epoch))
        span = f"policy:{action_id}"
        telemetry.span_event(span, "planned", node=self.leader.node.my_id,
                             action=dec["Action"], rule=dec["Rule"],
                             target=str(dec["Target"]),
                             reason=dec["Reason"])
        trace.count(f"policy.action_{dec['Action']}")
        log.warn("policy action", **{k.lower(): v for k, v in rec.items()})
        done, detail = self._fire(rec)
        rec["Detail"] = detail
        if done:
            rec["Outcome"] = "done"
            telemetry.span_event(span, "acked",
                                 node=self.leader.node.my_id,
                                 detail=detail)
            self._audit_add(rec)
        elif rec.get("Job"):
            rec["Outcome"] = "inflight"
            with self._lock:
                self._inflight[action_id] = rec
            self._audit_add(dict(rec))
        else:
            rec["Outcome"] = "skipped"
            telemetry.span_event(span, "acked",
                                 node=self.leader.node.my_id,
                                 detail=detail, skipped=True)
            self._audit_add(rec)
        self._publish()

    def _fire(self, rec: dict):
        """Dispatch one action record to its actuator.  Returns
        (completed_now, detail); a job-backed action completes later in
        :meth:`_complete_inflight`."""
        action = rec["Action"]
        if action == "quarantine":
            with self._lock:
                self._quarantined.add(int(rec["Target"]))
            # The mask is read by the rollout driver's pool derivation
            # and soak baselining on their next evaluation — no push
            # needed (docs/autonomy.md).
            return True, "serve-rotation mask set"
        if action == "replan":
            s, _, d = str(rec["Target"]).partition("->")
            demote = getattr(self.leader, "policy_demote_link", None)
            if demote is None:
                return True, "no link model in this mode (noop)"
            if rec.get("Lift"):
                with self._lock:
                    self._demoted.pop(str(rec["Target"]), None)
                self.leader.policy_lift_link(int(s), int(d))
                return True, "demotion lifted, re-planned"
            with self._lock:
                self._demoted[str(rec["Target"])] = {
                    "Bps": int(rec["Bps"])}
            demote(int(s), int(d), int(rec["Bps"]))
            return True, f"link demoted to {rec['Bps']} B/s, re-planned"
        if action == "grow":
            jid = self.leader.policy_grow(int(rec["Target"]),
                                          rec["ID"])
            if not jid:
                return True, "no placeable spare (skipped)"
            with self._lock:
                key = str(rec["Target"])
                self._grown[key] = self._grown.get(key, 0) + 1
            rec["Job"] = jid
            return False, f"join+refill {jid} submitted"
        if action == "rehome":
            jid = self.leader.policy_rehome(int(rec["Target"]),
                                            rec["ID"])
            if not jid:
                return True, "no unique holdings at risk (skipped)"
            rec["Job"] = jid
            return False, f"repair {jid} submitted"
        return True, f"unknown action {action!r} (noop)"

    def _complete_inflight(self) -> None:
        """Close out job-backed actions whose job finished — the span's
        terminal phase stamps HERE, so RUN_REPORT shows when the fleet's
        own action landed, not just when it was decided."""
        with self._lock:
            pending = [(aid, rec.get("Job"))
                       for aid, rec in self._inflight.items()]
        if not pending:
            return
        closed = []
        for aid, jid in pending:
            job = self.leader.jobs.get(jid) if jid else None
            if job is not None and job.state == "done":
                closed.append((aid, jid, job.dropped_pairs))
        if not closed:
            return
        for aid, jid, dropped in closed:
            with self._lock:
                rec = self._inflight.pop(aid, None)
            if rec is None:
                continue
            rec["Outcome"] = "done" if not dropped else "done_degraded"
            telemetry.span_event(f"policy:{aid}", "acked",
                                 node=self.leader.node.my_id,
                                 job=jid, dropped=int(dropped))
            log.info("policy action completed", id=aid, job=jid)
            self._audit_add(rec)
        self._publish()

    # ------------------------------------------------- replication/failover

    def _audit_add(self, rec: dict) -> None:
        rec = dict(rec, TMs=round(time.time() * 1000.0, 1))
        with self._lock:
            self._audit.append(rec)
            del self._audit[:-AUDIT_RING]

    def to_json(self) -> dict:
        """Full engine state, REPLACE semantics (kind ``policy`` +
        snapshot ``Policy`` section).  Cooldowns ship as REMAINING
        seconds — monotonic clocks don't replicate; the successor
        re-arms them against its own clock (a small skew costs at most
        one early/late fire, never a double)."""
        with self._lock:
            now = time.monotonic()
            return {
                "Enabled": bool(self._enabled),
                "Rules": [dict(r) for r in self._rules],
                "Cooldowns": {k: round(max(0.0, t - now), 3)
                              for k, t in self._cooldowns.items()
                              if t > now},
                "Streaks": dict(self._streaks),
                "Quarantined": sorted(self._quarantined),
                "Demoted": {k: dict(v) for k, v in self._demoted.items()},
                "Inflight": {k: dict(v)
                             for k, v in self._inflight.items()},
                "Grown": dict(self._grown),
                "Seq": int(self._seq),
                "Audit": [dict(a) for a in self._audit[-16:]],
            }

    def load(self, d: dict) -> None:
        """Restore from a replicated snapshot/delta (standby side, and
        ``adopt_shadow`` on the promoted leader)."""
        d = d or {}
        with self._lock:
            now = time.monotonic()
            self._enabled = bool(d.get("Enabled", True))
            try:
                self._rules = validate_policies(d.get("Rules"))
            except ValueError:
                # A replicated rule the OLD leader validated must not
                # brick the successor on vocabulary skew: drop it
                # loudly, keep the rest of the state.
                log.error("replicated policy rules failed validation; "
                          "dropping rules, keeping state")
                self._rules = []
            self._cooldowns = {str(k): now + float(v)
                               for k, v in (d.get("Cooldowns") or
                                            {}).items()}
            self._streaks = {str(k): int(v)
                             for k, v in (d.get("Streaks") or {}).items()}
            self._quarantined = {int(n)
                                 for n in d.get("Quarantined") or ()}
            self._demoted = {str(k): dict(v) for k, v in
                             (d.get("Demoted") or {}).items()}
            self._inflight = {str(k): dict(v) for k, v in
                              (d.get("Inflight") or {}).items()}
            self._grown = {str(k): int(v)
                           for k, v in (d.get("Grown") or {}).items()}
            self._seq = int(d.get("Seq", 0))
            self._audit = [dict(a) for a in d.get("Audit") or ()]

    def resume_from_takeover(self) -> None:
        """Promoted-leader resume (leader.resume_from_takeover): re-apply
        the inherited demotions/mask idempotently and COMPLETE in-flight
        actions at the bumped epoch — an action whose job already rode
        the replicated job table resumes through the job plane (no
        double fire); one whose job record never made it is re-submitted
        through the same chokepoint it originally used."""
        with self._lock:
            demoted = {k: dict(v) for k, v in self._demoted.items()}
            inflight = [dict(rec) for rec in self._inflight.values()]
        demote = getattr(self.leader, "policy_demote_link", None)
        for key, rec in demoted.items():
            if demote is None:
                break
            s, _, d = key.partition("->")
            demote(int(s), int(d), int(rec["Bps"]))
        for rec in inflight:
            jid = rec.get("Job")
            if jid and self.leader.jobs.get(jid) is not None:
                continue  # the job plane carries it — resumed, not re-fired
            aid = rec.get("ID", "?")
            log.warn("re-submitting policy action lost in failover",
                     id=aid, job=jid)
            action, target = rec.get("Action"), rec.get("Target")
            new_jid = ""
            if action == "grow":
                new_jid = self.leader.policy_grow(int(target), aid)
            elif action == "rehome":
                new_jid = self.leader.policy_rehome(int(target), aid)
            with self._lock:
                if aid in self._inflight:
                    if new_jid:
                        self._inflight[aid]["Job"] = new_jid
                        self._inflight[aid]["Epoch"] = int(
                            self.leader.epoch)
                    else:
                        self._inflight.pop(aid, None)
        if demoted or inflight:
            self._audit_add({"Action": "resume", "Rule": "-",
                             "Target": "-", "Outcome": "done",
                             "Reason": f"takeover: {len(demoted)} "
                                       f"demotions re-applied, "
                                       f"{len(inflight)} actions "
                                       f"inherited",
                             "Epoch": int(self.leader.epoch)})
        self._publish()

    def _publish(self) -> None:
        """Replicate the full state (REPLACE) — called after every
        mutation, outside the engine lock."""
        try:
            self.leader._replicate("policy", **self.to_json())
        except Exception as e:  # noqa: BLE001 — replication is advisory
            log.warn("policy state replication failed", err=repr(e))

    def table(self) -> dict:
        """The -policies verb's reply payload: the replicated state plus
        the live switches, JSON-clean."""
        out = self.to_json()
        out["EnvEnabled"] = env_enabled()
        out["Active"] = self.active()
        return out
