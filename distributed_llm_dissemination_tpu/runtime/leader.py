"""Leader state machines: the four dissemination schedulers.

Re-design of the reference's leaders (``/root/reference/distributor/node.go``):

- **Mode 0** ``LeaderNode`` — naive broadcast: once every assigned node has
  announced, the leader itself sends every assigned layer to every assignee
  (node.go:228-469).
- **Mode 1** ``RetransmitLeaderNode`` — peer retransmission: layers already
  owned by some peer are forwarded by that peer instead, offloading the
  leader's NIC (node.go:472-626).
- **Mode 2** ``PullRetransmitLeaderNode`` — pull/work-stealing: rarest-first
  job table, min-loaded sender selection, and straggler mitigation by
  stealing pending jobs from slow senders, re-scheduled on every ack
  (node.go:629-1073).
- **Mode 3** ``FlowRetransmitLeaderNode`` — max-flow optimal: a global plan
  of partial-layer byte-range jobs with per-job rate budgets, computed by
  the time-parameterized max-flow solver (node.go:1076-1288).

Deviations from the reference, on purpose:
- ``start_distribution``/``ready`` queues are buffered, so a leader never
  deadlocks when the driver isn't listening yet (reference quirk: unbuffered
  send inside the announce handler, node.go:322).
- Startup/ready fire exactly once, guarded by a flag (the reference's mode-2
  per-node completion map can re-fire, node.go:753-759).
"""

from __future__ import annotations

import dataclasses
import hmac
import itertools
import os
import queue
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..core.types import (
    Assignment,
    LayerID,
    LayerIDs,
    LayerLocation,
    LayerMeta,
    LayersSrc,
    LayerSrc,
    NodeID,
    Status,
    codec_accepts,
    codec_capability,
    delivered,
    delta_base_digest,
    parse_shard_spec,
    layer_ids_to_json,
    satisfies,
    shard_covers,
    shard_range,
)
from ..sched.flow import (
    FlowJob,
    FlowJobsMap,
    pick_salvage_source,
    pod_shard_demands,
    rate_for,
    solve_joint,
)
from ..sched.jobs import Job, JobManager
from ..sched.native import make_flow_graph
from ..transport.messages import (
    AckMsg,
    AnnounceMsg,
    BootHintMsg,
    BootReadyMsg,
    DevicePlanMsg,
    DrainMsg,
    FlowRetransmitMsg,
    GenerateReqMsg,
    GenerateRespMsg,
    GroupPlanMsg,
    GroupStatusMsg,
    HeartbeatMsg,
    JoinMsg,
    JobRevokeMsg,
    JobStatusMsg,
    JobSubmitMsg,
    LayerDigestsMsg,
    LayerMsg,
    LayerNackMsg,
    LeaderLeaseMsg,
    MetricsReportMsg,
    PlanResendReqMsg,
    PolicyCtlMsg,
    RetransmitMsg,
    RolloutCtlMsg,
    ServeMsg,
    SourceDeadMsg,
    StartupMsg,
    SwapCommitMsg,
    TimeSyncMsg,
)
from ..utils import integrity, intervals, telemetry, trace
from ..utils.logging import log
from .checkpoint import map_through_gaps
from .failover import (
    ControlReplicator,
    _nested_layer_map_to_json,
    _partial_to_json,
)
from .failure import FailureDetector
from .membership import MembershipTable
from . import membership as mship
from .node import MessageLoop, Node
from .policy import PolicyEngine
from .rollout import RolloutDriver
from .store import ContentIndex
from .send import (
    NackRetransmitter,
    RevokeRegistry,
    contribute_device_plan,
    fetch_from_client,
    handle_flow_retransmit,
    release_upload_cache,
    reopen_upload_cache,
    send_layer,
)


def _range_key(spec: str, codec: str) -> str:
    """The range-digest cache's spec key: the shard spec, qualified by
    the wire codec when the range hashes the ENCODED blob
    (docs/codec.md; shard x codec pod pairs).  One definition shared
    by the stamping writer and the ack-time reader."""
    return spec + (f"+{codec}" if codec else "")


def assignment_satisfied(a: Assignment, s: Status) -> bool:
    """Every assigned layer is held in RAM/HBM by its node
    (node.go:435-446) — at a shard that COVERS the assigned one for
    sharded targets (docs/sharding.md; a shard-holder never satisfies a
    full-layer demand)."""
    for node_id, layers in a.items():
        held = s.get(node_id, {})
        for layer_id, want in layers.items():
            if not satisfies(held.get(layer_id), want):
                return False
    return True


class LeaderNode:
    """Mode 0: naive leader broadcast."""

    # Scheduler mode number, for the replicated snapshot (a promoted
    # standby must construct the SAME scheduler class at takeover).
    MODE = 0

    def __init__(
        self,
        node: Node,
        layers: LayersSrc,
        assignment: Assignment,
        start_loop: bool = True,
        expected_nodes: Optional[Set[NodeID]] = None,
        failure_timeout: float = 0.0,
        fabric=None,
        placement=None,
        standbys: Optional[List[NodeID]] = None,
        lease_interval: float = 0.0,
        epoch: int = -1,
        loop=None,
        lock=None,
        codecs=None,
    ):
        """``expected_nodes``: when given, distribution also waits for these
        nodes to announce — not just the assignment keys.  The reference
        starts once all *assignees* have announced (node.go:313-319), which
        races pure seeders' announcements and silently schedules around
        them (its benchmark config has 7 seeders and 1 assignee).

        ``failure_timeout``: seconds of silence after which an announced
        node is declared crashed and ``crash()`` re-plans around it; 0
        disables detection (the reference has none — crash() is its TODO,
        node.go:218-220).

        ``fabric`` + ``placement``: a ``parallel.fabric.FabricPlane`` and a
        ``parallel.mesh.fabric_placement`` covering every node.  When both
        are set, scheduled transfers whose participants all have fabric
        stages are dispatched as ``DevicePlanMsg`` control commands and the
        layer bytes move as device traffic (ICI) instead of TCP streams —
        the north-star data plane (SURVEY §5.8).  Transfers the fabric
        can't carry (client-held sources, unstaged nodes) fall back to the
        host path per transfer.

        Control-plane HA (docs/failover.md): ``standbys`` is the ordered
        succession list — when non-empty, every control-state mutation
        replicates to them as ``ControlDeltaMsg``s; ``lease_interval``
        > 0 beacons ``LeaderLeaseMsg`` liveness; ``epoch`` is this
        leader's fencing epoch (stamped onto every control message it
        emits; -1 = HA off, wire format unchanged).  ``loop``: share an
        already-running MessageLoop instead of owning one — the
        promoted-standby path, where the worker's loop keeps its
        data-plane handlers and the leader fills the control-plane
        gaps."""
        self.node = node
        self.layers = layers
        self.assignment = assignment
        # Multi-job service plane (docs/service.md): the constructor's
        # assignment is the BASE single-run goal; admitted jobs merge
        # into ``self.assignment`` (the effective cluster goal every
        # existing path reads) and are tracked per job.  Until a job is
        # admitted the two are the SAME object — zero behavior change
        # for single-run deployments.
        self._base_assignment = assignment
        self.jobs = JobManager()
        # Live-swap driver state (docs/swap.md): version -> record
        # {"version", "job_id", "swap_base", "dests", "state"
        # (rolling|committed|aborted), "confirmed"} — replicated to
        # standbys (delta kind "swap" + snapshot section) so a promoted
        # leader resumes a half-finished rollout.
        self._swaps: Dict[str, dict] = {}
        self._swaps_by_job: Dict[str, str] = {}
        # Rollout pipeline (docs/rollout.md): wave versions whose swap
        # commit is HELD for the pipeline (version -> rollout id) — the
        # driver writes the marker before submitting each wave job —
        # and the pipeline state machine itself.
        self._swap_holds: Dict[str, str] = {}
        self.rollouts = RolloutDriver(self)
        # Joiner seats whose NIC rate was PINNED to the most
        # conservative configured value at admission: their first
        # announce carrying a real rate supersedes it
        # (docs/membership.md).
        self._joiner_bw_pinned: Set[NodeID] = set()
        # Admission control (docs/service.md): the shared-secret job
        # token.  Read at construction like the other env knobs; empty
        # = open admission (the legacy behavior).
        self._job_token = os.environ.get("DLD_JOB_TOKEN", "")
        # Per-submitter quotas/rate limits (docs/service.md), keyed by
        # the DLD_JOB_TOKEN identity: DLD_JOB_QUOTA caps a submitter's
        # concurrently ACTIVE jobs, DLD_JOB_RATE ("N/SECONDS") caps its
        # submit attempts per window.  0/empty = unlimited (legacy).
        # Refusals are counted (jobs.quota_refused) and always ANSWER.
        try:
            self._job_quota = int(os.environ.get("DLD_JOB_QUOTA", "0"))
        except ValueError:
            self._job_quota = 0
        self._job_rate_n, self._job_rate_s = 0, 0.0
        rate_spec = os.environ.get("DLD_JOB_RATE", "")
        if rate_spec:
            try:
                n, s = rate_spec.split("/", 1)
                self._job_rate_n, self._job_rate_s = int(n), float(s)
            except ValueError:
                log.error("malformed DLD_JOB_RATE (want 'N/SECONDS'); "
                          "rate limiting disabled", value=rate_spec)
        self._submit_times: Dict[str, List[float]] = {}
        # Wire-codec plane (docs/codec.md): this leader's own
        # encode/decode capability + each announced node's (the
        # negotiation's capability table), the memoized per-(dest,
        # layer) codec CHOICE (stable across re-merges and re-plans),
        # and the codec-qualified digest cache the stamp reads.
        self.codecs = codecs
        self.node_codecs: Dict[NodeID, frozenset] = {}
        if codecs is not None:
            self.node_codecs[node.my_id] = frozenset(
                codecs.decode_codecs())
        self._codec_choice: Dict[Tuple[NodeID, LayerID], str] = {}
        self._codec_digest_cache: Dict[Tuple[LayerID, str], str] = {}
        # Content-delta plane (docs/codec.md): the per-layer pinned base
        # digest ("" = delta provably not worth it for this layer) and
        # digest → leader-local verified canonical bytes — what the
        # plane's delta encoder reads the base from.  The resolver is a
        # PLAIN dict .get: it runs inside the plane's encode path while
        # self._lock may be held, so it must never take the leader lock.
        self._delta_base: Dict[LayerID, str] = {}
        self._delta_base_src: Dict[str, LayerSrc] = {}
        if codecs is not None:
            codecs.base_resolver = self._delta_base_src.get
        # Sticky once ANY pair was ever chosen quantized: digests-off
        # stamps then carry explicit ""-codec entries so a REVERTED
        # pair still reconciles at the dest (mirrors _sharding_seen).
        self._codec_seen = False
        # (layer, dest) pairs already reported as content-skipped (the
        # counter/log fire once per pair, not once per replan).
        self._content_skip_seen: Set[Tuple[LayerID, NodeID]] = set()
        # Content-addressed holdings (runtime/store.py): digest →
        # (node, layer) holders, fed by announces and acks — lets a
        # delta-rollout job skip shipping layers whose bytes a dest
        # already holds under another id.
        self.content = ContentIndex()
        self.fabric = fabric
        self.placement = placement
        self._plan_seq = itertools.count()
        # Batch-hint ids must be unique across DISPATCH ROUNDS (a
        # re-plan within the dest's batch wait would otherwise collide
        # with a still-open group and fire a mixed batch); separate from
        # _plan_seq on purpose — consuming plan seqs for ids would punch
        # holes in the SPMD lockstep ordering.
        self._batch_seq = itertools.count()
        # seq -> the operative DevicePlanMsg broadcast for it (plan, or
        # the cancel that superseded it): the re-send store for SPMD
        # gap recovery (handle_plan_resend).  Insertion-ordered, bounded.
        self._sent_plans: Dict[int, DevicePlanMsg] = {}
        # seq -> {"t": dispatch time, "retries": n} for SPMD plans whose
        # (layer, dest) ack hasn't arrived: the WATCHDOG side of the gap
        # recovery.  The receiver-side gap report only fires when LATER
        # seqs queue behind a hole — a dropped TAIL plan stalls silently
        # (nothing queues, the dest never learned of it), so the leader
        # also re-broadcasts unacked plans on a timer and cancels after
        # PLAN_REBROADCASTS tries (the dest's collect-timeout
        # re-announce then re-plans the bytes, host path).
        self._plan_watch: Dict[int, dict] = {}
        self._watch_stop = threading.Event()
        self.expected_nodes = set(expected_nodes or ())
        self.status: Status = {}
        # A promoted standby's leader shares the hosting WORKER's lock
        # (``lock=``): both mutate the same ``layers`` store, and two
        # locks over one dict would race.
        self._lock = lock if lock is not None else threading.Lock()
        # Multi-controller lockstep fabric?  Fixed at construction.
        self._spmd = getattr(fabric, "kind", "") == "spmd"
        # SPMD fabric: declared crashes break pod-wide lockstep, so later
        # transfers fall back to the host path (_fabric_ok).
        self._fabric_disabled = False
        if fabric is not None and hasattr(fabric, "bind_store"):
            fabric.bind_store(layers, self._lock)
        self._start_q: "queue.Queue[Assignment]" = queue.Queue()
        self._ready_q: "queue.Queue[Assignment]" = queue.Queue()
        self._started = False
        self._starting = False
        self._startup_sent = False
        # The leader's boot decision rides StartupMsg so one flag governs
        # the whole run (see send_startup); the CLI sets this False for
        # dissemination-only runs of boot-capable topologies (-boot none).
        self.boot_enabled = True
        self._serve_promised = False  # StartupMsg said a ServeMsg follows
        self.serve_generate = 0  # >0: pod serving decodes N tokens (-gen)
        # Model-boot completion tracking (BootReadyMsg is an extension:
        # the reference's startup hook has no completion signal).
        self._boot_q: "queue.Queue[Dict[NodeID, float]]" = queue.Queue()
        self._booted: Dict[NodeID, float] = {}
        self._boot_kinds: Dict[NodeID, str] = {}  # serve needs "stage"
        self._boot_reported = False
        self._t_start: Optional[float] = None
        # node -> {layer: {"Total": n, "Covered": [[s, e], ...]}} from
        # announces of checkpoint-resuming receivers.
        self.partial_status: Dict[NodeID, dict] = {}
        # Assignments dropped by crash(), kept so a declared-dead node that
        # restarts and re-announces gets its layers back (resume).
        self._dropped_assignment: Dict[NodeID, LayerIDs] = {}
        # Elastic membership (docs/membership.md): the replicated
        # roster.  Configured seats (assignees, expected seeders,
        # standbys, the leader itself) are ACTIVE + source-verified from
        # the start — the config is the operator's trust statement;
        # joiners enter JOINING (dest immediately, source once their
        # holdings digest-verify) and drains run a re-home-then-prune
        # protocol that never touches the crash path.
        self.membership = MembershipTable()
        self.membership.seed(
            set(assignment) | set(expected_nodes or ())
            | set(standbys or ()) | {node.my_id}, epoch=epoch)
        # job_id -> the node a kind="drain" re-home job is draining;
        # its completion fires the atomic prune (_finalize_drain).
        self._drain_jobs: Dict[str, NodeID] = {}
        # joiner -> wanted layer ids ([] = the layer universe): admits
        # whose refill job waits for the joiner's FIRST announce — the
        # announce carries its cold-boot holdings (inventory, partials,
        # digests), so the job's remaining demand is computed against
        # them and only the complement ever ships.
        self._join_pending: Dict[NodeID, List[LayerID]] = {}
        # draining node -> requester seats awaiting the DONE notice.
        self._drain_waiters: Dict[NodeID, Set[NodeID]] = {}
        self.detector = FailureDetector(failure_timeout, self.crash)
        # Seed the liveness leases so a node that dies before ever
        # announcing is still detected (its lease simply expires).  Never
        # monitor the leader itself: it sends itself no heartbeats, and
        # "self-crashing" would drop its own layer inventory.
        for node_id in set(self.assignment) | self.expected_nodes:
            if node_id != node.my_id:
                self.detector.touch(node_id)

        # Integrity plane (docs/integrity.md): layer_id -> self-
        # describing digest of
        # the layer's true bytes — collected from holders' announces plus
        # the leader's own layers (hashed on a background thread: at
        # physical sizes the hash takes seconds, all of them PRE-timer,
        # overlapped with the announce round-trips).  Stamped per
        # assignee at distribution start (LayerDigestsMsg); the NACK
        # retransmit service serves corrupt-fragment re-requests for
        # layers the leader itself sends (all four modes).
        self.layer_digests: Dict[LayerID, str] = {}
        self._digests_ready = threading.Event()
        # (layer, shard spec) -> digest of exactly that byte range — the
        # sharded-delivery stamp cache (docs/sharding.md); replans and
        # per-dest stamps must not re-hash gigabytes.
        self._range_digest_cache: Dict[Tuple[LayerID, str], str] = {}
        # Sticky once any sharded target/holding has been seen: with
        # digests disabled, stamps then carry explicit ""-spec entries
        # so a widened target still reconciles at the dest.
        self._sharding_seen = False
        # Fabric-assisted pod delivery (docs/fabric.md): (layer, dest)
        # -> the pair's NIC shard spec.  Empty on every scheduler that
        # doesn't pod-plan (only mode 3 does, and only with a ``pods``
        # grouping configured); the goal stays OPEN until every pod
        # pair's FULL wire-form tree materialized (_pods_open_locked).
        self._pod_pairs: Dict[Tuple[LayerID, NodeID], str] = {}
        self.nacker = NackRetransmitter()
        # Preemption revoke (docs/service.md): the leader is a sender
        # too — its own queued flow sends honor revokes via this
        # registry (remote senders get JobRevokeMsg).
        self.revokes = RevokeRegistry()

        # Control-plane HA (docs/failover.md).
        self.epoch = epoch
        self.standbys: List[NodeID] = list(standbys or [])
        self.lease_interval = lease_interval
        self._lease_stop = threading.Event()
        self._lease_inflight: Set[NodeID] = set()
        self._deposed = False
        self._plan_seq_hint = 0  # last issued plan seq + 1, for snapshots
        # (layer, dest) pairs being range-salvaged after a source crash
        # (mode 3): their acks clear them; re-plans skip them.
        self._salvaging: Set[Tuple[LayerID, NodeID]] = set()
        self.replicator = (ControlReplicator(node, self.standbys)
                           if self.standbys else None)

        # Telemetry plane (docs/observability.md): the latest cumulative
        # MetricsReportMsg snapshot per node.  Replace-per-node fold (the
        # snapshots are run-scoped cumulative), replicated to standbys so
        # a takeover keeps the cluster picture; the leader's own process
        # metrics are read live from the registry at fold time.
        self.cluster_metrics: Dict[NodeID, dict] = {}
        # Live fleet health timeline (docs/observability.md): per-
        # interval deltas of those cumulative snapshots — per-link
        # throughput/stall/NACK series + first-class straggler events,
        # derived at every report fold, replicated so a promoted
        # standby keeps the event history.
        self.health = telemetry.HealthTimeline()
        # Closed-loop autonomy (docs/autonomy.md): the policy engine —
        # declarative rules over the folded signals, acting through the
        # SAME chokepoints the CLI verbs use.  Unarmed (no rules) it is
        # inert; the CLI arms it from the config's Policies block.  Its
        # state REPLACE-replicates (kind "policy" + snapshot section)
        # so a promoted standby inherits armed rules, cooldowns, and
        # in-flight actions.
        self.policy = PolicyEngine(self)

        if integrity.digests_enabled():
            threading.Thread(target=self._compute_own_digests,
                             name="layer-digests", daemon=True).start()
        else:
            self._digests_ready.set()

        # The leader's own layers seed its status row (node.go:251-257);
        # carry sizes so the flow solver can size any layer from status.
        self.status[node.my_id] = {
            lid: LayerMeta(
                location=src.meta.location,
                limit_rate=src.meta.limit_rate,
                source_type=src.meta.source_type,
                data_size=src.data_size,
                version=src.meta.version,
                codec=src.meta.codec,
            )
            for lid, src in self.layers.items()
        }

        self._shared_loop = loop is not None
        self.loop = loop if loop is not None else MessageLoop(node.transport)
        self._register_handlers()
        if start_loop:
            self.loop.start()
            self.detector.start()
            self.start_ha()
            if self._spmd:
                threading.Thread(target=self._plan_watchdog,
                                 name="plan-watchdog", daemon=True).start()

    # How many broadcast plans the leader retains for gap re-sends; a
    # goal's plan count is bounded by its (layer, dest) pairs, so this
    # comfortably covers any in-flight window while bounding memory.
    SENT_PLAN_RETENTION = 4096
    # Watchdog knobs (class attrs: tests tune them): how long an SPMD
    # plan may sit unacked before a re-broadcast, how often to check,
    # and how many re-broadcasts before the seq is cancelled.
    PLAN_ACK_TIMEOUT = 60.0
    PLAN_WATCH_PERIOD = 5.0
    PLAN_REBROADCASTS = 3

    def _plan_watchdog(self) -> None:
        """Tail-gap liveness (the receiver-side gap report's blind
        spot): re-broadcast unacked SPMD plans on a timer.

        The give-up CANCEL is crash-gated (round-5 advice residual): a
        cancel advances processes that never entered the seq's
        collective — but peers already blocked INSIDE it (they received
        the plan; some other participant didn't, or the dest is merely
        slow) cannot be recalled, so a cancel fired while the dest is
        still alive would desynchronize the lockstep: the gap process
        skips the seq while its peers sit in the collective waiting for
        it.  So past the retry budget the watchdog only keeps
        re-broadcasting (duplicate deliveries are free — the executor
        returns the settled/pending handle for any seq it already saw)
        until the failure detector declares a participant crashed and
        ``crash()`` disables the fabric; ``crash()`` then cancels every
        still-watched seq so gap processes stop waiting on plans that
        can no longer execute.  See docs/fabric.md ("Failure domain and
        the cancel wedge")."""
        while not self._watch_stop.wait(self.PLAN_WATCH_PERIOD):
            now = time.monotonic()
            due = []
            with self._lock:
                fabric_down = self._fabric_disabled
                for seq, rec in list(self._plan_watch.items()):
                    if now - rec["t"] < self.PLAN_ACK_TIMEOUT:
                        continue
                    msg = self._sent_plans.get(seq)
                    if msg is None:
                        del self._plan_watch[seq]
                        continue
                    if rec["retries"] >= self.PLAN_REBROADCASTS:
                        if fabric_down:
                            # Crash declared: safe (and necessary) to
                            # advance the gap processes past the seq.
                            del self._plan_watch[seq]
                            due.append((seq, msg, True))
                        else:
                            # Dest alive (no crash declared): a cancel
                            # here could strand peers inside the
                            # collective.  Keep re-broadcasting at the
                            # ack-timeout cadence instead.
                            rec["t"] = now
                            due.append((seq, msg, False))
                    else:
                        rec["retries"] += 1
                        rec["t"] = now
                        due.append((seq, msg, False))
                recipients = sorted(set(self.status)
                                    | {self.node.my_id})
            for seq, msg, give_up in due:
                if give_up:
                    log.error("spmd plan unacked and fabric disabled; "
                              "cancelling seq (dest re-announce will "
                              "re-plan the bytes)", seq=seq,
                              plan=msg.plan_id)
                    out = self._make_plan_cancel(seq, msg)
                else:
                    log.warn("re-broadcasting unacked spmd plan",
                             seq=seq, plan=msg.plan_id)
                    out = msg
                self._send_plan_to(out, set(recipients) | {msg.dest_id},
                                   seq)

    def _make_plan_cancel(self, seq: int, msg: DevicePlanMsg) -> DevicePlanMsg:
        """Build (and retain for gap re-sends) the cancellation that
        supersedes ``seq``."""
        cancel = DevicePlanMsg(self.node.my_id, msg.plan_id,
                               msg.layer_id, msg.dest_id, 0, [], seq=seq,
                               epoch=self.epoch)
        with self._lock:
            self._sent_plans[seq] = cancel
        return cancel

    def _send_plan_to(self, out: DevicePlanMsg, recipients, seq: int) -> None:
        for r in sorted(recipients):
            try:
                self.node.transport.send(r, out)
            except (OSError, KeyError) as e:
                log.error("plan watchdog send failed", seq=seq,
                          dest=r, err=repr(e))

    def _register_handlers(self) -> None:
        # A PROMOTED leader shares the worker's already-running loop:
        # register-keep fills the control-plane gaps (announce / ack /
        # heartbeat / ...) without clobbering the worker's data-plane
        # handlers (layer reassembly, flow jobs, NACK service).
        reg = (self.loop.register_keep if self._shared_loop
               else self.loop.register)
        reg(AnnounceMsg, self.handle_announce)
        reg(AckMsg, self.handle_ack)
        reg(LayerMsg, self.handle_layer)
        reg(HeartbeatMsg, lambda msg: self.detector.touch(msg.src_id))
        reg(BootReadyMsg, self.handle_boot_ready)
        reg(DevicePlanMsg, self.handle_device_plan)
        reg(GenerateReqMsg, self.handle_generate_req)
        reg(PlanResendReqMsg, self.handle_plan_resend)
        reg(LayerNackMsg, self.handle_layer_nack)
        reg(LeaderLeaseMsg, self.handle_leader_lease)
        reg(MetricsReportMsg, self.handle_metrics_report)
        reg(TimeSyncMsg, self.handle_time_sync)
        reg(JobSubmitMsg, self.handle_job_submit)
        reg(JobStatusMsg, self.handle_job_status)
        reg(SwapCommitMsg, self.handle_swap_commit)
        reg(JoinMsg, self.handle_join)
        reg(DrainMsg, self.handle_drain)
        reg(RolloutCtlMsg, self.handle_rollout_ctl)
        reg(PolicyCtlMsg, self.handle_policy_ctl)

    # --------------------------------------------------- control-plane HA

    def start_ha(self) -> None:
        """Begin beaconing the leadership lease (no-op when HA is off).
        The first beat goes out immediately — for a promoted standby it
        IS the takeover announcement."""
        if self.lease_interval > 0 and self.epoch >= 0:
            threading.Thread(target=self._lease_loop, name="leader-lease",
                             daemon=True).start()

    def _lease_loop(self) -> None:
        while True:
            self._broadcast_lease()
            if self._lease_stop.wait(self.lease_interval):
                return

    def _lease_recipients_locked(self) -> Set[NodeID]:
        """Who hears the leadership beacon.  Lock held.  The
        hierarchical leader narrows this to sub-leaders + ungrouped
        seats: a grouped member's control parent is its SUB-LEADER, and
        a root lease reaching it would re-point it flat
        (docs/hierarchy.md)."""
        return (set(self.status) | set(self.standbys)
                | self.expected_nodes | set(self.assignment))

    def _broadcast_lease(self) -> None:
        with self._lock:
            if self._deposed:
                return
            recipients = self._lease_recipients_locked()
            recipients.discard(self.node.my_id)
        msg = LeaderLeaseMsg(self.node.my_id, self.epoch,
                             list(self.standbys), self.lease_interval)
        for r in sorted(recipients):
            self._lease_send_async(r, msg)

    def _lease_send_async(self, dest: NodeID, msg: LeaderLeaseMsg) -> None:
        """One NON-BLOCKING lease send per recipient, at most one in
        flight each: a dead peer's TCP dial-retry window (seconds) must
        not stall the single beacon thread past the standbys' expiry —
        that would fake the leader's own death to every LIVE observer
        and trigger a spurious (if benign) takeover."""
        with self._lock:
            if dest in self._lease_inflight:
                return  # previous beat to this peer still dialing
            self._lease_inflight.add(dest)

        def run():
            try:
                self.node.add_node(dest)
                self.node.transport.send(dest, msg)
            except (OSError, KeyError) as e:
                log.debug("lease send failed", dest=dest, err=repr(e))
            finally:
                with self._lock:
                    self._lease_inflight.discard(dest)

        threading.Thread(target=run, daemon=True,
                         name=f"lease-{dest}").start()

    def handle_leader_lease(self, msg: LeaderLeaseMsg) -> None:
        """A lease from ANOTHER leader at a higher epoch means a standby
        took over while this process was presumed dead: step down.  Our
        stale-epoch control traffic is already fenced cluster-wide; the
        step-down just stops this zombie from burning the wire and from
        declaring live nodes crashed.  EQUAL epochs (two standbys fired
        concurrently — pathological, but rank staggering is not
        consensus) break deterministically: the LOWER node id keeps the
        seat, the other deposes."""
        if msg.src_id == self.node.my_id:
            return
        if msg.epoch < self.epoch or (msg.epoch == self.epoch
                                      and msg.src_id > self.node.my_id):
            return
        with self._lock:
            if self._deposed:
                return
            self._deposed = True
        trace.count("failover.deposed")
        log.error("a higher-epoch leader exists; stepping down",
                  new_leader=msg.src_id, new_epoch=msg.epoch,
                  my_epoch=self.epoch)
        self._lease_stop.set()
        self.detector.stop()

    def _replicate(self, kind: str, **data) -> None:
        """Stream one control-state mutation to the standbys (no-op when
        HA is off).  Best-effort: takeover reconciliation repairs any
        divergence — replication buys recovery SPEED, not correctness."""
        rep = self.replicator
        if rep is not None and self.epoch >= 0:
            rep.publish(self.epoch, kind, data)

    def _snapshot_payload(self) -> dict:
        # Health snapshot BEFORE the leader lock: HealthTimeline.observe
        # calls back into _modeled_link_rate (health lock → leader
        # lock), so taking the health lock while holding the leader's
        # would be a lock-order inversion.
        health = self.health.snapshot()
        # Same discipline for the autonomy engine (docs/autonomy.md):
        # PolicyEngine._lock is leaf-most, so its state is folded
        # before the leader lock is taken.
        policy = self.policy.to_json()
        with self._lock:
            return {
                # Fleet health timeline (docs/observability.md): the
                # event ring + series tail — a promoted standby keeps
                # the straggler history with onset timestamps.
                "Health": health,
                # Autonomy engine (docs/autonomy.md): armed rules,
                # cooldowns (as remaining seconds), quarantine mask and
                # in-flight actions — a promoted standby inherits the
                # closed loop mid-action instead of re-deciding cold.
                "Policy": policy,
                "PlanGen": int(getattr(self, "_plan_gen", 0)),
                "Mode": self.MODE,
                "Assignment": _nested_layer_map_to_json(self.assignment),
                "BaseAssignment": _nested_layer_map_to_json(
                    self._base_assignment),
                # The admitted-job table (docs/service.md): a promoted
                # standby resumes EVERY job, not just one run.
                "Jobs": self.jobs.to_json(),
                # Live-swap driver records (docs/swap.md): a promoted
                # standby resumes a half-finished rollout's fence.
                "Swaps": {v: self._swap_record_locked(v)
                          for v in sorted(self._swaps)},
                # Rollout pipeline records (docs/rollout.md): a
                # promoted standby resumes the pipeline MID-WAVE with
                # the SLO guard still armed.
                "Rollouts": self.rollouts.to_json(),
                "Status": _nested_layer_map_to_json(self.status),
                "Partial": _partial_to_json(self.partial_status),
                "Dropped": _nested_layer_map_to_json(
                    self._dropped_assignment),
                "Digests": {str(l): d
                            for l, d in self.layer_digests.items()},
                # Wire-codec plane (docs/codec.md): the per-pair codec
                # choices and node capabilities — a promoted leader
                # must keep planning the SAME byte spaces mid-transfer
                # partials live in.
                "WireCodecs": {f"{d}:{l}": c for (d, l), c
                               in self._codec_choice.items() if c},
                "NodeCodecs": {str(n): sorted(s)
                               for n, s in self.node_codecs.items()},
                # Elastic membership (docs/membership.md): the roster +
                # in-flight drain re-home jobs — a promoted standby
                # resumes admission and drains, and keeps departed
                # members fenced.
                "Membership": self.membership.to_json(),
                "DrainJobs": {jid: int(n) for jid, n in
                              sorted(self._drain_jobs.items())},
                "PlanSeq": self._plan_seq_hint,
                "StartupSent": self._startup_sent,
                "NetworkBw": {str(n): b for n, b in getattr(
                    self, "node_network_bw", {}).items()},
                "FailureTimeout": self.detector._timeout,
                "BootEnabled": self.boot_enabled,
                # Private bookkeeping ("_recv_mono": THIS process's
                # monotonic clock) must not cross the wire — a standby
                # restoring it would compare a foreign clock against
                # its own in await_metrics.
                "Metrics": {str(n): {k: v for k, v in s.items()
                                     if not k.startswith("_")}
                            for n, s in self.cluster_metrics.items()},
                # Subclass sections (e.g. the hierarchical leader's
                # group table, docs/hierarchy.md).
                **self._snapshot_extra_locked(),
            }

    def _snapshot_extra_locked(self) -> dict:
        """Extra snapshot sections from subclasses.  Lock held."""
        return {}

    def _send_snapshot_to(self, standby: NodeID) -> None:
        if self.replicator is None or self.epoch < 0:
            return
        self.replicator.publish_to(standby, self.epoch, "snapshot",
                                   self._snapshot_payload())
        log.info("control snapshot sent to standby", standby=standby)

    def adopt_shadow(self, shadow: dict, dead_leader=None) -> None:
        """Assume a dead leader's replicated control state (the takeover
        half of ``runtime/failover.StandbyController``).  The dead
        leader's own status row is dropped — its in-RAM layers died with
        it; anything ONLY it held will surface as a loud "no owner"
        during re-planning, exactly like any other crashed holder."""
        with self._lock:
            own_row = {
                lid: LayerMeta(
                    location=src.meta.location,
                    limit_rate=src.meta.limit_rate,
                    source_type=src.meta.source_type,
                    data_size=src.data_size,
                    version=src.meta.version,
                    codec=src.meta.codec,
                )
                for lid, src in self.layers.items()
            }
            self.status = {n: dict(row)
                           for n, row in shadow["status"].items()
                           if n != dead_leader}
            self.status[self.node.my_id] = own_row
            self.assignment = {n: dict(r) for n, r in
                               shadow["assignment"].items()
                               if n != dead_leader}
            # Job plane (docs/service.md): restore the admitted-job
            # table and the base goal so EVERY job resumes, not just
            # the run.  The replicated merged assignment above already
            # carries the jobs' pairs; keeping it (rather than
            # re-merging) also survives lost best-effort job deltas.
            base = shadow.get("base_assignment")
            self._base_assignment = (
                {n: dict(r) for n, r in base.items() if n != dead_leader}
                if base is not None else self.assignment)
            self.jobs.load(shadow.get("jobs") or {})
            # Live-swap records (docs/swap.md): the promoted leader owns
            # every half-finished rollout's fence now.
            self._swaps = {}
            self._swaps_by_job = {}
            for v, rec in (shadow.get("swaps") or {}).items():
                r = {"version": str(rec.get("Version", v)),
                     "job_id": str(rec.get("JobID", "")),
                     "swap_base": int(rec.get("SwapBase", -1)),
                     "dests": [int(d) for d in rec.get("Dests") or []
                               if int(d) != dead_leader],
                     "state": str(rec.get("State", "rolling")),
                     "confirmed": {int(d) for d in
                                   rec.get("Confirmed") or []},
                     # Rollout-pipeline wave bookkeeping
                     # (docs/rollout.md), absent on plain swaps.
                     "hold": bool(rec.get("Hold", False)),
                     "staged": bool(rec.get("Staged", False)),
                     "rollout": str(rec.get("Rollout", "")),
                     "revert": bool(rec.get("Revert", False))}
                self._swaps[r["version"]] = r
                if r["job_id"]:
                    self._swaps_by_job[r["job_id"]] = r["version"]
            if dead_leader is not None:
                self.jobs.drop_dest(dead_leader)
            # Dests the DEAD leader declared crashed pre-takeover: the
            # "crash" delta recorded them in dropped, but the per-job
            # record re-replication may have been lost (best-effort) —
            # re-apply the drops so no adopted job waits on a dest the
            # cluster already wrote off.
            for node_id in list(shadow["dropped"]):
                self.jobs.drop_dest(node_id)
            self.partial_status = {n: dict(p) for n, p in
                                   shadow["partial"].items()}
            self._dropped_assignment = {n: dict(r) for n, r in
                                        shadow["dropped"].items()}
            for lid, dg in shadow["digests"].items():
                self.layer_digests.setdefault(lid, dg)
            # Wire-codec plane (docs/codec.md): adopt the dead leader's
            # codec choices and the cluster's capability table, so the
            # resumed plans keep the byte spaces in-flight partials are
            # accounted in (and the re-sent stamps carry the same
            # codec-qualified expectations).
            for key, c in (shadow.get("wire_codecs") or {}).items():
                self._codec_choice[key] = c
                if c:
                    self._codec_seen = True
                base = delta_base_digest(c)
                if base:
                    # Adopted delta pairs: re-pin the base per layer and
                    # re-point the plane's resolver at THIS seat's copy
                    # of the base bytes (reverse scan of its own digest
                    # table).  A seat that holds no such copy keeps the
                    # choice but can't encode — the digest-stamp path
                    # reverts those pairs to raw, loudly.
                    self._delta_base[key[1]] = base
                    if base not in self._delta_base_src:
                        for lid2, dg2 in self.layer_digests.items():
                            lay2 = self.layers.get(lid2)
                            if (dg2 == base and lay2 is not None
                                    and lay2.meta.location
                                    != LayerLocation.CLIENT):
                                self._delta_base_src[base] = lay2
                                break
            for n, caps in (shadow.get("node_codecs") or {}).items():
                if n != dead_leader:
                    self.node_codecs.setdefault(n, frozenset(caps))
            if self.codecs is None and self._codec_choice:
                # No codec plane here (a promoted seat without the
                # model): chosen pairs can't be sized or digest-stamped
                # in encoded space — revert them to raw, loudly.  The
                # re-sent stamp clears each dest's codec expectation
                # and its stale encoded partials demote for a clean raw
                # redelivery (docs/codec.md, honest limits).
                log.warn("adopted wire-codec choices without a codec "
                         "plane; reverting pairs to raw",
                         pairs=len(self._codec_choice))
                self._codec_choice = {
                    key: "" for key in self._codec_choice}
                for dest, lids in self.assignment.items():
                    for lid, meta in list(lids.items()):
                        if meta.codec:
                            lids[lid] = dataclasses.replace(meta, codec="")
            # The replicated telemetry picture survives the takeover:
            # the dead leader's fold is the starting table, and every
            # live node's next cumulative report simply replaces its row.
            self.cluster_metrics = {n: dict(s) for n, s in
                                    shadow.get("metrics", {}).items()}
            self._plan_seq = itertools.count(shadow["plan_seq"])
            self._plan_seq_hint = shadow["plan_seq"]
            self._started = True
            self._startup_sent = shadow["startup_sent"]
            self._t_start = time.monotonic()
            if self._spmd:
                # An epoch change breaks any presumed pod lockstep; the
                # rest of the run rides the host path.
                self._fabric_disabled = True
            peers = [n for n in self.status if n != self.node.my_id]
        # Rollout pipeline (docs/rollout.md): adopt the wave records so
        # the promoted leader resumes the pipeline mid-wave (the SLO
        # guard re-arms in resume_from_takeover).
        self.rollouts.load(shadow.get("rollouts") or {})
        # Fleet health timeline (docs/observability.md): adopt the dead
        # leader's event ring verbatim — straggler onset timestamps
        # survive the takeover; fresh interval deltas re-baseline from
        # the first post-takeover report round.
        self.health.ingest((shadow.get("health") or {}).get("events"))
        # Autonomy engine (docs/autonomy.md): inherit the armed rules,
        # cooldowns, quarantine mask and in-flight actions so the
        # promoted leader completes what the dead one started (policy
        # lock only — leaf-most, taken outside the leader lock).
        self.policy.load(shadow.get("policy") or {})
        if hasattr(self, "_plan_gen"):
            self._plan_gen = int(shadow.get("plan_gen") or 0)
        # Elastic membership (docs/membership.md): adopt the roster so
        # the promoted leader keeps departed members fenced, resumes
        # in-flight drains, and can dial adopted joiners (their
        # addresses rode the membership replication — they are in
        # nobody's config).
        self.membership.load(shadow.get("membership") or {})
        self.membership.seed(set(self.status) | set(self.assignment)
                             | {self.node.my_id}, epoch=self.epoch)
        if dead_leader is not None:
            self.membership.forget(dead_leader)
        with self._lock:
            self._drain_jobs = {str(j): int(n) for j, n in
                                (shadow.get("drain_jobs") or {}).items()}
        for n, addr in sorted(self.membership.addrs().items()):
            if n != self.node.my_id:
                self._install_member_addr(n, addr)
        for n in peers:
            if not self.membership.is_left(n):
                self.detector.touch(n)
        if dead_leader is not None:
            self.detector.forget(dead_leader)
        # Replicated job deltas are best-effort: reconcile remaining
        # pairs against the adopted status so a lost ack delta can't
        # strand a pair the cluster already shows delivered.
        with self._lock:
            status_view = {n: dict(r) for n, r in self.status.items()}
        for jid in self.jobs.credit_status(status_view):
            log.info("adopted job already complete per replicated status",
                     job=jid)

    def resume_from_takeover(self) -> None:
        """Re-drive delivery from the adopted shadow: finish immediately
        when the goal is already met, else run the mode's re-planner.
        Worker re-announces (triggered by the takeover lease) refine the
        picture as they arrive — each one re-plans incrementally via the
        existing re-announce machinery."""
        log.info("resuming distribution from replicated shadow state",
                 epoch=self.epoch,
                 dests=sorted(self.assignment),
                 partials=sorted(self.partial_status))
        self._resume_swaps()
        self.rollouts.resume_all()
        self._resume_drains()
        self._resume_joins()
        # Autonomy (docs/autonomy.md): re-apply inherited link
        # demotions and re-submit inherited in-flight actions whose
        # jobs did not survive the takeover — at the bumped epoch,
        # under the SAME action ids (no double-fire, no drop).
        self.policy.resume_from_takeover()
        with self._lock:
            already_done = self._startup_sent
        if already_done:
            # The dead leader finished delivery before dying (the
            # replicated ``startup`` delta says so): _maybe_finish will
            # never re-fire, so release this side's ready() waiters
            # directly — a completed goal must not read as a hang.
            log.info("takeover adopted a FINISHED distribution; "
                     "nothing to re-drive")
            self._ready_q.put(self.assignment)
            return
        self._drive(self._recover)

    def _resume_swaps(self) -> None:
        """Re-drive every adopted swap at the bumped epoch: committed
        fences re-send to unconfirmed nodes, aborted ones re-announce
        the release, and a rolling swap whose job the adopted status
        already shows complete fires its fence — otherwise the resumed
        job plane carries the rollout and the usual completion path
        commits (docs/swap.md)."""
        with self._lock:
            states = {v: rec["state"] for v, rec in self._swaps.items()}
        for version, state in sorted(states.items()):
            if state == "committed":
                log.warn("adopted a committed swap; re-driving its "
                         "fence at the new epoch", version=version)
                self._swap_send_round(version)
                threading.Thread(target=self._swap_watchdog,
                                 args=(version,), daemon=True,
                                 name=f"swap-fence-{version}").start()
            elif state == "aborted":
                self._swap_send_round(version)
            else:  # rolling
                with self._lock:
                    jid = self._swaps[version]["job_id"]
                job = self.jobs.get(jid)
                if job is not None and job.state == "done":
                    self._on_swap_job_done(jid)
                else:
                    log.info("adopted swap still rolling; the resumed "
                             "job plane carries it", version=version)

    # --------------------------------------------------------- integrity

    def handle_layer_nack(self, msg: LayerNackMsg) -> None:
        """A receiver's transport dropped a corrupt/abandoned fragment
        this leader sent: retransmit the byte range (bounded; in the
        transfer's own — possibly encoded — byte space)."""
        self.nacker.handle(self.node, self.layers, self._lock, msg,
                           codecs=self.codecs)

    def _compute_own_digests(self) -> None:
        """Hash the leader's own layers for the digest stamp (background
        — the announce wait overlaps it; _send_digests waits briefly)."""
        try:
            for lid, src in list(self.layers.items()):
                d = integrity.digest_layer_src(src)
                if d is None:
                    continue
                with self._lock:
                    prior = self.layer_digests.get(lid)
                    if (prior is not None and prior != d
                            and integrity.stamp_algo(prior)
                            == integrity.stamp_algo(d)):
                        # A holder's announce won the race against this
                        # background hash and disagrees: one copy is
                        # corrupt.  The leader's own digest wins — it
                        # was just computed from local bytes, and
                        # stamping the announcer's digest would let a
                        # rotted seeder's delivery VERIFY against its
                        # own rot.
                        trace.count("integrity.digest_conflict")
                        log.error("announced layer digest conflicts "
                                  "with the leader's own copy; a "
                                  "holder is corrupt (stamping the "
                                  "LEADER's digest)", layerID=lid,
                                  announced=prior, own=d)
                    self.layer_digests[lid] = d
        finally:
            self._digests_ready.set()

    def _merge_announced_digests(self, src_id, digests: dict) -> None:
        """Collect a holder's announced digests (first writer wins); a
        CONFLICT between two holders means one of them already holds
        corrupt bytes — loud, counted, and the first stamp stands."""
        if not digests:
            return
        with self._lock:
            for lid, d in digests.items():
                prior = self.layer_digests.get(lid)
                if prior is None:
                    self.layer_digests[lid] = d
                elif (prior != d and integrity.stamp_algo(prior)
                        == integrity.stamp_algo(d)):
                    trace.count("integrity.digest_conflict")
                    log.error("conflicting layer digest announced; a "
                              "holder's copy is corrupt (keeping the "
                              "first stamp)", layerID=lid, node=src_id,
                              stamped=prior, announced=d)

    def _send_digests(self) -> None:
        """Stamp each assignee with its layers' expected digests.  Waits
        (bounded, PRE-timer) for the leader's own background hash so the
        first stamp is complete; advisory — a dest without a digest for
        some layer simply skips end-to-end verification for it.

        Sharded targets ride the same stamp (docs/sharding.md) even
        with digests disabled: the shard SPEC is what tells a dest its
        interval set completes at shard coverage, so it must flow
        whenever any target is sub-layer."""
        if integrity.digests_enabled():
            self._digests_ready.wait(timeout=300.0)
        with self._lock:
            dests = self._digest_recipients_locked()
            digests = {str(l): d for l, d in self.layer_digests.items()}
        if digests:
            self._replicate("digests", Digests=digests)
        for dest in dests:
            self._send_digests_to(dest)

    def _digest_row_locked(self, dest: NodeID) -> dict:
        """The target metas the digest stamp to ``dest`` describes
        (lock held): the dest's assignment row — plus, in the
        hierarchical subclass, any synthetic group-ingress demand
        routed THROUGH the seat (docs/hierarchy.md), so a sub-leader
        ingesting a qualified (shard/codec/version) group form is
        stamped exactly like a direct dest."""
        return self.assignment.get(dest) or {}

    def _digest_recipients_locked(self) -> list:
        """Seats owed a digest stamp (lock held): every assignee — plus,
        in the hierarchical subclass, sub-leaders whose only demand is a
        synthetic group ingress (they'd otherwise verify nothing)."""
        return list(self.assignment)

    def _assigned_shards_locked(self, dest: NodeID) -> Dict[LayerID, str]:
        """Lock held.  The dest's sub-layer targets: {layer: spec}."""
        return {lid: meta.shard
                for lid, meta in self._digest_row_locked(dest).items()
                if meta.shard}

    def _range_digests_for(self, shards: Dict[LayerID, str],
                           codec_map: Optional[Dict[LayerID, str]] = None,
                           ) -> Dict[LayerID, str]:
        """Per-range digests for a dest's shard targets — the digest of
        exactly the target's byte range, so the shard verifies without
        the dest ever holding the full layer (docs/sharding.md).  Only
        computable for layers whose bytes this leader can read; absent
        entries verify by per-fragment CRC alone (honest limit).
        Cached per (layer, spec[, codec]): replans must not re-hash
        gigabytes.

        ``codec_map`` (docs/codec.md, docs/fabric.md): layers whose
        pair ships a wire codec hash the range of the ENCODED blob —
        shard x codec composes in encoded byte space, and the stamp
        must describe the bytes that actually cross the wire (this is
        what lets a quantized pod slice verify end-to-end)."""
        if not integrity.digests_enabled():
            return {}
        codec_map = codec_map or {}
        out: Dict[LayerID, str] = {}
        for lid, spec in shards.items():
            codec = codec_map.get(lid, "")
            key = (lid, _range_key(spec, codec))
            with self._lock:
                cached = self._range_digest_cache.get(key)
                layer = self.layers.get(lid)
            if cached is not None:
                out[lid] = cached
                continue
            if layer is None or layer.meta.shard:
                continue  # unreadable here (or leader holds a shard only)
            if codec:
                if self.codecs is None:
                    continue  # CRC-only verify (honest limit)
                enc = self.codecs.encoded_src(lid, layer, codec)
                if enc is None:
                    continue
                off, size = shard_range(spec, enc.data_size)
                d = integrity.digest_layer_src_range(enc, off, size)
            else:
                off, size = shard_range(spec, layer.data_size)
                d = integrity.digest_layer_src_range(layer, off, size)
            if d is None:
                continue
            with self._lock:
                self._range_digest_cache[key] = d
            out[lid] = d
        return out

    # ------------------------------------------------- wire-codec choice

    # Whether this scheduler supports negotiated wire codecs.  Mode 2's
    # pull/steal tables pick senders per-layer with no per-pair codec
    # admissibility, so it opts out (docs/codec.md, honest limits).
    WIRE_CODEC_OK = True

    def _node_bw(self, node_id: NodeID) -> int:
        """The node's modeled NIC rate for the codec-choice bottleneck
        estimate; 0 = unknown/unlimited.  Only mode 3 models NICs."""
        return 0

    def _pair_rate_locked(self, dest: NodeID, lid: LayerID,
                          want) -> int:
        """Lock held.  The (dest, layer) pair's modeled bottleneck rate
        (bytes/s): the best raw holder's source rate, capped by the
        dest's NIC.  0 = effectively unlimited (or unknown) — such a
        pair ships raw; only provably-slow links pay the encode/decode
        pass (docs/codec.md)."""
        inf = 1 << 62
        best = 0
        for node_id, row in self.status.items():
            m = row.get(lid)
            if m is None or m.location == LayerLocation.CLIENT:
                continue
            if m.shard or getattr(m, "codec", ""):
                continue  # rate-model raw full holders only
            r = m.limit_rate if m.limit_rate > 0 else (
                self._node_bw(node_id) or inf)
            best = max(best, r)
        if best == 0:
            return 0  # no raw holder visible: stay raw
        rate = min(best, self._node_bw(dest) or inf)
        return 0 if rate >= inf else rate

    def _decide_codec_locked(self, dest: NodeID, lid: LayerID,
                             meta) -> str:
        """Lock held.  The wire codec this (dest, layer) transfer
        ships under ("" = canonical): the run's configured codec, IFF
        the scheduler supports it, the dest advertised decode, the blob
        has a codec layout, the pair is unsharded/unversioned (honest
        limits: range digests hash raw ranges, and swap staging is
        untested against re-encoded forms), and the pair's modeled
        bottleneck is at or below the threshold — fast links ship raw.

        Content-delta (docs/codec.md) is tried FIRST: when the dest
        provably holds a verified base and the encoded (v2 − base) is
        small, the pair ships ``delta:<base>`` — an order-of-magnitude
        byte win whole-form quantization can't reach — and version-
        qualified rollout pairs are eligible (the wave's whole point)."""
        plane = self.codecs
        if plane is None or not self.WIRE_CODEC_OK:
            return ""
        target = self.layer_digests.get(lid)
        if (target and self.jobs.owner_of(dest, lid) is not None
                and self.content.node_has(dest, target)):
            # The dest already holds content-equal bytes and the job
            # plane's resolve path exists for this pair: the content
            # store acks it for ZERO wire bytes (_content_skip_locked,
            # which refuses codec-stamped targets) — any encoded form,
            # even a near-empty delta, would ship bytes a skip doesn't.
            return ""
        delta = self._decide_delta_locked(dest, lid, meta)
        if delta:
            return delta
        if not plane.enabled:
            return ""
        if meta.shard or meta.version:
            return ""
        c = plane.wire_codec
        if c not in self.node_codecs.get(dest, ()):
            return ""
        own = self.layers.get(lid)
        if own is not None and own.meta.location == LayerLocation.CLIENT:
            # The leader's own copy is client-held: modes 0-2 would
            # pipe-fetch RAW bytes under an encoded stamp (the client
            # stream can't encode) — keep the pair canonical.
            return ""
        if plane.nbytes(lid, c) is None:
            # Entropy forms are data-dependent: size them by actually
            # encoding the leader's own copy once (cached).  A pair
            # nobody here can size must not ship the form.
            if own is None or plane.ensure_sized(lid, own, c) is None:
                return ""
        rate = self._pair_rate_locked(dest, lid, meta)
        if rate <= 0 or rate > plane.min_rate_for(c):
            return ""
        return c

    def _decide_delta_locked(self, dest: NodeID, lid: LayerID,
                             meta) -> str:
        """Lock held.  The content-delta choice for this pair ("" = no
        delta): requires the integrity plane (reconstruction verifies
        against the stamped full-form digest — without it a stale base
        would poison the layer silently), a dest that announced the
        generic "delta" capability and PROVABLY holds the base
        (ContentIndex), a leader-readable raw canonical copy, a link
        slow enough that the encode pays, and an encoded delta that
        actually survived the worth-it gate (docs/codec.md)."""
        plane = self.codecs
        if plane is None or not plane.delta_enabled:
            return ""
        if not integrity.digests_enabled():
            return ""
        if meta.shard:
            # Honest limit: a pre-sharded target acks (and holds) its
            # range only — it can never reconstruct the full layer from
            # a slice of the delta stream.  Multi-source striping of a
            # FULL delta pair still shards fine (ranges of one blob).
            return ""
        if "delta" not in self.node_codecs.get(dest, ()):
            return ""
        if getattr(self, "_pod_of", {}).get(dest) is not None:
            # Honest limit: pod gathers assume a pod-uniform byte space;
            # per-dest bases would de-uniform the gather (docs/fabric.md).
            return ""
        own = self.layers.get(lid)
        if (own is None
                or own.meta.location == LayerLocation.CLIENT
                or own.meta.shard or getattr(own.meta, "codec", "")):
            return ""
        target = self.layer_digests.get(lid)
        if not target:
            return ""
        rate = self._pair_rate_locked(dest, lid, meta)
        if rate <= 0 or rate > plane.delta_min_rate:
            return ""
        base = self._delta_base.get(lid)
        if base is None:
            base = self._pick_delta_base_locked(lid, own)
            self._delta_base[lid] = base
        if not base or base == target:
            return ""
        if not self.content.node_has(dest, base):
            return ""
        codec = "delta:" + base
        if plane.ensure_sized(lid, own, codec) is None:
            return ""
        trace.count("codec.delta_pairs_chosen")
        return codec

    def _pick_delta_base_locked(self, lid: LayerID, own) -> str:
        """Lock held; runs once per layer (memoized by the caller, ""
        pins "no base").  Candidate bases are the leader's OWN verified
        raw full layers of the same byte length (the only bytes it can
        encode against); they're ranked by strided-sample XOR sparsity
        — cheap, no full encode per candidate — and only the winner is
        fully encoded.  A delta that fails to at least halve the raw
        bytes pins "": a rollout whose v2 actually changed everything
        must ship whole forms, not a delta dressed up as one."""
        plane = self.codecs
        try:
            raw = own.read_range()
        except (OSError, ValueError) as e:
            log.warn("delta base pick: target layer unreadable",
                     layerID=lid, err=repr(e))
            return ""
        target = self.layer_digests.get(lid, "")
        candidates: Dict[str, LayerSrc] = {}
        for other_lid, digest in self.layer_digests.items():
            if other_lid == lid or not digest or digest == target:
                continue
            if digest in candidates:
                continue
            layer = self.layers.get(other_lid)
            if (layer is None
                    or layer.meta.location == LayerLocation.CLIENT
                    or layer.meta.shard
                    or getattr(layer.meta, "codec", "")
                    or layer.data_size != len(raw)):
                continue
            candidates[digest] = layer
        if not candidates:
            return ""
        import numpy as np

        tgt = np.frombuffer(raw, dtype=np.uint8)[::257]
        scored: List[Tuple[float, str]] = []
        for digest, layer in candidates.items():
            try:
                cand = layer.read_range()
            except (OSError, ValueError):
                continue
            s = np.frombuffer(cand, dtype=np.uint8)[::257]
            frac = float(np.count_nonzero(tgt != s)) / max(1, tgt.size)
            scored.append((frac, digest))
        if not scored:
            return ""
        frac, base = min(scored)
        base_layer = candidates[base]
        # The resolver map must carry the base BEFORE the sizing encode
        # (the plane resolves it mid-encode, lock-free).
        self._delta_base_src[base] = base_layer
        sized = plane.ensure_sized(lid, own, "delta:" + base)
        if sized is None or sized * 2 >= len(raw):
            log.info("delta base rejected (encoded form not worth it)",
                     layerID=lid, base=base,
                     encoded=sized, raw_bytes=len(raw),
                     sampled_diff_frac=round(frac, 4))
            return ""
        log.info("delta base pinned for layer", layerID=lid, base=base,
                 encoded=sized, raw_bytes=len(raw),
                 sampled_diff_frac=round(frac, 4))
        return base

    def _stamp_codecs(self) -> None:
        """Choose (memoized) and stamp the wire codec onto every
        assignment target meta — called before digest stamping and
        before every plan, so re-merges (update/submit_job rebuild the
        merged goal codec-less) re-apply the stable choices.  Choices
        replicate to standbys: a promoted leader must keep planning the
        SAME byte spaces mid-transfer partials live in."""
        if self.codecs is None and not self._codec_choice:
            return
        changed = False
        with self._lock:
            for dest, lids in self.assignment.items():
                for lid, meta in lids.items():
                    key = (dest, lid)
                    choice = self._codec_choice.get(key)
                    if choice is None:
                        choice = self._decide_codec_locked(dest, lid, meta)
                        self._codec_choice[key] = choice
                        if choice:
                            changed = True
                            self._codec_seen = True
                            trace.count("codec.pairs_chosen")
                            log.info("wire codec chosen for slow pair",
                                     dest=dest, layerID=lid, codec=choice)
                    if meta.codec != choice:
                        lids[lid] = dataclasses.replace(meta, codec=choice)
            choices = dict(self._codec_choice)
        # Job targets must carry the same choices or quantized acks
        # would never credit their pairs (sched/jobs.apply_codecs).
        # Skipped while no pair was ever chosen (the common case) so
        # replan ticks don't rescan every job for nothing; individual
        # reverts apply their "" directly (_revert_codec_choice).
        if any(choices.values()):
            self.jobs.apply_codecs(
                {(d, l): c for (d, l), c in choices.items()})
        if changed:
            self._replicate_codecs()

    def _stamp_targets(self) -> None:
        """Pre-plan target stamping, in dependency order: the wire-codec
        choice first (it refuses sharded metas, and a pod slice must
        inherit the pair's codec), then the pod-delivery shard split
        (docs/fabric.md) over the codec-stamped metas."""
        self._stamp_codecs()
        self._stamp_pod_shards()

    def _stamp_pod_shards(self) -> None:
        """Hook: rewrite pod members' full targets into per-host shard
        slices (fabric-assisted pod delivery).  Only the mode-3 flow
        scheduler implements it; every other mode plans pods flat."""

    def _pods_open_locked(self) -> bool:
        """Lock held.  Whether any pod pair still owes its FULL
        materialized tree (the goal must not finish on shard coverage
        alone).  Base schedulers never pod-plan."""
        return False

    def _on_pod_ack(self, dest: NodeID, layer_id: LayerID, shard: str,
                    codec: str) -> None:
        """Hook: an ack landed for a pod-delivery pair (mode 3 drives
        the SPMD gather dispatch / completion accounting from here)."""

    def _pods_member_gone(self, node: NodeID) -> None:
        """Hook: a pod member crashed or departed — its pods' unfinished
        pairs must degrade to the host path (mode 3 only)."""

    def _replicate_codecs(self) -> None:
        with self._lock:
            choices = {f"{d}:{l}": c
                       for (d, l), c in self._codec_choice.items() if c}
            caps = {str(n): sorted(s)
                    for n, s in self.node_codecs.items()}
        self._replicate("codecs", Choices=choices, NodeCodecs=caps)

    def _codec_digest(self, lid: LayerID, codec: str) -> Optional[str]:
        """The codec-qualified digest stamped for a quantized pair —
        the hash of exactly the encoded bytes (cached; replans must not
        re-encode/re-hash).  None when this leader can't produce it."""
        key = (lid, codec)
        with self._lock:
            cached = self._codec_digest_cache.get(key)
            layer = self.layers.get(lid)
        if cached is not None:
            return cached
        if self.codecs is None or layer is None:
            return None
        d = self.codecs.encoded_digest(lid, layer, codec)
        if d is not None:
            with self._lock:
                self._codec_digest_cache[key] = d
        return d

    def _revert_codec_choice(self, dest: NodeID, lid: LayerID) -> None:
        """A chosen codec turned out unstampable (encode failed): the
        pair reverts to canonical, loudly, and the memo pins the
        reversion so replans don't flap."""
        log.warn("wire codec reverted to raw for pair (encoded digest "
                 "unavailable)", dest=dest, layerID=lid)
        with self._lock:
            self._codec_choice[(dest, lid)] = ""
            row = self.assignment.get(dest)
            if row is not None and lid in row:
                row[lid] = dataclasses.replace(row[lid], codec="")
            pod_pair = (lid, dest) in self._pod_pairs
        self.jobs.apply_codecs({(dest, lid): ""})
        if pod_pair:
            # The revert de-uniforms the pod's wire byte space for this
            # layer (docs/fabric.md: one gather = one encoding) —
            # degrade the (layer, pod) to host path instead of letting
            # the watchdog discover a gather that can never verify.
            pid = self._pod_of.get(dest)
            if pid is not None:
                log.warn("codec revert de-uniforms a pod layer; "
                         "degrading to host path", layerID=lid, pod=pid)
                self._degrade_pod_layer(lid, pid)

    def _send_digests_to(self, dest: NodeID) -> None:
        if dest == self.node.my_id:
            return
        with self._lock:
            digests = ({lid: self.layer_digests[lid]
                        for lid in self._digest_row_locked(dest)
                        if lid in self.layer_digests}
                       if integrity.digests_enabled() else {})
            shards = self._assigned_shards_locked(dest)
            # Versioned rollout targets (docs/swap.md): the stamp is
            # the one leader→dest channel preceding the bytes, so the
            # dest's holdings and acks carry the version tag.
            versions = {lid: meta.version
                        for lid, meta in
                        self._digest_row_locked(dest).items()
                        if meta.version}
            # Sticky: once ANY sharded target or shard holding exists,
            # later stamps must keep carrying the dest's target picture
            # even after widening removed the specs.
            self._sharding_seen = (
                self._sharding_seen or bool(shards)
                or any(m.shard for row in self.status.values()
                       for m in row.values()))
            if self._sharding_seen and not integrity.digests_enabled():
                # With digests OFF the shards map is the ONLY channel
                # that can tell a dest its target reverted to the full
                # layer (the digest-keyed widen detection has nothing
                # to iterate): explicit "" entries carry the reconcile.
                for lid in self._digest_row_locked(dest):
                    shards.setdefault(lid, "")
            # Wire-codec transfers (docs/codec.md): the chosen codec
            # per assigned layer rides the stamp — the one leader→dest
            # channel preceding the bytes — so the dest accounts the
            # transfer in encoded byte space from the first fragment.
            codec_map = {lid: meta.codec
                         for lid, meta in
                         self._digest_row_locked(dest).items()
                         if meta.codec}
            if self._codec_seen and not integrity.digests_enabled():
                # With digests OFF the codec map is the ONLY channel
                # that can tell a dest a pair REVERTED to raw (with
                # digests on, the pair's digest entry carries the
                # reconcile): explicit "" entries clear the dest's
                # stale codec expectation — same sticky-"" discipline
                # as the shards map above.
                for lid in self._digest_row_locked(dest):
                    codec_map.setdefault(lid, "")
        full_digests: Dict[LayerID, str] = {}
        if integrity.digests_enabled():
            # For codec pairs the stamped digest is CODEC-QUALIFIED:
            # the hash of exactly the encoded bytes — the CANONICAL
            # digest must never reach the dest for them (encoded bytes
            # would "fail" it forever).  Three cases, mirroring the
            # sharded range-digest policy (docs/sharding.md):
            # leader-readable layers stamp the encoded digest; a
            # readable layer that REFUSES to encode (not a model blob)
            # reverts the pair to raw; a holder-only layer keeps the
            # codec and stamps NO digest — the transfer verifies by
            # per-fragment CRC alone (docs/codec.md, honest limits;
            # the seeders' deterministic encode keeps multi-sender
            # ranges byte-identical).  Delta pairs additionally stamp
            # the CANONICAL digest under FullDigests: the wire stream
            # verifies under its own identity, the RECONSTRUCTED bytes
            # under the canonical one — both gates must pass before ack.
            bad = []
            for lid, c in sorted(codec_map.items()):
                d = self._codec_digest(lid, c)
                if d is not None:
                    digests[lid] = d
                    if delta_base_digest(c):
                        with self._lock:
                            full = self.layer_digests.get(lid)
                        if full:
                            full_digests[lid] = full
                        else:
                            # No canonical identity for the reconstructed
                            # form: the delta cannot gate — revert.
                            self._revert_codec_choice(dest, lid)
                            bad.append(lid)
                            digests.pop(lid, None)
                    continue
                with self._lock:
                    readable = (
                        self.layers.get(lid) is not None
                        and self.layers[lid].meta.location
                        != LayerLocation.CLIENT)
                if readable:
                    self._revert_codec_choice(dest, lid)
                    bad.append(lid)
                else:
                    digests.pop(lid, None)
                    log.info("codec pair stamped without a digest "
                             "(holder-only layer; CRC-only verify)",
                             dest=dest, layerID=lid, codec=c)
            for lid in bad:
                codec_map.pop(lid, None)
        # Fabric-assisted pod delivery (docs/fabric.md): the dest's pod
        # pairs ride the stamp as {layer: pod width} — the channel that
        # tells it to feed its verified shard into the on-mesh
        # reconstruction and ack the FULL tree, not stop at the shard.
        # Pods whose EVERY member already materialized are omitted: a
        # re-stamp (job admission, update) must not re-trigger
        # publish/gather rounds in the steady state.
        with self._lock:
            pods = {}
            pod_of = getattr(self, "_pod_of", {})
            pod_members = getattr(self, "pods", {})
            for (lid, d2), spec in self._pod_pairs.items():
                if d2 != dest:
                    continue
                want = (self.assignment.get(dest) or {}).get(lid)
                if want is None or want.shard != spec:
                    continue
                pid = pod_of.get(dest)
                members = (pod_members.get(pid, ())
                           if pid is not None else ())
                done = bool(members)
                for m in members:
                    if (lid, m) not in self._pod_pairs:
                        continue
                    held = self.status.get(m, {}).get(lid)
                    w = (self.assignment.get(m) or {}).get(lid)
                    if (held is None or not delivered(held) or held.shard
                            or (w is not None and not codec_accepts(
                                held.codec, w.codec))):
                        done = False
                        break
                if not done:
                    pods[lid] = parse_shard_spec(spec)[0]
        if (not digests and not shards and not versions and not codec_map
                and not pods):
            return
        try:
            self.node.transport.send(
                dest, LayerDigestsMsg(
                    self.node.my_id, digests, epoch=self.epoch,
                    shards=shards,
                    range_digests=self._range_digests_for(shards,
                                                          codec_map),
                    versions=versions, codecs=codec_map, pods=pods,
                    full_digests=full_digests))
        except (OSError, KeyError) as e:
            log.warn("digest stamp send failed", dest=dest, err=repr(e))

    # ------------------------------------------------------ telemetry plane

    def handle_time_sync(self, msg: TimeSyncMsg) -> None:
        """Answer a node's clock probe with this leader's wall clock —
        the reference clock multi-host traces align on (docs/
        observability.md).  Replies (another seat answering a probe this
        leader never sent) are ignored."""
        if msg.reply:
            return
        try:
            self.node.transport.send(
                msg.src_id,
                TimeSyncMsg(self.node.my_id, msg.t0_ms,
                            t1_ms=time.time() * 1000.0, reply=True))
        except (OSError, KeyError) as e:
            log.debug("time-sync reply send failed", dest=msg.src_id,
                      err=repr(e))

    def handle_metrics_report(self, msg: MetricsReportMsg) -> None:
        """Fold one node's cumulative telemetry snapshot into the
        cluster table.  Epoch-fenced: a reporter still pointing at a
        dead predecessor (its epoch is below this leader's) is stale
        by definition — its next lease observation re-points it and the
        following report carries the same cumulative totals, so nothing
        is lost by dropping the stale one."""
        if 0 <= msg.epoch < self.epoch:
            trace.count("telemetry.fenced_report")
            return
        snap = {"counters": msg.counters, "gauges": msg.gauges,
                "links": msg.links, "hists": msg.hists,
                "spans": msg.spans,
                "t_wall_ms": msg.t_wall_ms,
                "proc": msg.proc, "_recv_mono": time.monotonic()}
        with self._lock:
            self.cluster_metrics[msg.src_id] = snap
        self._replicate("metrics", Node=msg.src_id,
                        Counters=msg.counters, Gauges=msg.gauges,
                        Links=msg.links, Hists=msg.hists,
                        Spans=msg.spans,
                        T=msg.t_wall_ms, Proc=msg.proc)
        events = self._health_observe(msg.src_id, snap, foreign=msg.health)
        # Closed-loop autonomy (docs/autonomy.md): every metrics
        # interval IS the policy evaluation tick — the engine senses
        # the folded serve signals + the NEW health events and drives
        # the leader's own chokepoints.  Unarmed engines return
        # immediately.
        self.policy.tick(msg.src_id, snap, events)

    def _health_observe(self, node_id: NodeID, snap: dict,
                        foreign=None) -> List[dict]:
        """Fold one report into the fleet health timeline (docs/
        observability.md): interval deltas + straggler scoring against
        the modeled link rates.  New events are logged the moment they
        are detected — the live channel ``-watch`` surfaces — and
        replicated (kind "health") so a promoted standby keeps the
        event history with onset timestamps.  Returns the new events
        (the policy engine's link-rule input)."""
        events = self.health.observe(
            node_id, snap, self._modeled_link_rate,
            expected_srcs=self._health_expected_srcs(node_id))
        if foreign:
            # Advisory reporter-surfaced events (MetricsReportMsg
            # .health) fold in verbatim, deduplicated by onset.
            events = list(events) + self.health.ingest(foreign)
        for ev in events:
            trace.count(f"telemetry.health_{ev.get('kind', 'event')}")
            log.warn("fleet health event", **ev)
            self._replicate("health", Events=[ev])
        return list(events)

    def _modeled_link_rate(self, src: NodeID, dest: NodeID) -> int:
        """The modeled rate (bytes/s) health scoring judges the (src,
        dest) link against, or 0 to skip.  The base leader has no link
        model — mode 3 overrides this with the flow solver's inputs,
        gated to links with an in-flight pair so a completed burst is
        never mis-read as a straggler."""
        return 0

    def _health_expected_srcs(self, dest: NodeID):
        """Sources with dispatched in-flight pairs to ``dest`` — the
        links health scoring must judge even when their FIRST byte
        never landed (no snapshot row).  Base leader: none (no link
        model); mode 3 reads its live-job index."""
        return ()

    def await_metrics(self, newer_than: float = 0.0,
                      timeout: float = 5.0) -> bool:
        """Block until every node in status has reported a metrics
        snapshot received after ``newer_than`` (monotonic), or the
        timeout elapses — the -report path's freshness gate, so a fast
        run's report isn't written from pre-completion snapshots.
        Receivers flush a final report on startup, so the common wait
        is one control round-trip, not a report interval."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                peers = set(self.status) - {self.node.my_id}
                fresh = {n for n, s in self.cluster_metrics.items()
                         if s.get("_recv_mono", 0.0) >= newer_than}
            if peers <= fresh:
                return True
            if time.monotonic() >= deadline:
                log.warn("metrics reports missing at report time",
                         missing=sorted(peers - fresh))
                return False
            time.sleep(0.05)

    def cluster_telemetry(self) -> dict:
        """The folded cluster view: per-node snapshots (the leader's own
        process read live from the registry), cluster-summed counters,
        and the per-(src, dest) link table with each field taken from
        the endpoint that owns it (utils/telemetry.fold_links).  This is
        what the -watch hook logs mid-run and what cli/report.py renders
        into RUN_REPORT."""
        from ..utils import threads as threads_util

        threads_util.publish_census()
        own = telemetry.snapshot()
        own_gauges = dict(own.get("gauges") or {})
        for name, rec in (own.get("phases") or {}).items():
            own_gauges[f"phase.{name}_ms"] = rec["ms"]
        with self._lock:
            reports = {n: {k: v for k, v in s.items()
                           if not k.startswith("_")}
                       for n, s in self.cluster_metrics.items()}
        reports[self.node.my_id] = {
            "proc": own.get("proc", ""),
            "counters": own.get("counters") or {},
            "gauges": own_gauges,
            "links": own.get("links") or {},
            "spans": own.get("spans") or [],
            # A live registry read is by definition the freshest view
            # of this process — it must beat any shipped report from a
            # co-resident node in the per-process counter fold.
            "t_wall_ms": time.time() * 1000.0,
        }
        return {
            "nodes": reports,
            "counters": telemetry.fold_counters(reports),
            "links": telemetry.fold_links(reports),
            # The merged cluster span timeline + the derived fleet
            # health view (docs/observability.md) — what the critical-
            # path analyzer and the RUN_REPORT sections consume.
            "spans": telemetry.fold_spans(reports),
            "health": self.health.snapshot(),
        }

    def dest_bytes_table(self) -> Dict[str, dict]:
        """Per-dest WIRE vs DECODED byte accounting for the run report
        (docs/codec.md): ``wire_bytes`` is what actually crossed the
        network for each delivered pair — the ENCODED size for a
        quantized transfer, the shard range's bytes for a sharded one —
        and ``decoded_bytes`` is what the dest materializes.  The two
        are reported as separate columns on purpose: the telemetry link
        table reconciles against WIRE bytes, never the decoded side."""
        out: Dict[str, dict] = {}
        plane = self.codecs
        with self._lock:
            for dest, lids in self.assignment.items():
                if dest == self.node.my_id:
                    continue
                row = {"wire_bytes": 0, "decoded_bytes": 0, "layers": 0,
                       "codec_layers": 0}
                for lid, want in lids.items():
                    held = self.status.get(dest, {}).get(lid)
                    if not satisfies(held, want):
                        continue
                    raw = self._layer_size_locked(lid)
                    if not raw and plane is not None:
                        raw = plane.decoded_nbytes(lid) or 0
                    codec = held.codec
                    wire = raw
                    if codec and plane is not None:
                        wire = plane.nbytes(lid, codec) or raw
                    if held.shard:
                        wire = shard_range(held.shard, wire)[1]
                        raw = shard_range(held.shard, raw)[1]
                    row["wire_bytes"] += wire
                    row["decoded_bytes"] += raw
                    row["layers"] += 1
                    if codec:
                        row["codec_layers"] += 1
                if row["layers"]:
                    out[str(dest)] = row
        return out

    def log_cluster_metrics(self) -> dict:
        """Log (and return) the folded cluster table — the mid-run
        status hook behind ``cli.main -watch`` and the end-of-run dump
        the offline run report is built from.  The dump now also
        carries the merged span timeline + health events (the offline
        critical-path/health sections read them back), and each active
        job gets its own live progress line (docs/observability.md)."""
        table = self.cluster_telemetry()
        health = table.get("health") or {}
        log.info("cluster telemetry",
                 nodes=sorted(table["nodes"]),
                 counters=table["counters"],
                 links=table["links"],
                 gauges={str(n): s.get("gauges") or {}
                         for n, s in table["nodes"].items()},
                 spans=table.get("spans") or [],
                 health=health)
        for ev in (health.get("events") or [])[-8:]:
            log.warn("fleet health timeline", **ev)
        for jid, row in sorted(self.job_progress().items()):
            # The -watch per-job LIVE progress line: delivered/total
            # bytes from the per-job link split, ETA from the job's own
            # tier pacing (the solver's min-time for its remaining
            # demand at the last re-plan).
            log.info("job progress", job=jid, **row)
        return table

    def job_progress(self) -> Dict[str, dict]:
        """Per-job delivery progress (docs/observability.md):
        ``delivered_bytes`` summed off the job-tagged link rows of the
        folded cluster table (the interval-delta data ``-watch``
        already ships), ``remaining_bytes`` sized from the job's
        remaining pairs (raw layer sizes — codec/shard-qualified pairs
        size by their canonical bytes, the honest approximation the
        docs note), and ``eta_s`` from the job's TIER PACING — the
        joint solver's min-time budget for exactly this job's remaining
        demand at the last re-plan."""
        jobs = getattr(self, "jobs", None)
        if jobs is None:
            return {}
        pairs = jobs.progress_pairs()
        if not pairs:
            return {}
        with self._lock:
            reports = {n: {k: v for k, v in s.items()
                           if not k.startswith("_")}
                       for n, s in self.cluster_metrics.items()}
            tier_ms = dict(getattr(self, "_tier_time", {}) or {})
            sizes = {}
            for row in pairs.values():
                for _dest, lid in row["remaining"]:
                    if lid not in sizes:
                        sizes[lid] = self._layer_size_locked(lid)
        own = telemetry.snapshot()
        reports[self.node.my_id] = {"proc": own.get("proc", ""),
                                    "links": own.get("links") or {},
                                    "t_wall_ms": time.time() * 1000.0}
        links = telemetry.fold_links(reports)
        delivered_by_job: Dict[str, int] = {}
        for key, row in links.items():
            job = row.get("job")
            if job:
                delivered_by_job[job] = (delivered_by_job.get(job, 0)
                                         + int(row.get("delivered_bytes")
                                               or 0))
        out: Dict[str, dict] = {}
        for jid, row in pairs.items():
            remaining_b = sum(sizes.get(lid, 0)
                              for _d, lid in row["remaining"])
            delivered_b = delivered_by_job.get(jid, 0)
            rec = {"state": row["state"], "kind": row["kind"],
                   "remaining_pairs": len(row["remaining"]),
                   "total_pairs": row["total_pairs"],
                   "delivered_bytes": delivered_b,
                   "total_bytes": delivered_b + remaining_b}
            eta = tier_ms.get(jid)
            if row["state"] == "active" and eta:
                rec["eta_s"] = round(eta / 1000.0, 3)
            out[jid] = rec
        return out

    def handle_generate_req(self, msg: GenerateReqMsg) -> None:
        """The leader seat serves no model — refuse immediately so a
        misdirected request gets an error, not a requester timeout (the
        serving invariant: every reachable seat ANSWERS)."""
        try:
            self.node.transport.send(
                msg.src_id,
                GenerateRespMsg(self.node.my_id, msg.req_id, [],
                                "the leader seat serves no model; ask a "
                                "booted assignee"),
            )
        except (OSError, KeyError, ConnectionError) as e:
            log.error("generate refusal send failed", requester=msg.src_id,
                      err=repr(e))

    # ------------------------------------------------------------- lifecycle

    def start_distribution(self) -> "queue.Queue[Assignment]":
        """Fires when all assigned nodes have announced (node.go:222)."""
        return self._start_q

    def ready(self) -> "queue.Queue[Assignment]":
        """Fires when the assignment is satisfied (node.go:225)."""
        return self._ready_q

    def boot_ready(self) -> "queue.Queue[Dict[NodeID, float]]":
        """Fires once every assignee has reported booting its model from
        the delivered layers (receivers constructed with ``boot_cfg``);
        carries {node: that node's boot seconds}.  The leader's
        time-to-first-token — timer start → last boot report — is logged
        as "timer stop: first token"."""
        return self._boot_q

    def boots_seen(self):
        """Node ids that reported a boot outcome so far (diagnostics)."""
        with self._lock:
            return list(self._booted)

    def boot_kinds(self):
        """Reported boot kinds per node ("full"/"stage"/"skipped"/
        "failed") — the CLI surfaces failures in its exit status."""
        with self._lock:
            return dict(self._boot_kinds)

    def _touch_liveness(self, src_id: NodeID) -> None:
        """Refresh a reporter's lease.  The hierarchical leader skips
        GROUPED members — their liveness belongs to the sub-leader's
        detector, and a forwarded boot report must not create a
        root-side lease that later falsely expires."""
        self.detector.touch(src_id)

    def handle_boot_ready(self, msg: BootReadyMsg) -> None:
        self._touch_liveness(msg.src_id)
        logger = log.error if msg.kind == "failed" else log.info
        logger("node booted its model", node=msg.src_id, kind=msg.kind,
               boot_seconds=round(msg.seconds, 6))
        with self._lock:
            if msg.src_id not in self.assignment:
                # Only assignees gate the boot wait; a seeder's "skipped"
                # report (it holds no assigned model) is just liveness.
                return
            self._booted[msg.src_id] = msg.seconds
            self._boot_kinds[msg.src_id] = msg.kind
        self._maybe_complete_boot_wait()

    def _maybe_complete_boot_wait(self) -> None:
        """Fire the boot/TTFT wait exactly once, when every REMAINING
        assignee has reported a boot outcome (incl. "failed"/"skipped").
        Called from ``handle_boot_ready`` and from ``crash`` — a dead
        assignee shrinks the assignment, which can be what completes the
        wait (found live: a dest whose boot died silently left the
        leader blocked in ``boot_ready().get()`` forever)."""
        with self._lock:
            if (self._boot_reported or not self._startup_sent
                    or not self.boot_enabled):
                return
            if set(self.assignment) - set(self._booted):
                return
            self._boot_reported = True
            ttft = (time.monotonic() - self._t_start
                    if self._t_start is not None else 0.0)
            booted = dict(self._booted)
        log.info("timer stop: first token", seconds=round(ttft, 6))
        self._dispatch_serve()
        self._boot_q.put(booted)

    def _dispatch_serve(self) -> None:
        """Broadcast the ServeMsg (multi-controller serving) — or its
        CANCELLATION (empty members) when startup promised serving but
        the pod can no longer serve (a crash changed the assignment, the
        fabric got disabled): receivers told ``serve=True`` are waiting
        and must be released, not left to a timeout."""
        served = self.serve_members()
        members, counts = served if served is not None else (None, [])
        if members is not None:
            # Every member must have REALLY booted a stage model: a
            # "skipped" (opted-out) or "full" report can't enter the
            # collective, and dispatching anyway would park the others
            # inside it — cancel instead.
            with self._lock:
                kinds = {m: self._boot_kinds.get(m) for m in members}
            if any(k != "stage" for k in kinds.values()):
                log.warn("pod serve cancelled: not all members stage-"
                         "booted", kinds={str(k): v for k, v in
                                          kinds.items()})
                members = None
        if members is None and not self._serve_promised:
            return
        serve = ServeMsg(self.node.my_id, members or [],
                         counts=counts if members else [],
                         gen=self.serve_generate, epoch=self.epoch)
        with self._lock:
            recipients = sorted(
                (set(self.status) | set(members or ()))
                - {self.node.my_id}
            )
        failed_member = False
        for r in recipients:
            try:
                self.node.transport.send(r, serve)
            except (OSError, KeyError) as e:
                log.error("failed to send serveMsg", dest=r, err=repr(e))
                failed_member = failed_member or r in (members or ())
        if members and failed_member:
            # A member never got the ServeMsg: the others would block in
            # the collective on the absent peer.  Best-effort cancel
            # (a member that already ENTERED can't be recalled — the
            # same residual window as plan cancellation, see
            # parallel/spmd_fabric.py).
            cancel = ServeMsg(self.node.my_id, [], epoch=self.epoch)
            for r in recipients:
                try:
                    self.node.transport.send(r, cancel)
                except (OSError, KeyError) as e:
                    log.error("serve cancel undeliverable", dest=r,
                              err=repr(e))
            log.error("pod serve aborted: a member missed the ServeMsg")
            return
        if members:
            log.info("pod serve dispatched", members=members)
        else:
            log.warn("pod serve cancelled: pod no longer servable")

    def serve_members(self):
        """(Stage-ordered member nodes, their stage depths) for
        multi-controller serving, or None.  The leader is model-agnostic,
        so the check is structural (blob ids only): the max assigned id H
        is the head blob, every member holds H (a process can only decode
        what its store has), and the members' remaining ids are contiguous
        slices partitioning [0, H) — UNEVEN slices are fine (the members
        pad to the deepest stage, ``pp_serve``).  Counts come from the
        SAME assignment snapshot the membership was validated on (a
        concurrent update()/crash must not desynchronize them).
        Receivers re-validate against the model."""
        if not self._spmd or self.placement is None or not self.boot_enabled:
            return None
        with self._lock:
            assignment = {n: set(lids) for n, lids in self.assignment.items()}
        if len(assignment) < 2:
            return None
        all_ids = set().union(*assignment.values())
        if not all_ids:
            return None
        head = max(all_ids)
        if self._fabric_disabled:
            # Same hazard as device plans: a restarted member is outside
            # the runtime and one more collective hangs every survivor.
            return None
        slices = {}
        for n, lids in assignment.items():
            if head not in lids:
                return None
            body = sorted(lids - {head})
            if not body or body != list(range(body[0], body[-1] + 1)):
                return None
            slices[n] = (body[0], body[-1] + 1)
        spans = sorted(slices.values())
        pos = 0
        for s, e in spans:
            if s != pos:
                return None
            pos = e
        if pos != head:
            return None
        members = sorted(slices, key=lambda n: slices[n][0])
        return members, [slices[m][1] - slices[m][0] for m in members]

    def close(self) -> None:
        self._watch_stop.set()
        self._lease_stop.set()
        if self.replicator is not None:
            self.replicator.close()
        self.detector.stop()
        if not self._shared_loop:
            # A promoted leader borrows the worker's loop — closing it
            # here would kill the worker's data plane too.
            self.loop.stop()

    # -------------------------------------------------------------- handlers

    def _await_announce_set_locked(self) -> Set[NodeID]:
        """The nodes whose announce gates the distribution start.  Lock
        held.  Grouped members announce to their SUB-LEADER; the folded
        aggregate creates their status rows, which is what satisfies
        this gate for them (docs/hierarchy.md)."""
        return set(self.assignment) | self.expected_nodes

    def _maybe_start(self) -> bool:
        """Flip to started when every awaited node has announced."""
        with self._lock:
            if self._started or self._starting:
                return False
            for node_id in self._await_announce_set_locked():
                if node_id not in self.status:
                    return False
            self._starting = True
        # Digest stamps go out BEFORE the timer starts: the stamp (and
        # any wait for the leader's own background hash) is announce-
        # phase work, not delivery time.  _started stays False until the
        # timer exists — an announce landing mid-hash must register as a
        # fresh peer, not trigger a pre-start re-plan against a None
        # _t_start.  The latch MUST clear even if the digest send
        # raises, or every later announce bounces off it and the run
        # wedges with no timer and no layers ever sent.
        try:
            # Codec choices precede the stamp: the digest channel is
            # what tells each dest its transfers' byte spaces
            # (docs/codec.md).
            self._stamp_targets()
            self._send_digests()
            with self._lock:
                self._started = True
                self._t_start = time.monotonic()
        finally:
            with self._lock:
                self._starting = False
        log.info("timer start")
        self._start_q.put(self.assignment)
        self._send_boot_hints()
        return True

    def _send_boot_hints(self) -> None:
        """Tell each assignee what it will hold, so it can compile its
        boot programs while the bytes are still on the wire (shapes are
        all XLA needs).  Advisory: a lost hint only costs the overlap."""
        if not self.boot_enabled:
            return
        with self._lock:
            per_dest = {dest: sorted(ids)
                        for dest, ids in self.assignment.items()
                        if dest != self.node.my_id and ids}
        for dest, blob_ids in per_dest.items():
            try:
                self.node.transport.send(
                    dest, BootHintMsg(self.node.my_id, blob_ids,
                                      epoch=self.epoch))
            except (OSError, KeyError) as e:
                log.warn("boot hint send failed", dest=dest, err=repr(e))

    def _send_boot_hint_to(self, dest: NodeID) -> None:
        """One assignee's hint (re-announce / update paths)."""
        if not self.boot_enabled or dest == self.node.my_id:
            return
        with self._lock:
            ids = sorted(self.assignment.get(dest) or {})
        if not ids:
            return
        try:
            self.node.transport.send(
                dest, BootHintMsg(self.node.my_id, ids, epoch=self.epoch))
        except (OSError, KeyError) as e:
            log.warn("boot hint send failed", dest=dest, err=repr(e))

    def handle_announce(self, msg: AnnounceMsg) -> None:
        """Register the peer; once everyone announced, start sending
        (node.go:295-324).

        A *re*-announce after the start is a restarted process: its status
        row is refreshed (delivered-to-RAM layers died with it; surviving
        state arrives via the announce itself, checkpointed partials
        included) and the scheduler re-plans its missing layers."""
        if self.membership.is_left(msg.src_id):
            # Zombie rejoiner (docs/membership.md): a departed member's
            # late announce must not resurrect it as a schedulable seat
            # nobody monitors — only a fresh JoinMsg re-admits it.
            trace.count("membership.zombie_fenced")
            log.warn("announce from a departed member fenced (a new "
                     "JoinMsg re-admits it)", node=msg.src_id)
            return
        was_dead = self.detector.is_dead(msg.src_id)
        if was_dead:
            log.warn("declared-dead node announced again; reviving",
                     node=msg.src_id)
            self.detector.revive(msg.src_id)
        self.detector.touch(msg.src_id)
        # Wire-codec capability (docs/codec.md): what this node can
        # decode — the negotiation's capability table.  An announce
        # with no codecs is authoritative too (a restarted node may
        # have lost the capability with its config), and REVOCATION
        # replicates like a grant — a standby keeping a stale
        # capability would let a promoted leader choose quantized
        # transfers the node can no longer decode.  Compare-before-
        # replicate: re-announces with an unchanged table (every
        # recovery replan) add no replication traffic.
        with self._lock:
            new_caps = (frozenset(str(c) for c in msg.codecs)
                        if msg.codecs else None)
            old_caps = self.node_codecs.get(msg.src_id)
            if new_caps:
                self.node_codecs[msg.src_id] = new_caps
            else:
                self.node_codecs.pop(msg.src_id, None)
        if new_caps != old_caps:
            self._replicate_codecs()
        if self._verify_member_source(msg.src_id, msg.digests):
            self._merge_announced_digests(msg.src_id, msg.digests)
            # Content index: an announce is the node's authoritative
            # current inventory — replace its digest contribution
            # wholesale (a restarted node no longer vouches for its dead
            # incarnation's bytes); acks extend it as deliveries land.
            self.content.reset_node(msg.src_id, msg.digests)
        else:
            # Probation (docs/membership.md): a JOINING seat whose
            # holdings have not digest-verified neither stamps the
            # integrity plane nor vouches in the content index — it is
            # a dest, not a source, until a clean announce verifies.
            self.content.reset_node(msg.src_id, {})
        with self._lock:
            # A re-plan is only for a node the run already has business
            # with: one that restarted (still in status), one returning
            # from the dead (crash() popped its row / dropped its
            # assignment), or an assignee added by update() that hadn't
            # announced yet.  A brand-new late announcer with no assigned
            # layers must NOT re-drive in-flight transfers.
            known = (msg.src_id in self.status or was_dead
                     or msg.src_id in self._dropped_assignment
                     or msg.src_id in self.assignment)
            reannounce = self._started and known
            # Always refresh: an announce is the node's authoritative
            # current inventory (a pre-start restart must not leave a stale
            # row claiming layers the new incarnation lost).
            self.status[msg.src_id] = msg.layer_ids
            self.node.add_node(msg.src_id)
            dropped = self._dropped_assignment.pop(msg.src_id, None)
            if dropped and not self._startup_sent:
                # The node was declared crashed and its assignment dropped;
                # it's back, so it gets its layers back.
                self._restore_assignment(msg.src_id, dropped)
                log.info("restored dropped assignment for returned node",
                         node=msg.src_id, layers=sorted(dropped))
            elif dropped:
                log.warn("node returned after distribution finished; its "
                         "dropped assignment stays dropped", node=msg.src_id)
            if msg.partial:
                # Checkpointed in-progress coverage (resume extension);
                # mode 3 schedules only the complement.
                self.partial_status[msg.src_id] = msg.partial
            else:
                # A re-announce without partials supersedes any stale ones
                # (e.g. the checkpoint dir was wiped between restarts).
                self.partial_status.pop(msg.src_id, None)
            # A re-announcing dest's salvage pairs revert to the normal
            # re-plan machinery — its announce carries the authoritative
            # partial coverage the planner schedules around.
            if reannounce:
                self._salvaging = {p for p in self._salvaging
                                   if p[1] != msg.src_id}
        self._replicate("status", Node=msg.src_id,
                        Layers=layer_ids_to_json(msg.layer_ids))
        self._replicate(
            "partial", Node=msg.src_id,
            Partial=({str(l): info for l, info in msg.partial.items()}
                     if msg.partial else None))
        bw_map = getattr(self, "node_network_bw", None)
        if (bw_map is not None and msg.nic_bw > 0
                and (msg.src_id in self._joiner_bw_pinned
                     # A roster-admitted seat (it joined — its addr
                     # rides the replicated membership plane, so this
                     # ALSO covers a promoted leader whose local pinned
                     # set died with its predecessor) whose modeled
                     # rate differs from its announced one.
                     or (bool(self.membership.addr_of(msg.src_id))
                         and bw_map.get(msg.src_id)
                         != int(msg.nic_bw)))):
            # Joiner NIC modeling (docs/membership.md): the admit-time
            # value was the most conservative configured rate (the seat
            # is in nobody's config); the joiner's own announce-carried
            # rate supersedes it, so the solver models the real link
            # instead of starving the refill behind a worst-case guess.
            with self._lock:
                pinned = bw_map.get(msg.src_id)
                bw_map[msg.src_id] = int(msg.nic_bw)
            self._joiner_bw_pinned.discard(msg.src_id)
            trace.count("membership.joiner_bw_honored")
            log.info("joiner's announce-carried NIC rate honored",
                     node=msg.src_id, pinned=pinned, rate=msg.nic_bw)
        with self._lock:
            pending_want = self._join_pending.pop(msg.src_id, None)
        if pending_want is not None:
            # The joiner's first announce: its cold-boot holdings are
            # now in status (and, verified, in the content index), so
            # the refill job's remaining demand is exactly the
            # complement (docs/membership.md).
            self._admit_join_job(msg.src_id, pending_want)
        if self._started and self.jobs.has_active():
            # An announce is authoritative inventory, and an ACK can be
            # LOST in a failover window (sent to the dead leader before
            # the worker re-pointed): reconcile active jobs against the
            # refreshed status so a delivered-but-unacked pair credits
            # here instead of wedging the job (and any swap fence
            # waiting on it) forever — the same repair adopt_shadow
            # runs at takeover.
            with self._lock:
                status_view = {n: dict(r) for n, r in self.status.items()}
            finished = self.jobs.credit_status(status_view)
            if finished:
                log.info("announce reconciled job pairs a lost ack "
                         "left uncredited", jobs=finished,
                         node=msg.src_id)
                self._jobs_completed(finished)
        if dropped:
            # The node came back from declared death: purge it from the
            # shadow's dropped map too, or a takeover would re-apply
            # the job-pair drops against a LIVE dest (adopt_shadow
            # re-drops for every still-dropped node).
            self._replicate("revive", Node=msg.src_id)
        if msg.src_id in self.standbys:
            # A standby joined (or re-joined): snapshot first, deltas
            # thereafter.
            self._send_snapshot_to(msg.src_id)
        if self._maybe_start():
            self.send_layers()
            # Announce metadata can already satisfy the assignment (every
            # assignee holds its layers in RAM) — no acks will ever arrive,
            # so check now or hang.  (The reference checks only on acks,
            # node.go:410-432, and would hang here.)
            self._maybe_finish()
            return
        if reannounce:
            log.info("node re-announced; re-planning", node=msg.src_id)
            if self._spmd and not self._fabric_disabled:
                # Either the process restarted (fresh executor at seq 0,
                # possibly outside the jax.distributed runtime — a fabric
                # plan would hang every survivor inside the collective) or
                # a live dest is reporting a failed fabric plan.  Both
                # mean the lockstep is no longer trustworthy: the rest of
                # the run rides the host path.
                log.error("re-announce under spmd fabric; disabling the "
                          "device plane for the rest of the run",
                          node=msg.src_id)
                self._fabric_disabled = True
            self._maybe_finish()
            with self._lock:
                finished = self._startup_sent
            if not finished:
                # A restarted assignee lost its warm jit caches with its
                # process — and has the longest re-transfer window to
                # overlap a fresh precompile with.  (Receivers latch the
                # first hint, so a repeat to a live process is a no-op.)
                # It also lost its digest stamp: re-stamp before the
                # re-plan re-sends its layers.
                self._send_boot_hint_to(msg.src_id)
                self._send_digests_to(msg.src_id)
                self._on_reannounce(msg.src_id)

    def _on_reannounce(self, node_id: NodeID) -> None:
        """Re-drive delivery for a restarted node; mode 2 overrides (its
        job table needs surgical repair, not a wholesale re-run)."""
        self._recover()

    def _restore_assignment(self, node_id: NodeID, layers: LayerIDs) -> None:
        """Re-admit a previously dropped assignee (called under _lock).
        The dropped set is the node's MERGED promise (base + any job
        layers), so it restores into the base goal: jobs that completed
        with the drop stay completed, but the returned node still gets
        every layer it was promised."""
        self.assignment[node_id] = layers
        if self._base_assignment is not self.assignment:
            self._base_assignment[node_id] = layers

    def update(self, assignment: Assignment) -> None:
        """Re-target the distribution to a new goal state — the
        reference's never-implemented ``update(assignment)``
        (node.go:215-217).

        Declarative semantics: the new assignment wholly replaces the old
        BASE goal (admitted jobs keep their own targets and re-merge on
        top — a version rollout must not be cancelled by a base
        re-target).  Already-delivered layers are not re-sent; missing
        ones are scheduled; if the new goal adds work after ``ready``
        already fired, the completion cycle re-arms and ``ready()``
        delivers again once the new goal is met."""
        with self._lock:
            self._base_assignment = assignment
            self.assignment = self.jobs.merged_assignment(assignment)
            self._dropped_assignment.clear()
            if self._started:
                # Re-arm: every update() answers with its own ready event,
                # immediate when the new goal is already met.  Replicated
                # under THIS lock — see _maybe_finish: the shadow's
                # startup flag must flip in write order, or a takeover
                # adopts "FINISHED" and never re-drives the new goal.
                self._startup_sent = False
                self._replicate("startup", Sent=False)
        # Re-merge dropped the codec choices from the target metas;
        # re-apply the memoized ones (docs/codec.md) before anything
        # replicates or stamps the new goal.
        self._stamp_targets()
        # New assignees that haven't announced get liveness leases, so one
        # that never shows up is still detected (as in __init__'s seeding).
        for node_id in assignment:
            if node_id != self.node.my_id and node_id not in self.status:
                self.detector.touch(node_id)
        log.info("assignment updated", dests=sorted(assignment))
        with self._lock:
            merged = _nested_layer_map_to_json(self.assignment)
        # The shadow's "assignment" tracks the MERGED goal (it is what
        # adopt_shadow resumes); the BASE re-target rides its own delta
        # — a standby that attached before this update would otherwise
        # restore the snapshot's stale base, and the first post-takeover
        # goal recompute would silently revert the re-target.
        self._replicate("assignment", Assignment=merged)
        self._replicate("base_assignment",
                        Assignment=_nested_layer_map_to_json(assignment))
        with self._lock:
            started = self._started
        if started:
            # New goal, possibly new assignees (or new held-sets for old
            # ones): re-hint everyone.  Receivers latch the first hint,
            # so live processes ignore the repeat.  Digest stamps are
            # leader-authoritative and re-sent for the new goal.
            self._send_boot_hints()
            self._send_digests()
        self._drive(self._update_replan)

    def _update_replan(self) -> None:
        """Schedule the new goal's missing deliveries; mode 2 overrides
        (its live job table needs incremental repair, not a rebuild)."""
        self._recover()

    # ------------------------------------------------- multi-job service

    def submit_job(self, job_id: str, assignment: Assignment,
                   priority: int = 0, kind: str = "push",
                   digests: Optional[Dict[LayerID, str]] = None,
                   avoid: Optional[Set[NodeID]] = None,
                   version: str = "", swap_base: int = -1,
                   submitter: str = "", waves=None, slo=None,
                   split: float = -1.0) -> dict:
        """Admit one dissemination job into the long-lived service plane
        (docs/service.md) — the multi-job generalization of ``update()``.

        The job's target merges into the effective cluster goal; its
        remaining (dest, layer) demands are planned WITH every other
        active job's in one shared-capacity flow solve (mode 3;
        priorities preempt at the re-plan), and acks credit all jobs
        wanting a pair.  ``digests`` keys the job's layers by content
        (``xxh3:<hex>``): a dest already holding content-equal bytes
        resolves the layer locally — zero wire bytes — via the
        content store.  Idempotent per ``job_id``; returns the job's
        status summary.

        ``version``/``swap_base`` (docs/swap.md): a ``kind="swap"`` job
        tags every target meta with the rollout version — only
        deliveries verified under that version complete its pairs —
        and registers the swap driver record; on the job's clean
        completion the epoch-fenced commit fence flips every replica.

        ``kind="rollout"`` (docs/rollout.md) does not admit a job at
        all: it EXPANDS into the declared waves — each a chained
        ``kind="swap"`` job over its replica subset with the commit
        held for the pipeline — and returns the rollout summary."""
        if kind == "rollout":
            return self.rollouts.admit(
                str(job_id), assignment, waves, str(version),
                int(swap_base), priority=int(priority),
                digests=digests, slo=slo, split=float(split))
        digests = dict(digests or {})
        if version:
            # Stamp the rollout version onto every target: the merged
            # goal, the digest stamps, and the acks all carry it.
            assignment = {
                dest: {lid: dataclasses.replace(meta, version=version)
                       for lid, meta in lids.items()}
                for dest, lids in assignment.items()}
        with self._lock:
            # A long-lived daemon's layer store GROWS between jobs (a
            # rollout seeder loads v2 bytes): refresh the leader's own
            # status row so the planner can size + source the new
            # layers (the constructor only saw the boot-time store).
            own = self.status.setdefault(self.node.my_id, {})
            for lid, src in self.layers.items():
                if lid not in own:
                    own[lid] = LayerMeta(
                        location=src.meta.location,
                        limit_rate=src.meta.limit_rate,
                        source_type=src.meta.source_type,
                        data_size=src.data_size,
                        version=src.meta.version)
            own_row = layer_ids_to_json(own)
        self._replicate("status", Node=self.node.my_id, Layers=own_row)
        if digests:
            with self._lock:
                for lid, d in digests.items():
                    # Job digests are authoritative for NEW layer ids;
                    # an existing stamp (e.g. a holder's announce) wins,
                    # matching the first-writer rule of the integrity
                    # plane — EXCEPT for a swap job, which owns its v2
                    # ids outright: a retry after a bad-digest abort
                    # must be able to supersede the poisoned stamp, or
                    # no corrected rollout can ever verify.
                    if kind == "swap":
                        self.layer_digests[lid] = d
                    else:
                        self.layer_digests.setdefault(lid, d)
        with self._lock:
            status_view = {n: dict(r) for n, r in self.status.items()}
        job = self.jobs.admit(
            Job(job_id=str(job_id), assignment=assignment,
                priority=int(priority), kind=str(kind), digests=digests,
                avoid_sources={int(n) for n in (avoid or ())},
                admit_ms=time.time() * 1000.0,
                version=str(version), swap_base=int(swap_base),
                submitter=str(submitter)),
            status_view)
        trace.count("jobs.admitted")
        log.info("dissemination job admitted", job=job.job_id,
                 priority=job.priority, kind=job.kind,
                 dests=sorted(job.assignment),
                 remaining=len(job.remaining),
                 resolved_at_admit=job.resolved_at_admit)
        with self._lock:
            self.assignment = self.jobs.merged_assignment(
                self._base_assignment)
            rearmed = self._started and job.state == "active"
            if rearmed:
                # Like update(): the completion cycle re-arms; ready()
                # fires again when the whole current goal (all jobs)
                # is met.  Replicated under THIS lock so the delta
                # order matches the flag-write order (_maybe_finish's
                # in-lock Sent=True is the other writer).
                self._startup_sent = False
                self._replicate("startup", Sent=False)
            merged = _nested_layer_map_to_json(self.assignment)
        # The re-merge rebuilt the goal codec-less: re-apply choices
        # (and choose for the job's new pairs) before stamps/replans.
        self._stamp_targets()
        for node_id in job.assignment:
            if node_id != self.node.my_id and node_id not in self.status:
                self.detector.touch(node_id)
        self._replicate("job", **self.jobs.record(job.job_id))
        if digests:
            self._replicate("digests",
                            Digests={str(l): d
                                     for l, d in digests.items()})
        self._replicate("assignment", Assignment=merged)
        with self._lock:
            started = self._started
        if started:
            for dest in sorted(job.assignment):
                # A job's dests need their (possibly new) digest stamps
                # BEFORE the re-plan: the stamp is what triggers the
                # dest-side content resolve, and what the ack gate
                # verifies shipped layers against.
                self._send_digests_to(dest)
                self._send_boot_hint_to(dest)
        if kind == "swap" and version:
            self._register_swap(job)
        # Preemption revoke (docs/service.md): queued sends of tiers
        # this admission demotes are dropped at their senders before
        # the re-plan reclaims their budget (mode-3 override).
        self._preempt_revoke(job)
        self._drive(self._update_replan)
        job = self.jobs.get(job.job_id) or job
        if kind == "swap" and version and job.state == "done":
            # Admission found every pair already satisfied (all v2
            # bytes verified): the fence can fire right now.
            self._on_swap_job_done(job.job_id)
        return job.summary()

    def _preempt_revoke(self, job: Job) -> None:
        """Hook: a newly admitted job may demote lower tiers' queued
        sends.  Only mode 3 tracks dispatched sends (``_live_jobs``);
        the base scheduler has nothing to revoke."""

    def _submitter_id(self, msg: JobSubmitMsg) -> str:
        """The submitter identity quotas key on (docs/service.md):
        derived from the DLD_JOB_TOKEN the submit authenticated with
        (hashed — the identity must never leak the secret into logs or
        job records), falling back to the wire seat id on open
        clusters."""
        if msg.auth:
            import hashlib

            return hashlib.sha256(msg.auth.encode()).hexdigest()[:12]
        return f"node{msg.src_id}"

    def _quota_refusal(self, msg: JobSubmitMsg) -> str:
        """Per-submitter quota/rate check (docs/service.md); returns
        the refusal text ("" = admitted).  Idempotent resubmits of a
        KNOWN job id are never refused — the retry path must stay safe.
        Every refusal counts on ``jobs.quota_refused`` and is ANSWERED
        by the caller (the serving invariant)."""
        if self._job_quota <= 0 and self._job_rate_n <= 0:
            return ""
        if self.jobs.get(msg.job_id) is not None:
            return ""
        ident = self._submitter_id(msg)
        now = time.monotonic()
        if self._job_rate_n > 0:
            with self._lock:
                times = self._submit_times.setdefault(ident, [])
                times[:] = [t for t in times
                            if now - t < self._job_rate_s]
                over = len(times) >= self._job_rate_n
                if not over:
                    times.append(now)
            if over:
                trace.count("jobs.quota_refused")
                log.warn("job submit rate-limited", job=msg.job_id,
                         submitter=ident, limit=self._job_rate_n,
                         window_s=self._job_rate_s)
                return (f"rate limited: {self._job_rate_n} submits per "
                        f"{self._job_rate_s:g}s per submitter")
        if (self._job_quota > 0
                and self.jobs.active_count_for(ident) >= self._job_quota):
            trace.count("jobs.quota_refused")
            log.warn("job submit over quota", job=msg.job_id,
                     submitter=ident, quota=self._job_quota)
            return (f"quota exceeded: {self._job_quota} active jobs "
                    "per submitter")
        return ""

    def handle_job_submit(self, msg: JobSubmitMsg) -> None:
        """Wire half of ``submit_job`` — the ``cli.main -submit`` entry
        point.  Always answered (the serving invariant): admission
        returns the job row, a refusal returns an error."""
        if self._deposed:
            reply = JobStatusMsg(self.node.my_id, epoch=self.epoch,
                                 error="deposed: a higher-epoch leader "
                                       "owns the job table")
        elif self._job_token and not hmac.compare_digest(
                msg.auth.encode(), self._job_token.encode()):
            # Admission control (docs/service.md): the job plane now
            # MUTATES cluster state (swaps flip serving models), so a
            # token-armed leader refuses unauthenticated submitters —
            # constant-time compare (no timing oracle), counted, and
            # ANSWERED (the serving invariant).
            trace.count("jobs.unauthorized")
            log.warn("unauthorized job submit rejected",
                     job=msg.job_id, submitter=msg.src_id)
            reply = JobStatusMsg(self.node.my_id, epoch=self.epoch,
                                 error="unauthorized: this leader "
                                       "requires a job token "
                                       "(DLD_JOB_TOKEN)")
        elif not msg.job_id or not msg.assignment:
            reply = JobStatusMsg(self.node.my_id, epoch=self.epoch,
                                 error="job_id and a non-empty "
                                       "assignment are required")
        elif (refusal := self._quota_refusal(msg)):
            # Per-submitter quotas/rate limits (docs/service.md): the
            # refusal ANSWERS — a throttled submitter sees why, never a
            # timeout.
            reply = JobStatusMsg(self.node.my_id, epoch=self.epoch,
                                 error=refusal)
        else:
            try:
                summary = self.submit_job(msg.job_id, msg.assignment,
                                          priority=msg.priority,
                                          kind=msg.kind,
                                          digests=msg.digests,
                                          avoid=msg.avoid,
                                          version=msg.version,
                                          swap_base=msg.swap_base,
                                          submitter=self._submitter_id(msg),
                                          waves=msg.waves, slo=msg.slo,
                                          split=msg.split)
                reply = JobStatusMsg(self.node.my_id,
                                     jobs={msg.job_id: summary},
                                     epoch=self.epoch)
            except Exception as e:  # noqa: BLE001 — ALWAYS answer
                # The admission tail runs a synchronous replan; if it
                # raises, the submitter must get the error, not a 30 s
                # timeout misdiagnosing a live daemon as down.
                log.error("job admission failed", job=msg.job_id,
                          err=repr(e))
                reply = JobStatusMsg(self.node.my_id, epoch=self.epoch,
                                     error=f"admission failed: {e!r}")
        try:
            self.node.add_node(msg.src_id)
            self.node.transport.send(msg.src_id, reply)
        except (OSError, KeyError) as e:
            log.error("job submit reply undeliverable", dest=msg.src_id,
                      err=repr(e))

    def handle_job_status(self, msg: JobStatusMsg) -> None:
        """Answer a ``-jobs`` query with the full admitted-job table; a
        non-query (someone's reply echoed here) is ignored."""
        if not msg.query:
            return
        try:
            self.node.add_node(msg.src_id)
            self.node.transport.send(
                msg.src_id,
                JobStatusMsg(self.node.my_id, jobs=self.jobs.table(),
                             epoch=self.epoch))
        except (OSError, KeyError) as e:
            log.error("job status reply undeliverable", dest=msg.src_id,
                      err=repr(e))

    # ------------------------------------------- zero-downtime swap driver

    # Commit-fence watchdog knobs (class attrs: tests tune them): how
    # often to re-send the fence to unconfirmed nodes, and how many
    # rounds before going quiet (the node-side query path can still
    # re-request later).
    SWAP_RESEND_S = 2.0
    SWAP_RESENDS = 10

    def _register_swap(self, job: Job) -> None:
        """Track a ``kind="swap"`` job as a live rollout and announce
        the version + blob mapping to every serving dest (the PREPARE
        notice: staging overlaps the rollout, docs/swap.md)."""
        with self._lock:
            prior = self._swaps.get(job.version)
            if prior is not None:
                if prior["job_id"] == job.job_id:
                    return  # idempotent re-submit of the same job
                if prior["state"] != "aborted":
                    # A live (rolling/committed) version name belongs to
                    # its job; a second job may not hijack its fence.
                    # LOUD, never silent: the new job still delivers as
                    # a plain rollout, but no flip will fire for it.
                    log.error("swap version already owned by another "
                              "job; refusing to re-register (pick a new "
                              "version name)", version=job.version,
                              owner=prior["job_id"], job=job.job_id)
                    return
                # Retrying an ABORTED rollout under the same version is
                # the mainline operator path: replace the dead record.
                log.warn("re-registering previously aborted swap "
                         "version for a retry job", version=job.version,
                         prior_job=prior["job_id"], job=job.job_id)
            self._swaps[job.version] = {
                "version": job.version,
                "job_id": job.job_id,
                "swap_base": job.swap_base,
                "dests": sorted(job.assignment),
                "state": "rolling",
                "confirmed": set(),
                # Rollout-pipeline wave bookkeeping (docs/rollout.md):
                # a HELD swap completes its rollout but does not
                # auto-commit — the pipeline releases the flip when the
                # previous wave's soak verdict passes.
                "hold": job.version in self._swap_holds,
                "staged": False,
                "rollout": self._swap_holds.get(job.version, ""),
                "revert": False,
            }
            self._swaps_by_job[job.job_id] = job.version
        trace.count("swap.registered")
        log.info("live swap registered; v2 disseminating while v1 "
                 "serves", version=job.version, job=job.job_id,
                 swap_base=job.swap_base, dests=sorted(job.assignment))
        self._replicate_swap(job.version)
        self._swap_send_round(job.version, prepare=True)

    def _swap_record_locked(self, version: str) -> dict:
        rec = self._swaps[version]
        out = {"Version": rec["version"], "JobID": rec["job_id"],
               "SwapBase": rec["swap_base"], "Dests": list(rec["dests"]),
               "State": rec["state"],
               "Confirmed": sorted(rec["confirmed"])}
        # Rollout-pipeline fields ride only when set (plain swap
        # records keep their pre-rollout shape).
        if rec.get("hold"):
            out["Hold"] = True
        if rec.get("staged"):
            out["Staged"] = True
        if rec.get("rollout"):
            out["Rollout"] = rec["rollout"]
        if rec.get("revert"):
            out["Revert"] = True
        return out

    def _replicate_swap(self, version: str) -> None:
        with self._lock:
            if version not in self._swaps:
                return
            data = self._swap_record_locked(version)
        self._replicate("swap", **data)

    def _swap_send_round(self, version: str, prepare: bool = False,
                         only: Optional[Set[NodeID]] = None,
                         finalize: bool = False) -> None:
        """One fence round: the operative message (prepare / commit /
        abort, per the record's state) to each dest — unconfirmed ones
        only, unless ``only`` narrows it further.  ``finalize`` sends
        the advisory release-the-retained-tree notice to EVERY dest
        (docs/rollout.md) regardless of confirm state."""
        with self._lock:
            rec = self._swaps.get(version)
            if rec is None:
                return
            state = rec["state"]
            revert = bool(rec.get("revert"))
            targets = [d for d in rec["dests"]
                       if (finalize or d not in rec["confirmed"])
                       and (only is None or d in only)
                       and d != self.node.my_id]
            swap_base = rec["swap_base"]
        for dest in targets:
            if finalize:
                msg = SwapCommitMsg(self.node.my_id, version,
                                    finalize=True, epoch=self.epoch)
            else:
                msg = SwapCommitMsg(self.node.my_id, version,
                                    swap_base=swap_base,
                                    abort=(state == "aborted"),
                                    revert=(state == "aborted"
                                            and revert),
                                    prepare=prepare
                                    and state == "rolling",
                                    epoch=self.epoch)
            try:
                self.node.add_node(dest)
                self.node.transport.send(dest, msg)
            except (OSError, KeyError) as e:
                log.warn("swap fence send failed", dest=dest,
                         version=version, err=repr(e))

    def _on_swap_job_done(self, job_id: str) -> None:
        """A swap job finished rolling: clean completion commits the
        fence; any dropped pair (dest crashed, pair cancelled) aborts —
        v1 keeps serving everywhere.  A HELD swap (a rollout wave,
        docs/rollout.md) marks STAGED instead of committing — the
        pipeline releases the flip."""
        with self._lock:
            version = self._swaps_by_job.get(job_id)
            rec = self._swaps.get(version) if version else None
            if rec is None or rec["state"] != "rolling":
                return
        job = self.jobs.get(job_id)
        if job is None or job.dropped_pairs > 0 or job.cancelled:
            self._abort_swap(version, "rollout degraded: "
                             f"{job.dropped_pairs if job else '?'} pairs "
                             "dropped")
            return
        with self._lock:
            rec = self._swaps.get(version)
            hold = rec is not None and rec.get("hold")
            if hold:
                rec["staged"] = True
        if hold:
            trace.count("swap.staged_held")
            log.info("held swap staged on every replica; awaiting the "
                     "pipeline's release", version=version)
            self._replicate_swap(version)
            self.rollouts.on_wave_staged(version)
            return
        self._commit_swap(version)

    def _commit_swap(self, version: str) -> None:
        with self._lock:
            rec = self._swaps.get(version)
            if rec is None or rec["state"] != "rolling":
                return
            rec["state"] = "committed"
        trace.count("swap.committed")
        log.info("swap rollout verified on every replica; issuing the "
                 "commit fence", version=version, epoch=self.epoch)
        self._replicate_swap(version)
        self._swap_send_round(version)
        threading.Thread(target=self._swap_watchdog, args=(version,),
                         daemon=True,
                         name=f"swap-fence-{version}").start()

    def _abort_swap(self, version: str, reason: str,
                    revert: bool = False) -> None:
        """Rollback = never flip: cancel the job (remaining pairs drop
        VISIBLY), tell every dest to release its staged v2, keep v1
        serving.  With ``revert`` (the rollout SLO guard's rollback,
        docs/rollout.md) an already-COMMITTED swap is allowed to abort:
        the fence carries ``Revert`` and each replica restores its
        retained pre-flip tree."""
        with self._lock:
            rec = self._swaps.get(version)
            if rec is None or rec["state"] in ("aborted",):
                return
            if rec["state"] == "committed" and not revert:
                log.error("abort requested for an already-committed "
                          "swap; refusing (the fleet flipped)",
                          version=version, reason=reason)
                return
            rec["state"] = "aborted"
            rec["confirmed"] = set()
            rec["revert"] = bool(revert)
            job_id = rec["job_id"]
            rollout = rec.get("rollout", "")
        trace.count("swap.aborts")
        if revert:
            trace.count("swap.reverts_issued")
        log.error("live swap ABORTED; "
                  + ("replicas reverting to the pre-flip tree"
                     if revert else "v1 keeps serving"),
                  version=version, reason=reason)
        if self.jobs.cancel(job_id):
            self._replicate("job", **self.jobs.record(job_id))
            with self._lock:
                self.assignment = self.jobs.merged_assignment(
                    self._base_assignment)
                merged = _nested_layer_map_to_json(self.assignment)
            self._replicate("assignment", Assignment=merged)
        self._replicate_swap(version)
        self._swap_send_round(version)
        self._maybe_finish()
        if rollout and not revert:
            # A wave that died OUTSIDE the guard's own rollback (dest
            # crash, staging failure): the pipeline pauses, loudly.
            self.rollouts.on_wave_aborted(version, reason)

    def _swap_watchdog(self, version: str) -> None:
        """Bounded fence re-send: a node that lost the commit gets it
        again until every dest confirmed (the node-side query path
        covers the long tail past the budget)."""
        for _ in range(self.SWAP_RESENDS):
            time.sleep(self.SWAP_RESEND_S)
            with self._lock:
                rec = self._swaps.get(version)
                if rec is None or rec["state"] != "committed":
                    return
                missing = [d for d in rec["dests"]
                           if d not in rec["confirmed"]]
                if not missing:
                    return
            if self._deposed or self._closed():
                return
            trace.count("swap.fence_resent")
            log.warn("swap fence unconfirmed; re-sending",
                     version=version, missing=missing)
            self._swap_send_round(version)
        log.error("swap fence re-send budget exhausted; remaining nodes "
                  "must query", version=version)

    def _closed(self) -> bool:
        # close() and a depose both set the lease stop — either way
        # this process must stop driving fences.
        return self._lease_stop.is_set()

    def handle_swap_commit(self, msg: SwapCommitMsg) -> None:
        """Node → leader swap traffic: flip confirmations, fence
        re-requests, and staging-failure reports.

        Gated to the swap's REGISTERED replica set: confirm/query/error
        are serving-state mutations (a forged error is a one-message
        rollout DoS; a forged confirm fakes a flip the fence watchdog
        would otherwise keep chasing), so a node outside the rollout's
        dest set is refused, loudly — the same posture as the
        DLD_JOB_TOKEN admission gate.  Honest limit: a compromised
        replica can still lie about ITSELF (inherent without
        per-message signatures; docs/swap.md)."""
        with self._lock:
            rec = self._swaps.get(msg.version)
            member = rec is not None and msg.src_id in rec["dests"]
        if (msg.applied or msg.query or msg.error) and not member:
            trace.count("swap.foreign_ctrl_dropped")
            log.warn("swap control message from a node outside the "
                     "rollout's replica set; dropped",
                     version=msg.version, node=msg.src_id,
                     applied=msg.applied, query=msg.query,
                     err=msg.error or None)
            return
        if msg.applied:
            with self._lock:
                rec = self._swaps.get(msg.version)
                if rec is None:
                    return
                rec["confirmed"].add(msg.src_id)
            self._replicate_swap(msg.version)
            self._maybe_swap_complete(msg.version)
            return
        if msg.query:
            # A staged node that never saw its fence: answer with the
            # operative state (commit/abort); a still-rolling swap has
            # nothing to say yet.
            with self._lock:
                rec = self._swaps.get(msg.version)
                state = rec["state"] if rec is not None else None
            if state in ("committed", "aborted"):
                self._swap_send_round(msg.version, only={msg.src_id})
            elif state is None:
                log.warn("fence query for an unknown swap version",
                         version=msg.version, node=msg.src_id)
            return
        if msg.error:
            log.error("replica reports unrecoverable swap staging; "
                      "aborting rollout", version=msg.version,
                      node=msg.src_id, err=msg.error)
            self._abort_swap(msg.version,
                             f"node {msg.src_id}: {msg.error}")
            return

    def _maybe_swap_complete(self, version: str) -> None:
        """Fire the fleet-flipped completion edge once every REMAINING
        fence dest has confirmed.  Called from the confirm path and
        from a dead dest's fence-set prune (``crash``): the prune can
        be what completes the set, and without this edge a plain
        swap's finalize round (or a rollout wave's soak open) would
        wait forever on a confirmation that can no longer arrive —
        survivors pinning their retained pre-flip trees the whole
        time."""
        with self._lock:
            rec = self._swaps.get(version)
            if (rec is None or rec["state"] != "committed"
                    or not set(rec["dests"]) <= rec["confirmed"]
                    or rec.get("fleet_flipped")):
                return
            rec["fleet_flipped"] = True
            held = bool(rec.get("rollout"))
        trace.count("swap.fleet_flipped")
        log.info("every replica confirmed the flip; swap complete",
                 version=version)
        if held:
            # A rollout wave: the flip edge opens its soak window
            # (docs/rollout.md).
            self.rollouts.on_wave_flipped(version)
        else:
            # A plain fleet-wide swap: everyone flipped — the rollback
            # window closes now; release the retained pre-flip trees
            # (advisory).
            self._swap_send_round(version, finalize=True)

    def swap_table(self) -> Dict[str, dict]:
        """JSON-ready swap driver state (reports, tests, -jobs)."""
        with self._lock:
            return {v: self._swap_record_locked(v)
                    for v in sorted(self._swaps)}

    # --------------------------------------------- rollout operator channel

    def handle_rollout_ctl(self, msg: RolloutCtlMsg) -> None:
        """The rollout pipeline's operator front door (docs/rollout.md):
        query the table, pause/resume a pipeline, move the traffic-split
        knob.  The MUTATING verbs (pause/resume/split) ride the
        DLD_JOB_TOKEN admission gate — a resume re-submits a
        rolled-back wave's swap job and a commit flips serving, exactly
        the mutation class the token exists for; query stays open like
        -jobs.  Every request is ANSWERED, refusals included."""
        if msg.table or msg.error:
            return  # someone's reply echoed here
        error = ""
        mutating = msg.pause or msg.resume or msg.split >= 0
        if self._deposed:
            error = "deposed: a higher-epoch leader owns the rollouts"
        elif (mutating and self._job_token
                and not hmac.compare_digest(msg.auth.encode(),
                                            self._job_token.encode())):
            trace.count("jobs.unauthorized")
            log.warn("unauthorized rollout control verb rejected",
                     rollout=msg.rollout_id, submitter=msg.src_id,
                     pause=msg.pause, resume=msg.resume)
            error = ("unauthorized: this leader requires a job token "
                     "(DLD_JOB_TOKEN) for pause/resume/split")
        elif msg.pause:
            error = self.rollouts.pause(msg.rollout_id)
        elif msg.resume:
            error = self.rollouts.resume(msg.rollout_id)
        elif msg.split >= 0:
            error = self.rollouts.set_split(msg.rollout_id, msg.split)
        elif not msg.query:
            error = "no verb: set Query, Pause, Resume, or Split"
        try:
            self.node.add_node(msg.src_id)
            self.node.transport.send(
                msg.src_id,
                RolloutCtlMsg(self.node.my_id,
                              rollout_id=msg.rollout_id,
                              table=self.rollouts.table(),
                              error=error, epoch=self.epoch))
        except (OSError, KeyError, ConnectionError) as e:
            log.error("rollout ctl reply undeliverable",
                      dest=msg.src_id, err=repr(e))

    def handle_policy_ctl(self, msg: PolicyCtlMsg) -> None:
        """The autonomy engine's operator front door (docs/autonomy.md):
        query the policy table, enable/disable automatic actioning.
        The MUTATING verbs (enable/disable) ride the DLD_JOB_TOKEN
        admission gate — flipping a fleet between self-driving and
        manual is exactly the mutation class the token exists for;
        query stays open like -jobs.  Every request is ANSWERED,
        refusals included."""
        if msg.table or msg.error:
            return  # someone's reply echoed here
        error = ""
        mutating = msg.enable or msg.disable
        if self._deposed:
            error = "deposed: a higher-epoch leader owns the policies"
        elif (mutating and self._job_token
                and not hmac.compare_digest(msg.auth.encode(),
                                            self._job_token.encode())):
            trace.count("jobs.unauthorized")
            log.warn("unauthorized policy control verb rejected",
                     submitter=msg.src_id, enable=msg.enable,
                     disable=msg.disable)
            error = ("unauthorized: this leader requires a job token "
                     "(DLD_JOB_TOKEN) for enable/disable")
        elif msg.enable and msg.disable:
            error = "conflicting verbs: Enable and Disable both set"
        elif msg.enable:
            self.policy.set_enabled(True)
        elif msg.disable:
            self.policy.set_enabled(False)
        elif not msg.query:
            error = "no verb: set Query, Enable, or Disable"
        try:
            self.node.add_node(msg.src_id)
            self.node.transport.send(
                msg.src_id,
                PolicyCtlMsg(self.node.my_id,
                             table=self.policy.table(),
                             error=error, epoch=self.epoch))
        except (OSError, KeyError, ConnectionError) as e:
            log.error("policy ctl reply undeliverable",
                      dest=msg.src_id, err=repr(e))

    def serve_quarantined(self) -> Set[NodeID]:
        """The policy engine's serve-rotation mask (docs/autonomy.md):
        replicas the A/B split and rollout soak baselining route
        around.  Empty on an unarmed fleet."""
        return self.policy.quarantined()

    def policy_grow(self, model_node: NodeID, action_id: str) -> str:
        """Autonomy actuator (docs/autonomy.md): grow the replica set
        of ``model_node`` — copy its held layer set onto a placeable
        spare via a join+refill job through ``submit_job`` (the same
        chokepoint the join path uses), origin avoided.  Returns the
        job id, or "" when no spare exists / nothing to copy (the
        engine audits the skip)."""
        with self._lock:
            metas = dict(self.status.get(model_node) or {})
            busy = set(self.assignment) | {self.node.my_id}
        if not metas:
            return ""
        spares = self.membership.spares(busy | {model_node})
        if not spares:
            log.warn("policy grow: no placeable spare",
                     model=model_node)
            return ""
        spare = spares[0]
        target = {spare: {int(lid): LayerMeta(
            version=getattr(meta, "version", ""))
            for lid, meta in metas.items()}}
        jid = f"policy-{action_id}"
        self.submit_job(jid, target, kind="join",
                        avoid={self.node.my_id} - {model_node},
                        submitter="policy")
        log.warn("policy grow submitted", job=jid, model=model_node,
                 spare=spare, layers=len(metas))
        return jid

    def policy_rehome(self, node: NodeID, action_id: str) -> str:
        """Autonomy actuator (docs/autonomy.md): proactively re-home a
        death-suspect node's UNIQUE holdings before the failure
        detector's crash path fires — the drain plane's re-home
        derivation reused as a NON-destructive repair job (the suspect
        stays a member; if it was merely slow, the run just gained
        redundant copies).  Returns the job id, or "" when nothing is
        uniquely at risk."""
        with self._lock:
            target: Assignment = {}
            for lid, shard, codec in self._unique_holdings_locked(node):
                dest = self._rehome_dest_locked(node, lid, shard, codec)
                if dest is None:
                    log.warn("policy rehome: no placeable dest",
                             node=node, layer=lid)
                    continue
                target.setdefault(dest, {})[lid] = LayerMeta(
                    shard=shard, codec=codec)
                if codec:
                    # Same pinning as _drain_rehome: the re-home ships
                    # the qualified form the suspect holds.
                    self._codec_choice[(dest, lid)] = codec
                    self._codec_seen = True
        if not target:
            return ""
        jid = f"policy-{action_id}"
        # The suspect is NOT avoided as a source: it may be the only
        # holder — if it is truly dead its sends stall and the crash
        # path's salvage takes over; if it is slow, slow beats never.
        self.submit_job(jid, target, kind="repair", submitter="policy")
        log.warn("policy rehome submitted", job=jid, node=node,
                 layers=sorted(l for r in target.values() for l in r))
        return jid

    # ------------------------------------------------ elastic membership

    def _replicate_membership(self) -> None:
        """Replicate the full roster + the in-flight drain-job map (the
        delta REPLACES, like the hierarchy's group table — a revoked
        membership is exactly an absent row)."""
        with self._lock:
            drains = {jid: int(n) for jid, n in self._drain_jobs.items()}
        self._replicate("membership", Members=self.membership.to_json(),
                        DrainJobs=drains)

    def _install_member_addr(self, node: NodeID, addr: str) -> None:
        """Make an unconfigured seat dialable: joiners exist in nobody's
        config, so their wire address rides the membership plane."""
        if not addr:
            return
        try:
            self.node.transport.addr_registry[node] = addr
        except (AttributeError, TypeError):
            pass
        self.node.add_node(node)

    def _verify_member_source(self, node: NodeID, digests: dict) -> bool:
        """Whether this announcer's holdings may be TRUSTED as transfer
        sources (docs/membership.md).  Configured members are verified
        by the config; an unknown legacy announcer is seeded ACTIVE
        (pre-membership interop).  A JOINING seat verifies when every
        announced digest agrees with the leader's existing stamp for
        that layer — a mismatch keeps it a dest-only seat, loudly.
        Layers without a stamp (and joins with digests disabled) can't
        be cross-checked and verify trivially — an honest limit the
        docs own."""
        st = self.membership.state_of(node)
        if st is None:
            # A seat the roster never met: the pre-membership announce
            # path admitted such peers silently — keep doing so.
            self.membership.seed([node], epoch=self.epoch)
            return True
        if st != mship.JOINING:
            return True
        with self._lock:
            for lid, d in (digests or {}).items():
                stamped = self.layer_digests.get(lid)
                if (stamped is not None and stamped != d
                        and integrity.stamp_algo(stamped)
                        == integrity.stamp_algo(d)):
                    trace.count("membership.join_verify_failed")
                    log.error("joiner's announced digest conflicts with "
                              "the stamped one; holdings stay "
                              "quarantined (dest-only seat)",
                              node=node, layerID=lid, announced=d,
                              stamped=stamped)
                    return False
        if self.membership.verify_source(node):
            trace.count("membership.source_verified")
            log.info("joiner's holdings digest-verified; admitted as a "
                     "source", node=node)
            self._replicate_membership()
        return True

    def _layer_universe_locked(self) -> List[LayerID]:
        """The default join target: every layer the current goal names
        plus the leader's own store (a pre-start pure-seeder goal may
        be empty).  Lock held."""
        out = {int(l) for lids in self.assignment.values() for l in lids}
        out |= {int(l) for l in self.layers}
        return sorted(out)

    def _place_joiner(self, node: NodeID) -> NodeID:
        """The joiner's control parent: the leader itself in flat mode.
        The hierarchical leader overrides — grouped clusters absorb
        joiners into the least-loaded live group and the parent is that
        group's sub-leader (docs/membership.md)."""
        return self.node.my_id

    def handle_join(self, msg: JoinMsg) -> None:
        """Admission (docs/membership.md): an unconfigured node asked
        to join the running cluster.  It becomes a delivery DEST — a
        ``kind="join"`` refill job over the layer universe it wants
        (default: everything the current goal disseminates), submitted
        on its FIRST announce so cold-boot holdings (inventory,
        checkpointed partials, content-equal bytes under other ids)
        reduce the demand before anything ships, planned with every
        other demand and refilled from current peer holders (the origin
        seeder is avoided whenever peers can serve, so admission cost
        stops scaling with origin bandwidth) — and a SOURCE only once
        its announced holdings digest-verify.  Idempotent per (seat,
        generation): a retried request re-answers the same admit."""
        if msg.admitted:
            return  # admit/roster notices are receiver business
        if self._deposed:
            return  # the higher-epoch leader owns admission now
        node = msg.src_id
        if node == self.node.my_id:
            return
        rejoin = self.membership.is_left(node)
        rec = self.membership.admit(node, addr=msg.addr, epoch=self.epoch)
        self._install_member_addr(node, msg.addr)
        trace.count("membership.joins")
        log.info("membership: join request", node=node,
                 addr=msg.addr or None, rejoin=rejoin,
                 generation=rec.generation,
                 want=sorted(int(l) for l in msg.want) or "universe")
        want = sorted(int(l) for l in msg.want)
        # Mode 3 models NICs: an unconfigured joiner starts at the most
        # conservative configured rate (the solver never over-promises
        # an unknown link) — PINNED only until its announce carries its
        # own rate, which supersedes (docs/membership.md).
        bw_map = getattr(self, "node_network_bw", None)
        if bw_map is not None and node not in bw_map:
            known = [b for b in bw_map.values() if b > 0]
            bw_map[node] = min(known) if known else 0
            self._joiner_bw_pinned.add(node)
        parent = self._place_joiner(node)
        if parent == self.node.my_id:
            # The root monitors ungrouped joiners directly; a grouped
            # joiner's liveness belongs to its sub-leader's detector.
            self.detector.revive(node)
        self._replicate_membership()
        # Roster notices go out BEFORE any plan can command a send to
        # the joiner — a sender must be able to dial it — and the
        # joiner gets the existing fleet's addresses in return (it is
        # in nobody's config and has nobody's): NACK retransmits to a
        # peer source, failover leases, and peer pulls all need to
        # dial.
        self._broadcast_roster(node, msg.addr)
        self._introduce_peers(node)
        with self._lock:
            announced = node in self.status
            if not announced:
                self._join_pending[node] = want
        if announced:
            # A live re-join (the seat never left): refill immediately
            # against its current status row.
            self._admit_join_job(node, want)
        parent_addr = ""
        if parent != self.node.my_id:
            try:
                parent_addr = str(
                    self.node.transport.addr_registry.get(parent, ""))
            except AttributeError:
                parent_addr = ""
        try:
            self.node.transport.send(
                node, JoinMsg(self.node.my_id, node=node, admitted=True,
                              parent=parent, parent_addr=parent_addr,
                              epoch=self.epoch))
        except (OSError, KeyError, ConnectionError) as e:
            log.warn("join admit reply undeliverable (the joiner "
                     "retries)", node=node, err=repr(e))

    def _admit_join_job(self, node: NodeID, want: List[LayerID]) -> None:
        """Submit the joiner's refill job ([] want = the current layer
        universe).  Grouped joiners stay off this root's detector."""
        with self._lock:
            lids = [int(l) for l in want] or self._layer_universe_locked()
        if not lids:
            return
        gen = self.membership.generation_of(node)
        jid = f"join-{node}-g{gen}"
        self.submit_job(jid, {node: {int(lid): LayerMeta()
                                     for lid in lids}}, kind="join")
        if self._place_joiner(node) != self.node.my_id:
            # submit_job seeds a root-side lease for unknown dests; a
            # grouped joiner heartbeats its SUB-LEADER, never this root
            # — drop it or it expires into a false crash.
            self.detector.forget(node)
        job = self.jobs.get(jid)
        with self._lock:
            finished = self._startup_sent
        if job is not None and job.state == "done" and finished:
            # The refill resolved AT ADMISSION (the joiner's cold-boot
            # holdings already cover its want) against an already-met
            # goal: _maybe_finish will never re-fire, so the joiner's
            # StartupMsg — its ready() release — must go out directly.
            try:
                self.node.transport.send(
                    node, StartupMsg(self.node.my_id,
                                     boot=self.boot_enabled,
                                     serve=self._serve_promised,
                                     epoch=self.epoch))
            except (OSError, KeyError, ConnectionError) as e:
                log.warn("startup to resolved-at-admit joiner "
                         "undeliverable", node=node, err=repr(e))

    def _broadcast_roster(self, node: NodeID, addr: str) -> None:
        """Tell every live member the joiner's address — a later plan
        may command ANY of them to send to it.  Best-effort: a seat
        that missed the notice fails its send loudly and the re-plan
        re-routes."""
        if not addr:
            return
        with self._lock:
            peers = sorted(set(self.status) | set(self.standbys))
        out = JoinMsg(self.node.my_id, node=node, addr=addr,
                      admitted=True, epoch=self.epoch)
        for p in peers:
            if p in (node, self.node.my_id):
                continue
            try:
                self.node.transport.send(p, out)
            except (OSError, KeyError, ConnectionError) as e:
                log.debug("roster notice send failed", dest=p,
                          err=repr(e))

    def _introduce_peers(self, node: NodeID) -> None:
        """Roster notices TO the joiner: every (peer, addr) this leader
        can dial, so the joiner can answer any seat that later serves
        or commands it.  Best-effort, like the outbound roster."""
        try:
            entries = dict(self.node.transport.addr_registry)
        except (AttributeError, TypeError):
            return
        for peer, addr in sorted((int(p), str(a))
                                 for p, a in entries.items()
                                 if isinstance(p, int) and int(p) >= 0):
            if peer == node or not addr:
                continue
            try:
                self.node.transport.send(
                    node, JoinMsg(self.node.my_id, node=peer, addr=addr,
                                  admitted=True, epoch=self.epoch))
            except (OSError, KeyError, ConnectionError) as e:
                log.debug("peer introduction send failed", dest=node,
                          peer=peer, err=repr(e))
                return

    def handle_drain(self, msg: DrainMsg) -> None:
        """Planned departure (docs/membership.md): re-home the
        drainer's unique holdings onto survivors BEFORE it leaves —
        zero lost pairs, never post-crash salvage — then prune it from
        the detector, lease recipients, and announce gating atomically
        with the membership delta, and answer every requester."""
        if msg.done:
            return  # done notices are the drainer's business
        if self._deposed:
            return
        node = msg.node if msg.node >= 0 else msg.src_id
        requester = msg.src_id
        if node == self.node.my_id:
            self._answer_drain(requester, node,
                               error="cannot drain the leader seat")
            return
        st = self.membership.state_of(node)
        if st == mship.LEFT:
            self._answer_drain(requester, node)  # idempotent: it's out
            return
        if st is None:
            self._answer_drain(requester, node,
                               error=f"unknown member {node}")
            return
        with self._lock:
            self._drain_waiters.setdefault(node, set()).add(requester)
        if not self.membership.start_drain(node):
            if self.membership.is_left(node):
                # Lost the race with a concurrent finalize: it already
                # answered ITS waiter set — answer this straggler now
                # instead of leaking an orphaned waiter entry.
                with self._lock:
                    waiters = self._drain_waiters.pop(node, set())
                for w in sorted(waiters):
                    self._answer_drain(w, node)
            return  # already draining: the finalize answers every waiter
        trace.count("membership.drains")
        log.warn("membership: draining node (unique holdings re-home "
                 "before it leaves)", node=node, requested_by=requester)
        self._replicate_membership()
        self._drain_rehome(node)

    def _unique_holdings_locked(
            self, node: NodeID) -> List[Tuple[LayerID, str, str]]:
        """``(layer, shard, codec)`` holdings whose only live copy
        CAPABLE of satisfying the same demands is the drainer's —
        losing the seat without re-homing them would lose the pair.
        A full canonical holding is unique when no survivor holds the
        full raw layer; a QUALIFIED holding (shard slice, encoded form
        — docs/sharding.md, docs/codec.md) is unique when no survivor
        holds a covering shard in an accepting codec, and re-homes
        shard/codec-QUALIFIED (the drainer re-seeds its own form
        verbatim), never inflated to a whole raw layer it may not even
        be able to produce.  Lock held."""
        row = self.status.get(node) or {}
        unique: List[Tuple[LayerID, str, str]] = []
        for lid, meta in sorted(row.items()):
            if not delivered(meta):
                continue
            shard = meta.shard
            codec = getattr(meta, "codec", "")
            held_elsewhere = False
            for n, other in self.status.items():
                if (n == node or self.membership.is_left(n)
                        or self.membership.is_draining(n)):
                    continue
                m = other.get(lid)
                if (m is not None and delivered(m)
                        and shard_covers(m.shard, shard)
                        and codec_accepts(getattr(m, "codec", ""),
                                          codec)):
                    held_elsewhere = True
                    break
            if not held_elsewhere:
                unique.append((lid, shard, codec))
        return unique

    def _rehome_dest_locked(self, node: NodeID, lid: LayerID,
                            shard: str = "",
                            codec: str = "") -> Optional[NodeID]:
        """The survivor a draining node's unique holding re-homes onto:
        the lowest-id placeable announced seat without a satisfying
        copy (non-leader seats first — the leader is the fallback, not
        the default dumping ground).  Lock held."""
        placeable = self.membership.placeable()
        candidates = [n for n in sorted(self.status)
                      if n != node and n in placeable
                      and n != self.node.my_id]
        candidates.append(self.node.my_id)
        for n in candidates:
            if n == node or n not in placeable:
                continue
            if codec and codec_capability(codec) not in \
                    self.node_codecs.get(n, ()):
                # A codec-qualified re-home pins the wire codec onto
                # the dest (_drain_rehome), bypassing the negotiation's
                # advertised-decode check — so enforce it here: never
                # ship encoded bytes to a seat that can't decode them.
                continue
            base_d = delta_base_digest(codec)
            if base_d and not (
                    self.content.node_has(n, base_d)
                    or (n == self.node.my_id
                        and base_d in self._delta_base_src)):
                # A delta re-home additionally needs the BASE bytes at
                # the new seat — capability alone can't reconstruct.
                continue
            meta = self.status.get(n, {}).get(lid)
            if (meta is not None and delivered(meta)
                    and shard_covers(meta.shard, shard)
                    and codec_accepts(getattr(meta, "codec", ""),
                                      codec)):
                continue
            return n
        return None

    def _drain_rehome(self, node: NodeID) -> None:
        """Plan (or finish) one drain: submit the re-home job for the
        drainer's unique holdings — full canonical AND shard/codec-
        qualified (the PR 12 follow-up closed) — or finalize
        immediately when nothing unique remains.  Also the takeover
        re-drive (docs/membership.md: a promoted leader resumes adopted
        drains in the bumped epoch)."""
        with self._lock:
            target: Assignment = {}
            qualified = 0
            for lid, shard, codec in self._unique_holdings_locked(node):
                dest = self._rehome_dest_locked(node, lid, shard, codec)
                if dest is None:
                    log.error("no survivor can take a draining node's "
                              "unique holding; its bytes leave with it",
                              node=node, layerID=lid,
                              shard=shard or None, codec=codec or None)
                    continue
                target.setdefault(dest, {})[lid] = LayerMeta(
                    shard=shard, codec=codec)
                if shard or codec:
                    qualified += 1
                    if codec:
                        # Pin the codec CHOICE so the stamp/accounting
                        # machinery treats the re-home exactly like a
                        # negotiated encoded pair (the dest accounts,
                        # journals, and acks in encoded byte space).
                        self._codec_choice[(dest, lid)] = codec
                        self._codec_seen = True
            n_prior = sum(1 for n in self._drain_jobs.values()
                          if n == node)
        if qualified:
            trace.count("membership.qualified_rehomed", qualified)
        if not target:
            self._finalize_drain(node)
            return
        gen = self.membership.generation_of(node)
        jid = (f"drain-{node}-g{gen}" if n_prior == 0
               else f"drain-{node}-g{gen}.{n_prior}")
        with self._lock:
            self._drain_jobs[jid] = node
        log.info("drain re-home job submitted", node=node, job=jid,
                 layers=sorted({int(l) for r in target.values()
                                for l in r}),
                 dests=sorted(target))
        self.submit_job(jid, target, kind="drain")
        job = self.jobs.get(jid)
        if job is not None and job.state == "done":
            # Admission found every re-home already satisfied (or the
            # survivors' acks landed synchronously): finish now.
            self._on_drain_job_done(jid)

    def _on_drain_job_done(self, jid: str) -> None:
        """A completed ``kind="drain"`` re-home job releases its
        drainer (no-op for every other job)."""
        with self._lock:
            node = self._drain_jobs.pop(jid, None)
        if node is not None:
            self._finalize_drain(node)

    def _forget_sender_jobs(self, node: NodeID) -> None:
        """Hook: a departed seat's dispatched sends are forgotten —
        never range-salvaged (mode 3 overrides; the base scheduler
        tracks none)."""

    def _finalize_drain(self, node: NodeID) -> None:
        """The atomic prune: DRAINING → LEFT together with removal from
        status, the goal, the failure detector, lease recipients, and
        announce gating — after this, nothing the departed seat does
        (or fails to do) can fire ``crash()`` or the salvage path."""
        if not self.membership.complete_drain(node):
            return
        # Ban BEFORE the state prune: a straggler heartbeat landing
        # between the two must not re-arm a lease that later expires
        # into a false crash.
        self.detector.remove(node)
        with self._lock:
            self.status.pop(node, None)
            dropped = self.assignment.pop(node, None)
            if self._base_assignment is not self.assignment:
                self._base_assignment.pop(node, None)
            self.expected_nodes.discard(node)
            self.partial_status.pop(node, None)
            # A clean leave is not a crash: nothing is parked for a
            # revival resume, and none of its pairs enter range salvage.
            self._dropped_assignment.pop(node, None)
            self._salvaging = {p for p in self._salvaging
                               if p[1] != node}
            waiters = self._drain_waiters.pop(node, set())
        self._forget_sender_jobs(node)
        self.content.drop_node(node)
        trace.count("membership.drained")
        log.warn("membership: node drained and released", node=node,
                 had_assignment=bool(dropped))
        self._replicate_membership()
        self._replicate("member_left", Node=node)
        affected, finished = self.jobs.drop_dest(node)
        for jid in affected:
            self._replicate("job", **self.jobs.record(jid))
        self._jobs_completed(finished)
        for w in sorted(waiters | {node}):
            if w == self.node.my_id:
                continue
            try:
                self.node.add_node(w)
                self.node.transport.send(
                    w, DrainMsg(self.node.my_id, node=node, done=True,
                                epoch=self.epoch))
            except (OSError, KeyError, ConnectionError) as e:
                log.debug("drain done notice undeliverable", dest=w,
                          err=repr(e))
        # A cleanly-departed pod member breaks its pod the same way a
        # crashed one does (docs/fabric.md): survivors' unfinished pod
        # pairs degrade to host-path delivery.
        self._pods_member_gone(node)
        self._drive(self._recover)
        self._maybe_finish()
        self._maybe_complete_boot_wait()

    def _answer_drain(self, requester: NodeID, node: NodeID,
                      error: str = "") -> None:
        """Every drain request is ANSWERED (the serving invariant),
        refusals included."""
        if requester == self.node.my_id:
            return
        try:
            self.node.add_node(requester)
            self.node.transport.send(
                requester, DrainMsg(self.node.my_id, node=node,
                                    done=not error, error=error,
                                    epoch=self.epoch))
        except (OSError, KeyError, ConnectionError) as e:
            log.debug("drain answer undeliverable", dest=requester,
                      err=repr(e))

    def _resume_joins(self) -> None:
        """Takeover re-drive: an adopted JOINING member whose refill
        job never replicated (the admit raced the old leader's death)
        gets a fresh pending entry — its next announce (triggered by
        the takeover lease) submits the job at the bumped epoch."""
        active = set(self.jobs.table())
        for node in self.membership.joining():
            gen = self.membership.generation_of(node)
            if f"join-{node}-g{gen}" in active:
                continue
            with self._lock:
                self._join_pending.setdefault(node, [])
            log.info("adopted joiner without a replicated refill job; "
                     "re-admitting on its next announce", node=node)

    def _resume_drains(self) -> None:
        """Takeover re-drive: every adopted DRAINING member either has
        its re-home job resumed by the job plane (active record), or is
        re-planned/finalized afresh at the bumped epoch."""
        for node in self.membership.draining():
            with self._lock:
                jids = [j for j, n in self._drain_jobs.items()
                        if n == node]
            if any((job := self.jobs.get(j)) is not None
                   and job.state == "active" for j in jids):
                log.info("adopted drain still re-homing; the resumed "
                         "job plane carries it", node=node)
                continue
            # The re-home record finished (or was lost with the dead
            # leader): recompute and finish — or re-plan — now.
            with self._lock:
                for j in jids:
                    self._drain_jobs.pop(j, None)
            self._drain_rehome(node)

    def _content_skip_locked(self, dest: NodeID, layer_id: LayerID) -> bool:
        """Lock held.  True when shipping (dest, layer) would be wasted
        wire bytes: a job claims the pair AND the content index shows
        the dest already holds content-equal bytes under another layer
        id — the dest's own digest-stamp resolve acks it locally
        (docs/service.md).  Gated on job ownership so pre-service peers
        (which lack the resolve path) are never starved.  Sharded
        targets never content-skip: their resolve key is the (digest,
        range) pair, and full-layer vouching doesn't carry it
        (docs/sharding.md, honest limits)."""
        want = (self.assignment.get(dest) or {}).get(layer_id)
        if want is not None and (want.shard or want.codec):
            # Sharded and codec targets resolve by (digest, range/codec)
            # keys that full-layer raw vouching doesn't carry
            # (docs/sharding.md, docs/codec.md — honest limits).
            return False
        if self.jobs.owner_of(dest, layer_id) is None:
            return False
        digest = self.layer_digests.get(layer_id)
        if not digest or not self.content.node_has(dest, digest):
            return False
        # Count + log once per PAIR, not per replan consultation — the
        # counter is "content-equal pairs never shipped", and replans
        # re-consult every pair.
        if (layer_id, dest) not in self._content_skip_seen:
            self._content_skip_seen.add((layer_id, dest))
            trace.count("store.leader_skipped")
            log.info("content store: dest holds content-equal bytes; "
                     "skipping the wire ship", layerID=layer_id,
                     dest=dest, digest=digest)
        return True

    def _drive(self, replan) -> None:
        """The shared goal-chasing tail of crash()/update(): start if the
        change unblocked the start, finish if the goal is already met,
        otherwise run the supplied re-planner."""
        with self._lock:
            started = self._started
        if not started:
            if self._maybe_start():
                self.send_layers()
                self._maybe_finish()
            return
        self._maybe_finish()
        with self._lock:
            finished = self._startup_sent
        if not finished:
            replan()

    def send_layers(self) -> None:
        """Leader sends every missing assigned layer itself
        (node.go:326-352) — over the device fabric when one is wired.
        A sharded target (docs/sharding.md) ships as exactly its shard's
        byte range over the host path (the fabric plane speaks whole
        layers only); a wire-codec target (docs/codec.md) ships its
        ENCODED form over the host path the same way."""
        self._stamp_targets()
        for node_id, layer_ids in self.assignment.items():
            for layer_id, want in layer_ids.items():
                with self._lock:
                    meta = self.status.get(node_id, {}).get(layer_id)
                    skip = (meta is None
                            and self._content_skip_locked(node_id,
                                                          layer_id))
                if satisfies(meta, want) or skip:
                    continue
                layer = self.layers.get(layer_id)
                if layer is None:
                    log.warn("no layers found", layerID=layer_id)
                    continue
                if (not want.shard and not want.codec
                        and self._try_fabric_full_layer(
                            layer_id, self.node.my_id, node_id)):
                    continue
                owner = self.jobs.owner_of(node_id, layer_id)
                self.loop.submit(self._send_one, node_id, layer_id, layer,
                                 owner[1] if owner else "", want.shard,
                                 want.codec)

    def _send_one(self, dest: NodeID, layer_id: LayerID, layer,
                  job_id: str = "", shard: str = "",
                  codec: str = "") -> None:
        try:
            send_layer(self.node, dest, layer_id, layer, job_id=job_id,
                       shard=shard, codec=codec, codecs=self.codecs)
        except Exception as e:  # noqa: BLE001
            log.error("couldn't send a layer", layerID=layer_id, err=repr(e))

    # --------------------------------------------------- device-fabric plane

    def handle_device_plan(self, msg: DevicePlanMsg) -> None:
        """The leader can be a seeder in a device plan (it dispatches the
        plan to itself like any other participant); it is never a fabric
        *dest* — ``_fabric_ok`` routes those to the host path."""
        if self.fabric is None or self.placement is None:
            log.error("device plan but no fabric wired", plan=msg.plan_id)
            return
        if self._spmd:
            # Multi-controller lockstep: the leader's process enters every
            # collective too (seeder or not).
            try:
                self.fabric.submit(msg)
            except Exception as e:  # noqa: BLE001
                log.error("spmd fabric submit failed", plan=msg.plan_id,
                          err=repr(e))
            return
        contribute_device_plan(self.node, self.layers, self._lock,
                               self.fabric, self.placement, msg)

    def _fabric_ok(
        self, layer_id: LayerID, layout: List[Tuple[NodeID, int, int]],
        dest: NodeID, total: int,
    ) -> bool:
        """Whether one scheduled transfer can ride the device fabric:
        fabric + placement wired, every participant mapped to a stage, and
        no sender serving the layer from an external client (a client's
        bytes live outside the fabric — host path).  Status rows are read
        under ``_lock``: pool-concurrent handlers insert rows
        (``handle_ack``) and pop them (``crash``), and a torn ``LayerMeta``
        view here could route a CLIENT-held layer onto the fabric."""
        if self.fabric is None or self.placement is None:
            return False
        if self._fabric_disabled:
            # SPMD lockstep needs every process alive; after a declared
            # crash the remaining transfers ride the host path.
            return False
        if self._spmd:
            # The SPMD collective reassembles the WHOLE layer from the
            # plan alone — it has no dest-side coverage seeding, so a
            # resumed dest's gaps-only layout (mode-3 checkpoint resume)
            # must ride the host path, not livelock the fabric.
            pos = 0
            for _, off, size in sorted(layout, key=lambda t: t[1]):
                if off != pos:
                    return False
                pos += size
            if pos != total:
                return False
            # Each sender's ranges must fit its stage's device slots:
            # the executor would otherwise raise deterministically on
            # every process and the dest's recovery re-announce would
            # disable the fabric for the whole run — reject the one
            # transfer here instead.
            ranges_per: Dict[NodeID, int] = {}
            for sender, _, _ in layout:
                ranges_per[sender] = ranges_per.get(sender, 0) + 1
            for sender, count in ranges_per.items():
                if sender not in self.placement.node_to_stage:
                    return False
                if count > len(self.placement.devices_for_node(sender)):
                    return False
        if dest == self.node.my_id or dest not in self.placement.node_to_stage:
            return False
        for sender, _, _ in layout:
            if sender not in self.placement.node_to_stage:
                return False
        with self._lock:
            for sender, _, _ in layout:
                meta = self.status.get(sender, {}).get(layer_id)
                if meta is None or meta.location == LayerLocation.CLIENT:
                    return False
        return True

    def _dispatch_device_plan(
        self, layer_id: LayerID, dest: NodeID,
        layout: List[Tuple[NodeID, int, int]], total: int,
        batch_id: str = "", batch_n: int = 1, pod=None,
    ) -> bool:
        """Send the plan to every participant; the layer bytes themselves
        never touch the transport (the fabric carries them).  Returns
        False when any participant missed the plan — the caller must then
        deliver over the host path instead (liveness: an incomplete plan
        would strand the dest waiting on contributions that never come,
        or pin seeders' uploads that nobody collects).

        ``batch_id``/``batch_n``: plan-batching hint (mode 3 groups
        same-dest equal-size plans) — the dest finishes the whole group
        as one batched gather instead of N serial collectives."""
        seq = next(self._plan_seq)
        self._plan_seq_hint = seq + 1
        self._replicate("plan_seq", Seq=seq + 1)
        plan_id = f"{layer_id}.{dest}.{seq}"
        spmd = self._spmd
        msg = DevicePlanMsg(self.node.my_id, plan_id, layer_id, dest,
                            total, list(layout), seq=seq if spmd else -1,
                            batch_id=batch_id, batch_n=batch_n,
                            pod=sorted(pod or []), epoch=self.epoch)
        with self._lock:
            active = not self._startup_sent
        if active:
            # Dispatching for an unfinished goal (first cycle, or a
            # re-armed update()): upload retention may re-arm — the next
            # startup will release again.
            reopen_upload_cache()
        if spmd:
            ok = self._broadcast_spmd_plan(msg)
        else:
            ok = self._send_inproc_plan(msg)
        if ok:
            log.info("dispatching device plan", plan=plan_id, layer=layer_id,
                     dest=dest, senders=sorted({s for s, _, _ in layout}),
                     total_bytes=total, spmd=spmd)
        return ok

    def _send_inproc_plan(self, msg: DevicePlanMsg) -> bool:
        # Dest first: if the dest never learns of the plan, abort before
        # any seeder uploads a contribution nobody will collect.
        try:
            self.node.transport.send(msg.dest_id, msg)
        except (OSError, KeyError) as e:
            log.error("couldn't send device plan to dest; host path",
                      plan=msg.plan_id, dest=msg.dest_id, err=repr(e))
            return False
        ok = True
        for participant in sorted(
            {s for s, _, _ in msg.layout} - {msg.dest_id}
        ):
            try:
                self.node.transport.send(participant, msg)
            except (OSError, KeyError) as e:
                log.error("couldn't send device plan to seeder; host path",
                          plan=msg.plan_id, dest=participant, err=repr(e))
                ok = False
        # On partial failure the dest's collect for this plan will time
        # out and discard any partial contributions; the host-path
        # duplicate delivery is tolerated by every receiver.
        return ok

    def _broadcast_spmd_plan(self, msg: DevicePlanMsg) -> bool:
        """SPMD lockstep: EVERY process (self included, via the transport
        self-delivery short-circuit) must receive every plan — all of them
        enter the collective.  On any send failure the seq must still be
        consumed everywhere, so a best-effort CANCELLATION (empty layout,
        same seq) follows.  Either way, the OPERATIVE message for the seq
        is retained (``_sent_plans``) so a process that missed its copy
        can ask for a re-send (``handle_plan_resend``) instead of
        stalling the pod until a human reads the logs."""
        with self._lock:
            recipients = sorted(set(self.status)
                                | {msg.dest_id, self.node.my_id})
            self._sent_plans[msg.seq] = msg
            while len(self._sent_plans) > self.SENT_PLAN_RETENTION:
                dropped = next(iter(self._sent_plans))
                self._sent_plans.pop(dropped)
                self._plan_watch.pop(dropped, None)
            self._plan_watch[msg.seq] = {"t": time.monotonic(),
                                         "retries": 0}
        failed = []
        for r in recipients:
            try:
                self.node.transport.send(r, msg)
            except (OSError, KeyError) as e:
                log.error("couldn't send spmd plan; cancelling seq",
                          plan=msg.plan_id, dest=r, err=repr(e))
                failed.append(r)
        if not failed:
            return True
        cancel = DevicePlanMsg(self.node.my_id, msg.plan_id, msg.layer_id,
                               msg.dest_id, 0, [], seq=msg.seq,
                               epoch=self.epoch)
        with self._lock:
            # The cancel supersedes the plan for this seq: a late
            # re-send of the ORIGINAL would have the gap process enter a
            # collective its peers already skipped.
            self._sent_plans[msg.seq] = cancel
        for r in recipients:
            try:
                self.node.transport.send(r, cancel)
            except (OSError, KeyError) as e:
                log.error("spmd plan cancel undeliverable; the gap "
                          "process will request a re-send",
                          plan=msg.plan_id, dest=r, err=repr(e))
        return False

    def handle_plan_resend(self, msg) -> None:
        """A fabric process's executor is stalled on missing plan seqs:
        re-send the retained message for each (the plan, or the cancel
        that superseded it).  An unknown seq gets a fresh CANCELLATION —
        advancing the requester past the hole is always safe, because a
        plan the leader no longer knows is one whose outcome the goal
        no longer depends on (its dest either acked or re-announced)."""
        with self._lock:
            stored = {s: self._sent_plans.get(s) for s in msg.seqs}
        for seq, plan in sorted(stored.items()):
            if plan is None:
                plan = DevicePlanMsg(self.node.my_id, f"cancel.{seq}",
                                     0, msg.src_id, 0, [], seq=seq,
                                     epoch=self.epoch)
            try:
                self.node.transport.send(msg.src_id, plan)
                log.info("re-sent spmd plan after gap report",
                         seq=seq, dest=msg.src_id,
                         cancelled=not plan.layout)
            except (OSError, KeyError) as e:
                log.error("plan re-send failed", seq=seq,
                          dest=msg.src_id, err=repr(e))

    def _try_fabric_full_layer(
        self, layer_id: LayerID, sender: NodeID, dest: NodeID
    ) -> bool:
        """Route a single-source full-layer send (modes 0-2) over the
        fabric; returns False when it must go the host path."""
        with self._lock:
            meta = self.status.get(sender, {}).get(layer_id)
            size = meta.data_size if meta is not None else 0
            if size <= 0 and sender == self.node.my_id:
                src = self.layers.get(layer_id)
                size = src.data_size if src is not None else 0
        if size <= 0:
            return False
        layout = [(sender, 0, size)]
        if not self._fabric_ok(layer_id, layout, dest, size):
            return False
        return self._dispatch_device_plan(layer_id, dest, layout, size)

    def handle_layer(self, msg: LayerMsg) -> None:
        """The leader can itself receive layers (e.g. from a client pipe):
        store + ack (node.go:376-407)."""
        with self._lock:
            src = msg.layer_src
            src.meta = LayerMeta(location=LayerLocation.INMEM)
            self.layers[msg.layer_id] = src
        self.node.transport.send(
            self.node.leader_id,
            AckMsg(self.node.my_id, msg.layer_id, LayerLocation.INMEM),
        )

    def _ack_liveness(self, src_id: NodeID) -> bool:
        """The ack path's liveness gate: False = the sender is written
        off and the ack must be ignored; True also refreshes its lease.
        The hierarchical leader overrides for GROUPED members — their
        liveness belongs to the sub-leader's detector, so an aggregated
        ack must neither create a root-side lease nor bounce off one
        (docs/hierarchy.md)."""
        if self.detector.is_dead(src_id):
            # Re-creating the status row would resurrect the node as a
            # schedulable sender that no one monitors anymore.
            log.warn("ignoring ack from crashed node", node=src_id)
            return False
        self.detector.touch(src_id)
        return True

    def handle_ack(self, msg: AckMsg) -> None:
        """Record delivery; on satisfaction broadcast startup + signal ready
        (node.go:410-432)."""
        if msg.src_id != self.node.my_id:
            if self.membership.is_left(msg.src_id):
                # A departed member's straggler ack must not recreate
                # its status row (docs/membership.md).
                trace.count("membership.zombie_fenced")
                log.warn("ack from a departed member fenced",
                         node=msg.src_id)
                return
            if not self._ack_liveness(msg.src_id):
                return
        with self._lock:
            row = self.status.setdefault(msg.src_id, {})
            # Carry the layer's size into the new owner's status entry (the
            # ack doesn't repeat it): schedulers size transfers from status,
            # and a size-less entry would wrongly disqualify this owner as
            # a future fabric sender for the layer it just received.
            prev = row.get(msg.layer_id)
            size = prev.data_size if prev is not None else 0
            if size <= 0:
                size = self._layer_size_locked(msg.layer_id)
            # Shard-qualified holding (docs/sharding.md): a shard ack
            # records a PARTIAL holding; never let it narrow a wider one
            # the row already has (a full copy covers every shard).
            shard = msg.shard
            if (prev is not None and delivered(prev)
                    and shard_covers(prev.shard, msg.shard)):
                shard = prev.shard
            # Version-qualified holding (docs/swap.md): an unversioned
            # duplicate re-ack must not strip a versioned holding's tag
            # (that would un-satisfy the swap pair and re-plan it).
            version = msg.version
            if (not version and prev is not None and delivered(prev)
                    and prev.version):
                version = prev.version
            # Codec-qualified holding (docs/codec.md): the ack reports
            # the form the dest's bytes are actually in — trusted
            # verbatim (a raw re-delivery over a stale quantized
            # holding must be able to upgrade the row to raw, or the
            # pair livelocks re-planning forever).
            codec = msg.codec
            row[msg.layer_id] = LayerMeta(location=msg.location,
                                          data_size=size, shard=shard,
                                          version=version, codec=codec)
            # A delivered (layer, dest) pair needs no more salvage, and
            # stops aging for health scoring.
            self._salvaging.discard((msg.layer_id, msg.src_id))
            getattr(self, "_pair_dispatch_mono", {}).pop(
                (msg.src_id, msg.layer_id), None)
            # The watchdog stops chasing any plan this ack settles.
            for seq, _rec in list(self._plan_watch.items()):
                plan = self._sent_plans.get(seq)
                if (plan is not None and plan.dest_id == msg.src_id
                        and plan.layer_id == msg.layer_id):
                    del self._plan_watch[seq]
        self._replicate("ack", Node=msg.src_id, Layer=msg.layer_id,
                        Location=int(msg.location), Size=size,
                        Shard=shard, Version=version, Codec=codec)
        # Pair-lifecycle span (docs/observability.md): the delivery's
        # terminal control edge — staged→acked is the ack-propagation +
        # leader-handling attribution.  The receiver's advisory SpanId
        # wins (it is the span its own events filed under); a legacy
        # ack falls back to the deterministic id.
        telemetry.span_event(
            msg.span_id or telemetry.span_id(msg.src_id, msg.layer_id),
            "acked", node=self.node.my_id, dest=msg.src_id,
            layer=msg.layer_id, shard=shard, version=version,
            codec=codec)
        # Content index + job plane: the delivered copy verified against
        # the stamped digest before acking, so the new owner vouches for
        # those bytes; the ack credits every admitted job wanting the
        # pair (docs/service.md).  A SHARD ack vouches for its (range
        # digest, shard) key only — it can never alias-complete a
        # full-layer pair (docs/sharding.md) — and a CODEC ack for its
        # (encoded digest, codec) key only (docs/codec.md).
        with self._lock:
            if shard:
                # A shard ack vouches for its RANGE's bytes only —
                # codec-qualified when the range hashes the encoded
                # blob (pod pairs); the FULL codec digest must never
                # stand in for it (docs/sharding.md, docs/codec.md).
                digest = self._range_digest_cache.get(
                    (msg.layer_id, _range_key(shard, codec)))
            elif codec:
                digest = self._codec_digest_cache.get(
                    (msg.layer_id, codec))
            else:
                digest = self.layer_digests.get(msg.layer_id)
        self.content.add(msg.src_id, msg.layer_id, digest, shard=shard,
                         codec=codec)
        self._jobs_completed(
            self.jobs.on_ack(msg.src_id, msg.layer_id, shard=shard,
                             version=version, codec=codec))
        # Fabric-assisted pod delivery (docs/fabric.md): a shard ack may
        # complete a pod's NIC phase (the SPMD gather dispatches here);
        # a full ack is the pair's materialized tree landing.
        self._on_pod_ack(msg.src_id, msg.layer_id, shard, codec)
        self._maybe_finish()

    def _jobs_completed(self, job_ids) -> None:
        """Log + replicate job completions (no-op on an empty list).
        A completed ``kind="swap"`` job drives its fence: clean
        completion commits, a degraded one aborts (docs/swap.md).  A
        completed ``kind="drain"`` re-home job releases its drainer
        (docs/membership.md)."""
        for jid in job_ids:
            job = self.jobs.get(jid)
            trace.count("jobs.completed")
            log.info("dissemination job complete", job=jid,
                     **(job.summary() if job is not None else {}))
            self._replicate("job_done", JobID=jid)
            self._on_swap_job_done(jid)
            self._on_drain_job_done(jid)

    def _layer_size_locked(self, layer_id: LayerID) -> int:
        """A layer's full size: the max announced ``data_size`` across
        status rows.  Iterates ``status`` — callers MUST hold ``_lock``
        (pool-concurrent handlers insert/pop rows)."""
        size = 0
        for layer_metas in self.status.values():
            meta = layer_metas.get(layer_id)
            if meta is not None and meta.data_size > size:
                size = meta.data_size
        return size

    def _maybe_finish(self) -> None:
        """Fire startup + ready exactly once when the (possibly shrunk)
        assignment is satisfied."""
        with self._lock:
            if self._startup_sent or not assignment_satisfied(
                self.assignment, self.status
            ) or self._pods_open_locked():
                return
            self._startup_sent = True
            # Replicate INSIDE the lock (publish only enqueues): every
            # writer of _startup_sent enqueues its delta under this
            # lock, so the standbys' shadow sees the flag flips in
            # write order — a completion racing a submit_job/update
            # re-arm must not land its Sent=True AFTER the re-arm's
            # Sent=False (a takeover would then adopt "FINISHED" and
            # never re-drive the admitted work).
            self._replicate("startup", Sent=True)
        log.info("timer stop: startup")
        self.send_startup()
        # End-of-delivery telemetry dump: the folded cluster table goes
        # into the log stream (the single source of truth the offline
        # run report and trace tooling read).  Reports are periodic, so
        # the last interval's bytes may still be in flight — the
        # -report path re-folds later, at process exit.
        self.log_cluster_metrics()
        self._ready_q.put(self.assignment)
        # Startup may have been unblocked by crashes that already emptied
        # the boot wait's remaining set (every assignee dead before
        # announcing): nothing will ever report, so complete it here —
        # with the crashes visible in boot_kinds — instead of holding the
        # CLI for its whole -bw timeout.
        self._maybe_complete_boot_wait()

    # ------------------------------------------------------------- failures

    def _recover(self) -> None:
        """Re-drive delivery after a crash; mode-specific schedulers
        override this when re-running ``send_layers`` wholesale would
        corrupt their job state."""
        self.send_layers()

    def crash(self, node_id: NodeID) -> None:
        """Remove a dead node and re-plan around it — the reference's
        never-implemented ``crash(n node)`` (node.go:218-220).

        A dead *sender*'s duties are re-scheduled onto the survivors.  A
        dead *assignee* is dropped from the assignment (its layers can
        never land), loudly, so the rest of the cluster still converges."""
        if node_id == self.node.my_id:
            log.error("refusing to declare self crashed")
            return
        if self.membership.is_left(node_id):
            # A cleanly-departed member can never crash: the drain
            # pruned it from every liveness table, and any straggler
            # report naming it is fenced (docs/membership.md).
            trace.count("membership.zombie_fenced")
            log.info("crash report for a departed member ignored",
                     node=node_id)
            return
        if self._spmd:
            # Every process must enter every collective; one is gone, so
            # remaining transfers take the host path.  Already-queued
            # plans referencing the dead node stall their executors — the
            # dests' plan waits time out and re-plan over TCP.
            log.error("node crashed under spmd fabric; disabling the "
                      "device plane for the rest of the run",
                      node=node_id)
            self._fabric_disabled = True
        self.detector.forget(node_id)
        cancels = []
        with self._lock:
            self.status.pop(node_id, None)
            # The crash broke pod lockstep for good (fabric disabled
            # above): every still-unacked plan can no longer execute, so
            # CANCEL each watched seq — this is the one moment the
            # give-up cancel is safe AND useful (peers stuck inside a
            # collective are already waiting on the dead participant;
            # gap processes must not wait forever on plans that will
            # never run).  The watchdog itself never cancels while no
            # crash is declared (_plan_watchdog).
            if self._spmd:
                for seq in list(self._plan_watch):
                    plan = self._sent_plans.get(seq)
                    del self._plan_watch[seq]
                    if plan is not None and plan.layout:
                        cancels.append((seq, plan))
            recipients = set(self.status) | {self.node.my_id}
            dropped = self.assignment.pop(node_id, None)
            if self._base_assignment is not self.assignment:
                self._base_assignment.pop(node_id, None)
            if dropped:
                # Remembered so a restarted incarnation that re-announces
                # gets its layers back (resume after declared death).
                self._dropped_assignment[node_id] = dropped
            self.expected_nodes.discard(node_id)
            # A dead assignee that never reported must stay VISIBLE as
            # "crashed" — erasing it would let the CLI report a
            # successful TTFT (exit 0) for a run where the model never
            # booted anywhere.  One that DID report keeps its record: a
            # receiver that booted fine, exited, and then had its lease
            # expire is a completed deployment, not a failure.
            if node_id not in self._boot_kinds:
                self._booted.pop(node_id, None)
                if dropped:
                    self._boot_kinds[node_id] = "crashed"
        for seq, plan in cancels:
            log.error("cancelling unacked spmd plan after declared crash",
                      seq=seq, plan=plan.plan_id, crashed=node_id)
            self._send_plan_to(self._make_plan_cancel(seq, plan),
                               recipients | {plan.dest_id}, seq)
        if dropped:
            log.error("crashed node was an assignee; dropping its layers",
                      node=node_id, layers=sorted(dropped))
        self._replicate("crash", Node=node_id,
                        Dropped=(layer_ids_to_json(dropped)
                                 if dropped else None))
        # Job plane: the dead dest's pairs can never land — drop them
        # from every admitted job (counted as dropped_pairs, so a job
        # completed this way is visibly degraded) and stop trusting the
        # node's content holdings.  Every MUTATED job record
        # re-replicates: a standby restoring admit-time remaining sets
        # would otherwise resurrect the dead dest's pairs at takeover
        # and wedge the adopted goal.
        self.content.drop_node(node_id)
        # A serving replica died mid-rollout: the swap can no longer
        # land everywhere — abort (v1 keeps serving on the survivors)
        # BEFORE the job drops mark it "done with drops" (docs/swap.md).
        # Rollout waves mid-flip/soak fail FIRST: a dead canary must
        # read as a breach (pause + revert), never as a silent no_data
        # pass — and failing the wave before the fence-set prune below
        # keeps the prune's completion edge from opening a soak window
        # on a wave that just lost a replica.
        self.rollouts.on_replica_crashed(node_id)
        pruned = []
        with self._lock:
            dead_swaps = [v for v, rec in self._swaps.items()
                          if rec["state"] == "rolling"
                          and node_id in rec["dests"]]
            for v, rec in self._swaps.items():
                # A COMMITTED swap's dead dest can never confirm: drop
                # it from the fence set so the watchdog completes on
                # the survivors.
                if rec["state"] == "committed" and node_id in rec["dests"]:
                    rec["dests"] = [d for d in rec["dests"]
                                    if d != node_id]
                    pruned.append(v)
        for version in pruned:
            # The prune must REPLICATE like every other swap mutation,
            # or a promoted standby re-adopts the dead dest and chases
            # its confirmation through the whole re-send budget.
            self._replicate_swap(version)
            # The dead dest may have been the LAST unconfirmed one:
            # the prune completes the fence set, so the completion
            # edge (finalize round / soak open) must fire here — no
            # further confirm will ever arrive to fire it.
            self._maybe_swap_complete(version)
        for version in dead_swaps:
            self._abort_swap(version, f"dest {node_id} crashed mid-rollout")
        if self.membership.is_draining(node_id):
            # The drainer died MID-drain: that is a real crash (the
            # salvage above applies — its unsent re-home bytes may be
            # lost), but the seat is terminally out: fence its
            # generation and answer waiters loudly instead of silence.
            self.membership.mark_left(node_id)
            with self._lock:
                waiters = self._drain_waiters.pop(node_id, set())
                for jid in [j for j, n in self._drain_jobs.items()
                            if n == node_id]:
                    del self._drain_jobs[jid]
            self._replicate_membership()
            for w in sorted(waiters):
                self._answer_drain(w, node_id,
                                   error=f"node {node_id} crashed "
                                         "mid-drain")
        affected, finished = self.jobs.drop_dest(node_id)
        for jid in affected:
            self._replicate("job", **self.jobs.record(jid))
        self._jobs_completed(finished)
        # Fabric-assisted pod delivery (docs/fabric.md): a dead pod
        # member's pod degrades to host-path delivery — survivors must
        # not wait on a gather contribution that can never arrive.
        self._pods_member_gone(node_id)
        self._drive(self._recover)
        # The crash may have removed the last assignee the boot/TTFT wait
        # was blocked on.
        self._maybe_complete_boot_wait()

    def send_startup(self) -> None:
        with self._lock:
            receivers = list(self.status)
        serve = self.serve_members() is not None
        self._serve_promised = serve  # a later cancel must release waiters
        for node_id in receivers:
            try:
                self.node.transport.send(
                    node_id,
                    StartupMsg(self.node.my_id, boot=self.boot_enabled,
                               serve=serve, epoch=self.epoch),
                )
            except (OSError, KeyError) as e:
                log.error("failed to send startup", dest=node_id, err=repr(e))
        if self.fabric is not None:
            release_upload_cache()  # the leader can be a fabric seeder too


class RetransmitLeaderNode(LeaderNode):
    """Mode 1: peers that already own a layer forward it (node.go:472-626)."""

    MODE = 1

    def __init__(self, node: Node, layers: LayersSrc, assignment: Assignment,
                 start_loop: bool = True,
                 expected_nodes: Optional[Set[NodeID]] = None,
                 failure_timeout: float = 0.0, fabric=None, placement=None,
                 **ha):
        self.layer_owners: Dict[LayerID, Set[NodeID]] = {}
        super().__init__(node, layers, assignment, start_loop=start_loop,
                         expected_nodes=expected_nodes,
                         failure_timeout=failure_timeout,
                         fabric=fabric, placement=placement, **ha)

    def crash(self, node_id: NodeID) -> None:
        """A dead node no longer serves its layers; re-run the owner
        scheduling for everything unacked (receivers tolerate duplicate
        deliveries, so re-sending in-flight layers is safe)."""
        with self._lock:
            for owners in self.layer_owners.values():
                owners.discard(node_id)
        super().crash(node_id)

    def _build_layer_owners(self) -> None:
        """(Re)index layer → owner set from live status (node.go:558-571).
        Rebuilt from scratch: status is the source of truth, and a
        restarted node no longer owns what its dead incarnation held.
        FULL CANONICAL holdings only: a shard-holder (docs/sharding.md)
        can't forward a whole layer, and a CODEC holder's bytes are the
        encoded form — forwarding them as a raw delivery would ship
        garbage under the layer's identity (docs/codec.md; mode 1/2's
        coarse per-layer pool can't express per-pair admissibility, so
        quantized holders simply never re-seed here — honest limit,
        mode 3's arc filter does it exactly).  UNVERIFIED joiners
        (docs/membership.md) are quarantined too: their announced
        holdings are not trusted as forward sources until they
        digest-verify."""
        self.layer_owners = {}
        quarantined = self.membership.unverified_sources()
        for node_id, layer_ids in self.status.items():
            if node_id in quarantined:
                continue
            for layer_id, meta in layer_ids.items():
                if meta.shard or getattr(meta, "codec", ""):
                    continue
                self.layer_owners.setdefault(layer_id, set()).add(node_id)

    def send_layers(self) -> None:
        self._stamp_targets()
        with self._lock:
            self._build_layer_owners()
            owners_by_layer = {k: set(v) for k, v in self.layer_owners.items()}
        for node_id, layer_ids in self.assignment.items():
            for layer_id, want in layer_ids.items():
                with self._lock:
                    held = self.status.get(node_id, {}).get(layer_id)
                    if self._content_skip_locked(node_id, layer_id):
                        continue
                if satisfies(held, want):
                    continue  # dest already holds its target (shard-aware)
                jid_owner = self.jobs.owner_of(node_id, layer_id)
                jid = jid_owner[1] if jid_owner else ""
                owners = owners_by_layer.get(layer_id, set())
                owners = owners - {node_id}
                if want.codec:
                    # A codec pair's owner must be able to ENCODE the
                    # forward (the pool holds raw full holders only;
                    # docs/codec.md) — and a delta pair's owner must
                    # ALSO hold the base it encodes against.
                    with self._lock:
                        owners = {o for o in owners
                                  if codec_capability(want.codec)
                                  in self.node_codecs.get(o, ())}
                        base_d = delta_base_digest(want.codec)
                        if base_d:
                            owners = {
                                o for o in owners
                                if self.content.node_has(o, base_d)
                                or (o == self.node.my_id
                                    and base_d in self._delta_base_src)}
                if owners:
                    # Deterministic owner pick (reference picks randomly via
                    # map iteration, node.go:583-588).
                    owner = min(owners)
                    try:
                        self.send_retransmit(layer_id, owner, node_id,
                                             job_id=jid, shard=want.shard,
                                             codec=want.codec)
                    except Exception as e:  # noqa: BLE001
                        log.error(
                            "couldn't send retransmit",
                            layerID=layer_id, owner=owner, err=repr(e),
                        )
                else:
                    layer = self.layers.get(layer_id)
                    if layer is None:
                        log.warn("no layers found", layerID=layer_id)
                        continue
                    if (not want.shard and not want.codec
                            and self._try_fabric_full_layer(
                                layer_id, self.node.my_id, node_id)):
                        continue
                    self.loop.submit(self._send_one, node_id, layer_id,
                                     layer, jid, want.shard, want.codec)

    def send_retransmit(self, layer_id: LayerID, owner: NodeID,
                        dest: NodeID, job_id: str = "",
                        shard: str = "", codec: str = "") -> None:
        """Ask ``owner`` to forward ``layer_id`` to ``dest``; leader-owned
        layers go out directly (node.go:611-626).  With a fabric wired the
        forward becomes a one-source device plan — the owner's copy enters
        the fabric from its own stage and lands in the dest's HBM with no
        TCP byte stream (modes 1 and 2 share this path).  ``shard``:
        forward only that byte-range slice (host path only — the fabric
        plane speaks whole layers).  ``codec``: forward the ENCODED form
        (host path only, docs/codec.md)."""
        # Pair-lifecycle span (docs/observability.md): the pair entered
        # a concrete plan NOW (modes 0-2's forward command; mode 3's
        # flow dispatch records its own).
        telemetry.span_event(telemetry.span_id(dest, layer_id), "planned",
                             node=self.node.my_id, src=owner, dest=dest,
                             layer=layer_id, job=job_id, codec=codec,
                             shard=shard)
        if (not shard and not codec
                and self._try_fabric_full_layer(layer_id, owner, dest)):
            return
        if owner == self.node.my_id:
            layer = self.layers.get(layer_id)
            if layer is None:
                log.warn("no layers found", layerID=layer_id)
                return
            # Off the caller's thread: send_layers drives this in a loop,
            # and an inline rate-paced send would serialize every
            # leader-owned transfer behind the previous one (mode 0's
            # sends are pooled for the same reason, node.go:343-349).
            self.loop.submit(self._send_one, dest, layer_id, layer, job_id,
                             shard, codec)
            return
        self.node.transport.send(
            owner, RetransmitMsg(self.node.my_id, layer_id, dest,
                                 epoch=self.epoch, job_id=job_id,
                                 shard=shard, codec=codec)
        )


class _JobInfo:
    """Mode-2 job table entry (node.go:639-647)."""

    __slots__ = ("sender", "status", "t_start")
    PENDING = 0
    SENDING = 1

    def __init__(self, sender: Optional[NodeID] = None):
        self.sender = sender
        self.status = _JobInfo.PENDING
        self.t_start: Optional[float] = None


class PullRetransmitLeaderNode(RetransmitLeaderNode):
    """Mode 2: pull/work-stealing scheduler (node.go:662-1073).

    Rarest-first initial assignment to the min-loaded owner; every ack frees
    its sender, which immediately pulls its rarest remaining job or steals a
    pending job from the slowest/overloaded sender (estimated by moving-
    average job duration × queue length)."""

    MODE = 2
    # Mode 2's pull/steal tables pick senders per layer with no
    # per-pair codec admissibility: it never chooses wire codecs
    # (docs/codec.md, honest limits).
    WIRE_CODEC_OK = False

    def __init__(self, node: Node, layers: LayersSrc, assignment: Assignment,
                 start_loop: bool = True,
                 expected_nodes: Optional[Set[NodeID]] = None,
                 failure_timeout: float = 0.0, fabric=None, placement=None,
                 **ha):
        # layer -> dest -> pull job (the mode-2 work-stealing table;
        # renamed from ``jobs`` when the SERVICE job plane took that
        # name on the base class, docs/service.md)
        self._pull_jobs: Dict[LayerID, Dict[NodeID, _JobInfo]] = {}
        self.sender_load: Dict[NodeID, int] = {}
        # sender -> (avg job duration seconds, completed count)
        self.performance: Dict[NodeID, Tuple[float, int]] = {}
        super().__init__(node, layers, assignment, start_loop=start_loop,
                         expected_nodes=expected_nodes,
                         failure_timeout=failure_timeout,
                         fabric=fabric, placement=placement, **ha)

    def crash(self, node_id: NodeID) -> None:
        """Surgical job-table repair: jobs destined for the dead node are
        dropped; jobs it was sending (or queued to send) are orphaned for
        ``_recover`` to reassign.  Living senders' load counters for
        dropped jobs are left as-is — they only bias the min-load
        heuristic, and self-correct as jobs complete."""
        with self._lock:
            self.sender_load.pop(node_id, None)
            self.performance.pop(node_id, None)
            for layer_id in list(self._pull_jobs):
                dests = self._pull_jobs[layer_id]
                dests.pop(node_id, None)
                for job in dests.values():
                    if job.sender == node_id:
                        job.sender = None
                        job.status = _JobInfo.PENDING
                        job.t_start = None
                if not dests:
                    del self._pull_jobs[layer_id]
        super().crash(node_id)

    def _release_pending_load(self, job: "_JobInfo") -> None:
        """Give back a superseded job's load slot (held only while
        PENDING — a pull already decremented it).  Lock held."""
        if job.status == _JobInfo.PENDING and job.sender is not None:
            self.sender_load[job.sender] = max(
                0, self.sender_load.get(job.sender, 1) - 1
            )

    def _schedule_missing_locked(
        self, dest: NodeID, replace_existing: bool = False
    ) -> Set[NodeID]:
        """Create PENDING jobs for ``dest``'s undelivered assigned layers
        and return the senders to kick.  Lock held.  With
        ``replace_existing`` the dest's current jobs are superseded (a
        restarted dest's in-flight transfers are dead)."""
        kicked: Set[NodeID] = set()
        for node_id in self.status:
            self.sender_load.setdefault(node_id, 0)
        held = self.status.get(dest, {})
        for layer_id, want in self.assignment.get(dest, {}).items():
            if satisfies(held.get(layer_id), want):
                continue
            old = self._pull_jobs.get(layer_id, {}).get(dest)
            if old is not None and not replace_existing:
                continue  # already queued or in flight
            sender = self._min_loaded_sender(layer_id)
            if sender is None:
                log.error("no owner for missing assigned layer",
                          layer=layer_id, dest=dest)
                continue
            if old is not None:
                self._release_pending_load(old)
            self._pull_jobs.setdefault(layer_id, {})[dest] = _JobInfo(sender)
            self.sender_load[sender] = self.sender_load.get(sender, 0) + 1
            kicked.add(sender)
        return kicked

    def _on_reannounce(self, node_id: NodeID) -> None:
        """Rebuild jobs for a restarted assignee's still-missing layers
        (its in-flight transfers died with the old process) and kick the
        chosen senders.  Jobs the restarted node was *sending* also died
        with it: those are reset to PENDING (kept with the node if it
        still owns the layer, orphaned otherwise) and re-driven."""
        kicked: Set[NodeID] = set()
        orphaned = False
        with self._lock:
            self._build_layer_owners()
            for layer_id, dests in self._pull_jobs.items():
                for dest, job in dests.items():
                    if job.sender != node_id or job.status != _JobInfo.SENDING:
                        continue
                    job.t_start = None
                    job.status = _JobInfo.PENDING
                    if layer_id in self.status.get(node_id, {}):
                        # Still owns it (e.g. disk layer): re-drive there.
                        self.sender_load[node_id] = (
                            self.sender_load.get(node_id, 0) + 1
                        )
                        kicked.add(node_id)
                    else:
                        job.sender = None  # _recover reassigns orphans
                        orphaned = True
            kicked |= self._schedule_missing_locked(node_id,
                                                    replace_existing=True)
        if orphaned:
            self._recover()
        for sender in kicked:
            self.loop.submit(self._assign_new_job_safe, sender)

    def _update_replan(self) -> None:
        """Incremental job-table repair for a changed assignment: prune
        PENDING jobs whose dest is no longer assigned the layer (in-flight
        SENDING jobs are left to finish — their acks re-kick the sender,
        and receivers tolerate the extra delivery), create jobs for newly
        missing (dest, layer) pairs, and kick their senders."""
        kicked: Set[NodeID] = set()
        with self._lock:
            self._build_layer_owners()
            for layer_id in list(self._pull_jobs):
                dests = self._pull_jobs[layer_id]
                for dest in list(dests):
                    job = dests[dest]
                    if (layer_id not in self.assignment.get(dest, {})
                            and job.status == _JobInfo.PENDING):
                        self._release_pending_load(dests.pop(dest))
                if not dests:
                    del self._pull_jobs[layer_id]
            for dest in self.assignment:
                kicked |= self._schedule_missing_locked(dest)
        for sender in kicked:
            self.loop.submit(self._assign_new_job_safe, sender)

    def _recover(self) -> None:
        """Reassign orphaned jobs to the min-loaded surviving owner and
        kick those senders (instead of the base full ``send_layers`` rerun,
        which would rebuild the live job table from scratch)."""
        kicked: Set[NodeID] = set()
        with self._lock:
            for layer_id, dests in self._pull_jobs.items():
                for dest, job in dests.items():
                    if job.sender is not None:
                        continue
                    sender = self._min_loaded_sender(layer_id)
                    if sender is None:
                        log.error("no surviving owner for orphaned job",
                                  layer=layer_id, dest=dest)
                        continue
                    job.sender = sender
                    self.sender_load[sender] += 1
                    kicked.add(sender)
        for sender in kicked:
            self.loop.submit(self._assign_new_job_safe, sender)

    def send_layers(self) -> None:
        """Build the job table rarest-first and kick every node
        (node.go:810-904)."""
        with self._lock:
            self._build_layer_owners()
            # Rarest-first layer order, layer-id tiebreak (node.go:842-851).
            sorted_layers = sorted(
                self.layer_owners,
                key=lambda lid: (len(self.layer_owners[lid]), lid),
            )
            for dest, layer_ids in self.assignment.items():
                held = self.status.get(dest, {})
                for layer_id, want in layer_ids.items():
                    if not satisfies(held.get(layer_id), want):
                        self._pull_jobs.setdefault(layer_id, {})[dest] = _JobInfo()
            for node_id in self.status:
                self.sender_load.setdefault(node_id, 0)
            for layer_id in sorted_layers:
                for dest in sorted(self._pull_jobs.get(layer_id, {})):
                    sender = self._min_loaded_sender(layer_id)
                    self._pull_jobs[layer_id][dest] = _JobInfo(sender)
                    self.sender_load[sender] += 1
                    log.info("job assignment", layer=layer_id, sender=sender)
            # Kick every node that might have work: assignment dests AND
            # loaded senders.  (The reference kicks only assignment nodes,
            # node.go:890-903, which strands jobs assigned to the leader
            # when no peer owns a layer.)
            nodes = sorted(
                set(self.assignment)
                | {s for s, load in self.sender_load.items() if load > 0}
            )
        for node_id in nodes:
            self.loop.submit(self._assign_new_job_safe, node_id)

    def _assign_new_job_safe(self, node_id: NodeID) -> None:
        try:
            self.assign_new_job(node_id)
        except Exception as e:  # noqa: BLE001
            log.error("failed to assign a new job", node=node_id, err=repr(e))

    def _min_loaded_sender(self, layer_id: LayerID) -> NodeID:
        """Owner with the fastest source rate, then least load, then lowest
        id (node.go:948-978)."""
        best, best_rate, min_count = None, -1, 1 << 62
        for sender in sorted(self.sender_load):
            count = self.sender_load[sender]
            meta = self.status.get(sender, {}).get(layer_id)
            if meta is None or meta.shard:
                # A shard-holder can't forward the whole layer
                # (docs/sharding.md); it never enters the sender pool.
                continue
            rate = meta.limit_rate if meta.limit_rate != 0 else 1 << 62
            if rate > best_rate or (
                rate == best_rate
                and (count < min_count or (count == min_count and sender < best))
            ):
                best, best_rate, min_count = sender, rate, count
        return best

    def _rarest_own_job(
        self, node_id: NodeID
    ) -> Optional[Tuple[LayerID, NodeID, _JobInfo]]:
        """This node's still-pending job with the rarest layer
        (node.go:981-1010)."""
        best = None
        min_owners = 1 << 62
        for layer_id, own_meta in self.status.get(node_id, {}).items():
            if own_meta.shard:
                continue  # shard holders don't forward (docs/sharding.md)
            for dest, job in self._pull_jobs.get(layer_id, {}).items():
                if job.sender != node_id or job.status != _JobInfo.PENDING:
                    continue
                owners = len(self.layer_owners.get(layer_id, ()))
                if owners < min_owners or (
                    best is not None and owners == min_owners and layer_id < best[0]
                ):
                    min_owners = owners
                    best = (layer_id, dest, job)
        return best

    def _rarest_stealable_job(
        self, node_id: NodeID
    ) -> Optional[Tuple[LayerID, NodeID, NodeID]]:
        """A pending job owned by a slower/overloaded sender that this node
        could serve instead (node.go:1012-1073)."""
        best = None  # (layer, dest, sender, owner_count, time_to_finish)
        for layer_id, own_meta in self.status.get(node_id, {}).items():
            if own_meta.shard:
                continue  # shard holders don't forward (docs/sharding.md)
            owner_count = len(self.layer_owners.get(layer_id, ()))
            for dest, job in self._pull_jobs.get(layer_id, {}).items():
                sender = job.sender
                if sender is None:
                    continue
                # Normalize the 0-means-unlimited sentinel before comparing
                # (the reference's raw comparison lets a slow node steal
                # from an unlimited-rate sender, node.go:1039-1040).
                _raw_s = self.status.get(sender, {}).get(layer_id, LayerMeta()).limit_rate
                _raw_n = self.status.get(node_id, {}).get(layer_id, LayerMeta()).limit_rate
                sender_rate = _raw_s if _raw_s != 0 else 1 << 62
                node_rate = _raw_n if _raw_n != 0 else 1 << 62
                if (
                    sender == node_id
                    or job.status != _JobInfo.PENDING
                    or self.sender_load.get(sender, 0) == 0
                    or node_rate < sender_rate
                ):
                    continue
                perf = self.performance.get(sender)
                if perf is None:
                    # Sender stuck on its first job: steal with priority.
                    ttf = float("inf")
                else:
                    ttf = perf[0] * self.sender_load.get(sender, 0)
                cand = (layer_id, dest, sender, owner_count, ttf)
                if (
                    best is None
                    or cand[3] < best[3]
                    or (cand[3] == best[3] and cand[4] > best[4])
                ):
                    best = cand
        if best is None:
            return None
        return best[0], best[1], best[2]

    def assign_new_job(self, node_id: NodeID) -> None:
        """The scheduling loop body (node.go:909-945)."""
        with self._lock:
            own = self._rarest_own_job(node_id)
            if own is not None:
                layer_id, dest, job = own
                job.status = _JobInfo.SENDING
                job.t_start = time.monotonic()
                self.sender_load[node_id] -= 1
                sender = node_id
            else:
                stolen = self._rarest_stealable_job(node_id)
                if stolen is None:
                    log.info("there is no job left to assign", node=node_id)
                    return
                layer_id, dest, prev_sender = stolen
                self.sender_load[prev_sender] -= 1
                job = self._pull_jobs[layer_id][dest]
                job.sender = node_id
                job.status = _JobInfo.SENDING
                job.t_start = time.monotonic()
                sender = node_id
                log.debug("steal a job", layer=layer_id, frm=prev_sender, to=node_id)
        jid_owner = self.jobs.owner_of(dest, layer_id)
        with self._lock:
            want = (self.assignment.get(dest) or {}).get(layer_id)
        self.send_retransmit(layer_id, sender, dest,
                             job_id=jid_owner[1] if jid_owner else "",
                             shard=want.shard if want is not None else "")

    def handle_ack(self, msg: AckMsg) -> None:
        """Completion accounting + throughput tracking + re-scheduling
        (node.go:741-807)."""
        super().handle_ack(msg)
        with self._lock:
            job = self._pull_jobs.get(msg.layer_id, {}).get(msg.src_id)
            if job is None:
                return  # e.g. a client-loaded layer: no tracked job
            log.info("job completed", node=job.sender, layerID=msg.layer_id)
            dur = (
                time.monotonic() - job.t_start if job.t_start is not None else 0.0
            )
            avg, count = self.performance.get(job.sender, (0.0, 0))
            self.performance[job.sender] = ((avg * count + dur) / (count + 1), count + 1)
            # The new owner can now serve this layer too — unless it
            # holds only a shard of it (docs/sharding.md).
            if not msg.shard:
                self.layer_owners.setdefault(msg.layer_id, set()).add(
                    msg.src_id)
            del self._pull_jobs[msg.layer_id][msg.src_id]
            sender = job.sender
        if sender is not None:
            self._assign_new_job_safe(sender)


class FlowRetransmitLeaderNode(RetransmitLeaderNode):
    """Mode 3: globally optimal plan via time-parameterized max-flow
    (node.go:1076-1288).

    Unlike the reference, which supports only one destination per layer
    (node.go:1078, error at :1092), the flow graph here models a vertex
    per (layer, dest) pair, so one layer can be scheduled to any number
    of receivers — each needing its own full copy — with per-sender
    contributions still exactly attributable (PP-stage replication needs
    this).  Crash recovery needs no dest bookkeeping: the re-plan derives
    everything from assignment + status."""

    MODE = 3

    def __init__(
        self,
        node: Node,
        layers: LayersSrc,
        assignment: Assignment,
        node_network_bw: Dict[NodeID, int],
        start_loop: bool = True,
        expected_nodes: Optional[Set[NodeID]] = None,
        failure_timeout: float = 0.0,
        fabric=None,
        placement=None,
        topology=None,
        pods=None,
        **ha,
    ):
        """``topology``: optional ``sched.flow.PodTopology`` — multi-slice
        pods plan cross-slice transfers against the per-pair DCN
        capacity instead of pretending every edge is ICI.

        ``pods`` (docs/fabric.md): ``{pod_id: [member node ids]}`` —
        groups of dests sharing an ICI domain.  A layer every member of
        a pod wants ships as ONE 1/R shard per host over the NIC
        (possibly quantized), and the full tree materializes over the
        on-mesh gather — pod NIC ingress is O(model_bytes), not
        O(model_bytes x replicas).  Members must be disjoint across
        pods and must not include the leader seat."""
        self.node_network_bw = dict(node_network_bw)
        self.topology = topology
        self.pods: Dict[int, List[NodeID]] = {}
        self._pod_of: Dict[NodeID, int] = {}
        for pid, members in sorted((pods or {}).items()):
            ms = sorted(int(m) for m in members)
            if int(node.my_id) in ms:
                raise ValueError("the leader seat cannot be a pod member")
            for m in ms:
                if m in self._pod_of:
                    raise ValueError(
                        f"node {m} appears in more than one pod")
                self._pod_of[m] = int(pid)
            self.pods[int(pid)] = ms
        # Pods that lost a member (crash/drain) degrade to host-path
        # delivery for the rest of the run — a silent mid-flight
        # re-shard would strand partials in dead byte spaces.
        self._pods_broken: Set[int] = set()
        # (layer, dest) -> monotonic time of the pair's SHARD ack: the
        # gather watchdog degrades pairs whose full tree never follows.
        self._pod_shard_acked: Dict[Tuple[LayerID, NodeID], float] = {}
        # (layer, pod) gathers already dispatched on the SPMD fabric.
        self._pod_gather_sent: Set[Tuple[LayerID, int]] = set()
        self._pod_plane_warned = False
        # sender -> dispatched (not yet known-delivered) flow jobs: the
        # range-salvage index — crash(sender) re-plans only its jobs'
        # DESTS' uncovered byte ranges (docs/failover.md).
        self._live_jobs: Dict[NodeID, List[FlowJob]] = {}
        # The INITIAL solve's predicted completion time (ms) and wall
        # solve cost — prediction-vs-achieved is the plan-fidelity
        # record the CLI prints next to TTD (re-plans keep the first
        # full solve: that is the prediction the TTD clock started on).
        self.predicted_ttd_ms = 0
        self.solve_ms = 0.0
        # job_id -> its priority tier's solved min time (ms): per-job
        # pacing for multi-job dispatches (docs/service.md).
        self._tier_time: Dict[str, int] = {}
        # Autonomy link demotions (docs/autonomy.md): (src, dest) ->
        # measured bytes/s.  The flow solver prices these arcs at the
        # measured rate instead of infinity, so re-plans route AROUND a
        # straggling link without removing it from the graph.
        self._link_demotions: Dict[Tuple[NodeID, NodeID], int] = {}
        # Plan generation: bumped on every solve.  Revokes carry the
        # generation they fenced; re-dispatched commands carry the NEW
        # one — a late revoke can no longer eat the re-plan's fresh
        # command for the same (job, dest, layer) (docs/service.md).
        self._plan_gen = 0
        if topology is not None:
            # Pre-warm the LP solver import (scipy + HiGHS, ~1-2 s cold)
            # off the critical path: the first assign_jobs otherwise pays
            # it inside the TTD clock.
            threading.Thread(
                target=self._warm_lp, name="lp-warm", daemon=True
            ).start()
        super().__init__(node, layers, assignment, start_loop=start_loop,
                         expected_nodes=expected_nodes,
                         failure_timeout=failure_timeout,
                         fabric=fabric, placement=placement, **ha)
        if self.pods and start_loop:
            threading.Thread(target=self._pod_watchdog,
                             name="pod-watchdog", daemon=True).start()

    @staticmethod
    def _warm_lp() -> None:
        from ..sched.flow import warm_lp

        warm_lp()

    def _register_handlers(self) -> None:
        super()._register_handlers()
        # register_keep on a shared loop: a promoted mode-3 worker keeps
        # its own (equivalent) flow-job handler.
        reg = (self.loop.register_keep if self._shared_loop
               else self.loop.register)
        reg(FlowRetransmitMsg, self.handle_flow_retransmit)

    def _node_bw(self, node_id: NodeID) -> int:
        """Mode 3 models NICs: the codec-choice bottleneck estimate
        uses them (docs/codec.md)."""
        return self.node_network_bw.get(node_id, 0)

    def send_layers(self) -> None:
        self._stamp_targets()
        t, self_jobs, jobs = self.assign_jobs()
        self._dispatch(t, self_jobs, jobs)

    def _plan_assignment_locked(self) -> Assignment:
        """The goal ``assign_jobs`` plans over — the full assignment in
        flat mode; the hierarchical leader reduces grouped members to
        group-ingress demands (docs/hierarchy.md).  Lock held."""
        return self.assignment

    # ------------------------------------------ fabric-assisted pod delivery

    # How long a pod pair may sit shard-complete without its FULL tree
    # ack before the whole (layer, pod) degrades to host-path delivery,
    # and the check cadence (class attrs: tests tune them).
    POD_GATHER_TIMEOUT = 60.0
    POD_WATCH_PERIOD = 1.0

    def _pod_plane_available(self) -> bool:
        """Whether the pod members have an ICI reconstruction plane:
        the SPMD lockstep fabric (the leader dispatches the gather), or
        a shared single-controller ``FabricPlane`` whose shard board the
        members self-coordinate over.  Without one, pod pairs would
        shard-deliver and then wedge waiting for peers that can never
        be reached — plan flat instead, loudly (the "hosts without a
        fabric fall back to the host path" rule, docs/fabric.md)."""
        if self._fabric_disabled:
            return False
        if self._spmd:
            return True
        return self.fabric is not None and hasattr(self.fabric,
                                                   "pod_publish")

    def _stamp_pod_shards(self) -> None:
        """The pod-delivery demand transform (docs/fabric.md; the
        pricing half lives in ``sched.flow.pod_shard_demands``): every
        layer ALL members of a pod want as a plain full target is
        re-targeted as one 1/R shard slice per member — the NIC then
        carries ~model_bytes per pod (x codec ratio), and the ICI
        gather materializes the other R-1 copies.  Idempotent across
        re-plans: existing pairs keep their specs verbatim (mid-flight
        partials live in those byte ranges)."""
        if not self.pods:
            return
        if not self._pod_plane_available():
            if not self._pod_plane_warned:
                self._pod_plane_warned = True
                log.warn("pods configured but no reconstruction plane "
                         "(fabric missing/disabled); pod pairs plan "
                         "flat over the host path")
            return
        added = []
        redrive: Set[Tuple[LayerID, int]] = set()
        with self._lock:
            live = {pid: [m for m in ms if m in self.assignment]
                    for pid, ms in self.pods.items()
                    if pid not in self._pods_broken}
            live = {pid: ms for pid, ms in live.items() if len(ms) >= 2}
            if not live:
                return
            # ADOPTION (failover re-derivation): a promoted leader's
            # replicated assignment already carries the predecessor's
            # pod shard specs, which the transform below deliberately
            # refuses to re-slice — recognize a consistent 1/R@k
            # pattern across a pod's members as the SAME pod pairs, or
            # the goal would close on shard acks with no gather ever
            # driven (the open-until-materialized invariant).
            for pid, ms in live.items():
                layers = sorted({lid for m in ms
                                 for lid in (self.assignment.get(m)
                                             or {})})
                for lid in layers:
                    if any((lid, m) in self._pod_pairs for m in ms):
                        continue
                    speced = []
                    for m in ms:
                        meta = (self.assignment.get(m) or {}).get(lid)
                        if meta is None or not meta.shard \
                                or meta.version:
                            continue
                        speced.append((m, meta.shard))
                    n = len(speced)
                    if n < 2 or [s for _, s in speced] != [
                            f"1/{n}@{k}" for k in range(n)]:
                        continue
                    for m, spec in speced:
                        self._pod_pairs[(lid, m)] = spec
                    redrive.add((lid, pid))
                    log.info("adopted in-flight pod pairs from the "
                             "replicated goal", layerID=lid, pod=pid,
                             members=[m for m, _ in speced])
            pairs = pod_shard_demands(self.assignment, live,
                                      prior=self._pod_pairs)
            for (lid, dest), spec in sorted(pairs.items()):
                row = self.assignment.get(dest)
                meta = (row or {}).get(lid)
                if meta is None or meta.version:
                    continue
                if (lid, dest) in self._pod_pairs:
                    # A goal re-merge (update/submit_job) rebuilds the
                    # assignment spec-less: re-apply the STABLE prior
                    # spec, exactly like _stamp_codecs re-applies its
                    # memoized choices.
                    if not meta.shard:
                        row[lid] = dataclasses.replace(meta, shard=spec)
                    continue
                row[lid] = dataclasses.replace(meta, shard=spec)
                self._pod_pairs[(lid, dest)] = spec
                added.append((lid, dest, spec))
        if added:
            trace.count("pod.pairs_planned", len(added))
            log.info("pod delivery planned",
                     pairs=len(added),
                     layers=sorted({lid for lid, _, _ in added}))
        # Re-drive adopted pods whose shard phase already finished
        # under the predecessor: seed the gather clock (the watchdog
        # must cover them) and, under SPMD, dispatch the gather —
        # no further shard ack will arrive to trigger it.
        for lid, pid in sorted(redrive):
            with self._lock:
                if not self._pod_shards_ready_locked(lid, pid):
                    continue
                now = time.monotonic()
                for m in self.pods.get(pid, ()):
                    if (lid, m) in self._pod_pairs:
                        self._pod_shard_acked.setdefault((lid, m), now)
            if self._spmd:
                self._maybe_dispatch_pod_gather(lid, pid)

    def _pods_open_locked(self) -> bool:
        """Lock held.  A pod pair is OPEN until its dest's status row
        shows the FULL wire-form tree (shard "" in an accepting codec)
        — shard coverage alone must not finish the goal
        (docs/fabric.md: the gathered tree is the deliverable)."""
        for (lid, dest), spec in self._pod_pairs.items():
            want = (self.assignment.get(dest) or {}).get(lid)
            if want is None or want.shard != spec:
                continue  # degraded/dropped pair: the plain goal rules
            held = self.status.get(dest, {}).get(lid)
            if (held is None or not delivered(held) or held.shard
                    or not codec_accepts(held.codec, want.codec)):
                return True
        return False

    def _on_pod_ack(self, dest: NodeID, layer_id: LayerID, shard: str,
                    codec: str) -> None:
        key = (layer_id, dest)
        with self._lock:
            spec = self._pod_pairs.get(key)
            if spec is None:
                return
            if not shard:
                # The materialized tree landed: the pair is closed (the
                # watchdog stops aging it).
                self._pod_shard_acked.pop(key, None)
                trace.count("pod.pairs_materialized")
                log.info("pod pair materialized its full tree",
                         layerID=layer_id, dest=dest,
                         codec=codec or None)
                return
            pid = self._pod_of.get(dest)
            # The gather clock starts only when the POD's shard set is
            # COMPLETE — no tree can materialize before the last shard
            # lands, so aging a pair from its own (possibly first) ack
            # would spuriously degrade a pod whose other members are
            # still legitimately downloading.
            if pid is not None and self._pod_shards_ready_locked(
                    layer_id, pid):
                now = time.monotonic()
                for m in self.pods.get(pid, ()):
                    if (layer_id, m) in self._pod_pairs:
                        self._pod_shard_acked.setdefault(
                            (layer_id, m), now)
        if self._spmd and pid is not None:
            self._maybe_dispatch_pod_gather(layer_id, pid)

    def _pod_shards_ready_locked(self, layer_id: LayerID,
                                 pid: int) -> bool:
        """Lock held.  Whether every member of ``pid`` with a pod pair
        for ``layer_id`` has delivered its shard (the reconstruction
        phase can begin — and be timed)."""
        any_pair = False
        for m in self.pods.get(pid, ()):
            spec = self._pod_pairs.get((layer_id, m))
            if spec is None:
                continue
            any_pair = True
            held = self.status.get(m, {}).get(layer_id)
            if (held is None or not delivered(held)
                    or not shard_covers(held.shard, spec)):
                return False
        return any_pair

    def _pod_wire_total_locked(self, layer_id: LayerID, codec: str) -> int:
        """Lock held.  The pod pair's wire-space total: encoded bytes
        for a codec pair, the canonical layer size otherwise."""
        if codec and self.codecs is not None:
            n = self.codecs.nbytes(layer_id, codec)
            if n is not None:
                return n
        return self._layer_size_locked(layer_id)

    def _maybe_dispatch_pod_gather(self, layer_id: LayerID,
                                   pid: int) -> None:
        """SPMD pods: once EVERY member of ``pid`` acked its shard of
        ``layer_id``, broadcast ONE reconstruction plan — layout = the
        members' shard ranges, ``pod`` = the members (all of them keep
        the gathered tree).  The members then verify the full wire-form
        digest and ack the FULL layer (receiver._await_spmd_plan)."""
        with self._lock:
            if (layer_id, pid) in self._pod_gather_sent:
                return
            members = [m for m in self.pods.get(pid, ())
                       if (layer_id, m) in self._pod_pairs]
            if len(members) < 2:
                return
            codec = ""
            layout = []
            for m in members:
                spec = self._pod_pairs[(layer_id, m)]
                held = self.status.get(m, {}).get(layer_id)
                want = (self.assignment.get(m) or {}).get(layer_id)
                if (held is None or not delivered(held)
                        or not shard_covers(held.shard, spec)):
                    return  # a member's shard is still in flight
                codec = want.codec if want is not None else held.codec
            total = self._pod_wire_total_locked(layer_id, codec)
            if total <= 0:
                return
            for m in members:
                off, size = shard_range(self._pod_pairs[(layer_id, m)],
                                        total)
                layout.append((m, off, size))
            self._pod_gather_sent.add((layer_id, pid))
        layout.sort(key=lambda t: t[1])
        dest = min(members)
        if not self._fabric_ok(layer_id, layout, dest, total):
            log.warn("pod gather not fabric-eligible; members keep "
                     "their shards (watchdog will degrade)",
                     layerID=layer_id, pod=pid)
            return
        trace.count("pod.gathers_dispatched")
        log.info("dispatching pod gather plan", layerID=layer_id,
                 pod=pid, members=members, total_bytes=total,
                 codec=codec or None)
        if not self._dispatch_device_plan(layer_id, dest, layout, total,
                                          pod=members):
            # The documented contract: a failed plan send means the
            # host path must carry the bytes — degrade NOW instead of
            # sitting out the watchdog on a plan nobody received.
            log.error("pod gather plan dispatch failed; degrading to "
                      "host path", layerID=layer_id, pod=pid)
            self._degrade_pod_layer(layer_id, pid)

    def _pod_watchdog(self) -> None:
        """Liveness for the reconstruction phase: a pod pair whose
        shards all landed but whose FULL tree never acks (a member's
        gather failed, a peer shard never published) degrades the whole
        (layer, pod) to host-path delivery after ``POD_GATHER_TIMEOUT``
        — bounded, loud, never a wedge (docs/fabric.md)."""
        while not self._watch_stop.wait(self.POD_WATCH_PERIOD):
            now = time.monotonic()
            stale: Set[Tuple[LayerID, int]] = set()
            with self._lock:
                for key, t0 in list(self._pod_shard_acked.items()):
                    if now - t0 < self.POD_GATHER_TIMEOUT:
                        continue
                    lid, dest = key
                    pid = self._pod_of.get(dest)
                    if pid is not None:
                        stale.add((lid, pid))
            for lid, pid in sorted(stale):
                log.error("pod gather timed out; degrading to host path",
                          layerID=lid, pod=pid)
                trace.count("pod.gather_degraded")
                self._degrade_pod_layer(lid, pid)

    def _degrade_pod_layer(self, layer_id: LayerID, pid: int) -> None:
        """Fall a (layer, pod)'s unfinished pairs back to plain
        full-layer host-path targets: clear the shard specs (the
        re-stamp's widen reconcile re-opens the members' partials, so
        the re-plan ships only the missing ranges) and forget the pod
        records.  Pairs whose tree already materialized keep it.  The
        pod stops pod-planning for the rest of the run (a degrade →
        re-shard → degrade loop must not be possible)."""
        widened = []
        with self._lock:
            self._pods_broken.add(pid)
            for m in self.pods.get(pid, ()):
                key = (layer_id, m)
                spec = self._pod_pairs.get(key)
                if spec is None:
                    continue
                self._pod_pairs.pop(key)
                self._pod_shard_acked.pop(key, None)
                row = self.assignment.get(m)
                meta = (row or {}).get(layer_id)
                if meta is None or meta.shard != spec:
                    continue
                held = self.status.get(m, {}).get(layer_id)
                if (held is not None and delivered(held)
                        and not held.shard
                        and codec_accepts(held.codec, meta.codec)):
                    continue  # tree already landed; nothing to redo
                row[layer_id] = dataclasses.replace(meta, shard="")
                widened.append(m)
            self._pod_gather_sent.discard((layer_id, pid))
        self._replicate_pods()
        if widened:
            # The widen must reach the members BEFORE the re-plan's
            # bytes: the stamp reconcile is what re-opens their shard
            # holdings as partials (docs/sharding.md).
            for m in widened:
                self._send_digests_to(m)
            self._drive(self._recover)

    def _pods_member_gone(self, node: NodeID) -> None:
        """A pod member crashed or departed: its pods' unfinished pod
        pairs (every member's) degrade to host-path full delivery, and
        the pod stops pod-planning for the rest of the run — the
        survivors must never wait on a gather contribution that can no
        longer arrive."""
        pid = self._pod_of.get(node)
        if pid is None:
            return
        with self._lock:
            fresh = pid not in self._pods_broken
            if fresh:
                self._pods_broken.add(pid)
            lids = sorted({lid for (lid, d) in self._pod_pairs
                           if self._pod_of.get(d) == pid})
            # The departed seat's own pairs drop outright (its
            # assignment row is going away with it).
            for lid in lids:
                self._pod_pairs.pop((lid, node), None)
                self._pod_shard_acked.pop((lid, node), None)
        if fresh:
            trace.count("pod.pods_broken")
            self._replicate_pods()
        if not lids:
            return
        # Degrade EVERY remaining pair — even for an already-broken pod
        # (a second member dying after a single-layer timeout degrade
        # still strands the other layers' gathers).
        log.warn("pod member gone; degrading its pod to host path",
                 node=node, pod=pid, layers=lids)
        for lid in lids:
            self._degrade_pod_layer(lid, pid)

    # ------------------------------------------------ pod-plane failover

    def _pods_json(self) -> dict:
        return {"Table": {str(p): list(ms)
                          for p, ms in sorted(self.pods.items())},
                "Broken": sorted(self._pods_broken)}

    def _snapshot_extra_locked(self) -> dict:
        extra = dict(super()._snapshot_extra_locked())
        if self.pods:
            extra["Pods"] = self._pods_json()
        return extra

    def _replicate_pods(self) -> None:
        """Replicate the LIVE pod membership — full table + broken set,
        REPLACE like the group table (docs/failover.md).  Without it a
        standby promoted after a pod break would re-slice pod pairs for
        a pod that already degraded, resurrecting a gather whose
        contributions can never arrive."""
        with self._lock:
            if not self.pods:
                return
            pj = self._pods_json()
        self._replicate("pods", Pods=pj)

    def adopt_shadow(self, shadow: dict, dead_leader=None) -> None:
        super().adopt_shadow(shadow, dead_leader)
        pods = shadow.get("pods") or {}
        broken = {int(p) for p in (pods.get("Broken") or ())}
        with self._lock:
            for p, ms in (pods.get("Table") or {}).items():
                pid = int(p)
                members = sorted(int(m) for m in ms)
                if pid not in self.pods:
                    self.pods[pid] = members
                    for m in members:
                        self._pod_of.setdefault(m, pid)
            self._pods_broken |= broken
            # A pod that BROKE before the takeover degraded at the dead
            # leader, but the widened goal may not have reached this
            # shadow: any leftover 1/R@k pod slice of a broken pod in
            # the adopted assignment is a dead byte space — widen it
            # back to the plain full target, exactly like the degrade
            # did (docs/fabric.md).  Completed trees satisfy the plain
            # want too, so widening them is harmless.
            widened = []
            for pid in sorted(broken):
                for m in self.pods.get(pid) or ():
                    row = self.assignment.get(m)
                    if not row:
                        continue
                    for lid, meta in list(row.items()):
                        s = meta.shard or ""
                        if (s.startswith("1/") and "@" in s
                                and not meta.version):
                            row[lid] = dataclasses.replace(meta, shard="")
                            widened.append(m)
        if widened:
            log.warn("adopted a broken pod's leftover shard slices; "
                     "widened to host-path full targets",
                     pods=sorted(broken), members=sorted(set(widened)))
            # The widen reconcile stamp re-opens the members' shard
            # holdings as partials (docs/sharding.md).
            for m in sorted(set(widened)):
                self._send_digests_to(m)

    def assign_jobs(self) -> Tuple[int, FlowJobsMap, FlowJobsMap]:
        """Split off self-jobs (dest already holds the layer at its own
        client), then solve the flow problem for the rest
        (node.go:1200-1234).

        Resume extension: when a dest announced checkpointed partial
        coverage for a layer, the solver plans over its *remaining* bytes
        and the resulting jobs are mapped back through the gap list, so a
        resumed transfer re-sends only what's missing."""
        self_jobs: FlowJobsMap = {}
        modified: Assignment = {}
        # (layer, dest) -> uncovered [start, end) ranges, for resumes.
        gaps_by_pair: Dict[Tuple[LayerID, NodeID], list] = {}
        # (layer, dest) -> remaining bytes to plan for.
        remaining_sizes: Dict[Tuple[LayerID, NodeID], int] = {}
        with self._lock:
            # The goal the flow graph plans over: the full assignment in
            # flat mode; the hierarchical leader substitutes group
            # INGRESS demands for grouped members' pairs, so the graph
            # grows with the group count, not the fleet size
            # (docs/hierarchy.md).
            plan_asg = self._plan_assignment_locked()
            # Elastic membership (docs/membership.md): an UNVERIFIED
            # joiner's announced holdings must not be planned as
            # transfer sources (its own demand reduction above/below
            # still reads the full status) — hand the graph a
            # source-filtered view.
            quarantined = self.membership.unverified_sources()
            src_status = ({n: row for n, row in self.status.items()
                           if n not in quarantined}
                          if quarantined else self.status)
            # Size every layer from announced metadata — the leader need not
            # hold a layer to schedule it (its own layers are in status too).
            # CODEC holdings are skipped: their data_size is the ENCODED
            # byte count, not the canonical layer size the raw pairs
            # plan by (codec pairs size via codec_sizes below).
            layer_sizes: Dict[LayerID, int] = {}
            for layer_metas in src_status.values():
                for layer_id, meta in layer_metas.items():
                    if meta.data_size > 0 and not getattr(
                            meta, "codec", ""):
                        layer_sizes[layer_id] = max(
                            layer_sizes.get(layer_id, 0), meta.data_size)
            # Wire-codec planning inputs (docs/codec.md): exact encoded
            # sizes per chosen (layer, codec) — the demand-side
            # "effective capacity = bandwidth x ratio" formulation —
            # and each node's encode capability for arc admissibility.
            codec_sizes: Dict[Tuple[LayerID, str], int] = {}
            base_holders: Dict[str, frozenset] = {}
            if self.codecs is not None:
                for dest_l, lids_l in plan_asg.items():
                    for lid_l, meta_l in lids_l.items():
                        if meta_l.codec:
                            # Data-dependent forms (entropy, delta) size
                            # by their one cached encode; model-derivable
                            # forms straight from the layout.
                            n = self.codecs.ensure_sized(
                                lid_l, self.layers.get(lid_l),
                                meta_l.codec)
                            if n is not None:
                                codec_sizes[(lid_l, meta_l.codec)] = n
                        if lid_l not in layer_sizes:
                            # Only codec holders announced (a re-seed
                            # cluster): the canonical size derives from
                            # the model layout.
                            n = self.codecs.decoded_nbytes(lid_l)
                            if n:
                                layer_sizes[lid_l] = n
                # Delta arc admissibility (sched/flow._arc_ok): a
                # delta:<base> pair may only route through senders that
                # PROVABLY hold the base — the ContentIndex holders plus
                # this leader when it pinned the base from its own store.
                bases = {delta_base_digest(m.codec)
                         for lids_l in plan_asg.values()
                         for m in lids_l.values() if m.codec}
                bases.discard("")
                for b in bases:
                    hs = {n for n, _ in self.content.holders(b)}
                    if b in self._delta_base_src:
                        hs.add(self.node.my_id)
                    base_holders[b] = frozenset(hs)
            node_codecs = {n: frozenset(s)
                           for n, s in self.node_codecs.items()}
            for dest, layer_ids in plan_asg.items():
                for layer_id, meta in layer_ids.items():
                    if layer_id not in layer_sizes:
                        log.error("no announced size for layer", layerID=layer_id)
                        continue
                    if (layer_id, dest) in self._salvaging:
                        # A crashed source's uncovered ranges are being
                        # re-fetched via the NACK retransmit plane
                        # (crash() below); a whole-layer re-plan here
                        # would defeat the point of range salvage.
                        continue
                    if self._content_skip_locked(dest, layer_id):
                        # Content-addressed delta (docs/service.md): the
                        # dest holds these exact bytes under another
                        # layer id; its digest-stamp resolve acks the
                        # pair with zero wire bytes.
                        continue
                    held = self.status.get(dest, {}).get(layer_id)
                    if (held is not None
                            and shard_covers(held.shard, meta.shard)
                            and codec_accepts(held.codec, meta.codec)):
                        # Already in RAM/HBM (at covering shard, in an
                        # acceptable codec form — a quantized holding
                        # never stands in for a raw target):
                        # satisfaction counts it as-is — a self-job would
                        # re-send the layer to itself for nothing.
                        # DISK/CLIENT copies DO need the self-fetch
                        # (delivery means in-memory, node.go:435-446;
                        # self-jobs at :1205-1217).
                        if not delivered(held):
                            self_jobs.setdefault(dest, []).append(
                                FlowJob(dest, layer_id,
                                        layer_sizes[layer_id], 0, dest)
                            )
                        continue
                    info = self.partial_status.get(dest, {}).get(layer_id)
                    if info:
                        covered = [(int(s), int(e)) for s, e in info["Covered"]]
                        # Sharded targets resume within their shard's
                        # range: only the SHARD's uncovered bytes plan
                        # (docs/sharding.md) — coverage outside it is
                        # irrelevant to this target.
                        s0, s_sz = shard_range(meta.shard,
                                               int(info["Total"]))
                        gaps = intervals.uncovered(covered, s0, s0 + s_sz)
                        remaining = intervals.covered(gaps)
                        if remaining <= 0:
                            continue  # fully covered; receiver will re-ack
                        gaps_by_pair[(layer_id, dest)] = gaps
                        remaining_sizes[(layer_id, dest)] = remaining
                        log.info("resuming partial layer", layer=layer_id,
                                 dest=dest, remaining=remaining,
                                 total=info["Total"], shard=meta.shard)
                    modified.setdefault(dest, {})[layer_id] = meta
            if not modified:
                log.info("No jobs to assign other than self-assignment")
                return 0, self_jobs, {}
            t0 = time.monotonic()
            # Multi-job service (docs/service.md): when admitted jobs
            # claim pairs, ALL demands — base run + every active job —
            # solve as one shared-capacity flow problem per priority
            # tier (sched.flow.solve_joint); higher tiers consume link
            # budget first (preemption at the re-plan), equal tiers
            # fair-share one graph.  With no jobs active this is the
            # single-graph path, byte-identical to the pre-service
            # planner.
            by_tier: Dict[Tuple[int, str], Assignment] = {}
            tagged = False
            for dest, lids in modified.items():
                for layer_id, meta in lids.items():
                    owner = self.jobs.owner_of(dest, layer_id)
                    key = owner if owner is not None else (0, "")
                    tagged = tagged or owner is not None
                    by_tier.setdefault(key, {}).setdefault(
                        dest, {})[layer_id] = meta
            # Every solve is a new plan generation: commands dispatched
            # below carry it, and any revoke issued against an EARLIER
            # generation can no longer eat them (docs/service.md).
            self._plan_gen += 1
            self._replicate("plan_gen", Gen=self._plan_gen)
            # Autonomy link demotions (docs/autonomy.md): straggling
            # links price at their measured rate instead of infinity.
            demotions = dict(self._link_demotions)
            if not tagged:
                graph = make_flow_graph(
                    modified, src_status, layer_sizes,
                    self.node_network_bw,
                    remaining=remaining_sizes, topology=self.topology,
                    codec_sizes=codec_sizes, node_codecs=node_codecs,
                    base_holders=base_holders,
                    link_demotions=demotions,
                )
                t, jobs = graph.get_job_assignment()
            else:
                demands = [
                    (prio, jid, asg,
                     self._job_avoid_locked(jid, asg) if jid else set())
                    for (prio, jid), asg in sorted(by_tier.items())]
                t_by_prio, jobs = solve_joint(
                    demands, src_status, layer_sizes,
                    self.node_network_bw, remaining=remaining_sizes,
                    topology=self.topology,
                    graph_factory=make_flow_graph,
                    codec_sizes=codec_sizes, node_codecs=node_codecs,
                    base_holders=base_holders,
                    link_demotions=demotions)
                t = max(t_by_prio.values(), default=0)
                # Per-job pacing: each send's rate budget comes from its
                # OWN tier's min time (a preempting tier must not be
                # slowed to the laggard tier's horizon).
                self._tier_time = {d[1]: t_by_prio.get(d[0], t)
                                   for d in demands}
        if gaps_by_pair:
            jobs = self._remap_resumed_jobs(jobs, gaps_by_pair)
        solve_ms = round((time.monotonic() - t0) * 1000, 3)
        with self._lock:
            if not self.predicted_ttd_ms and t > 0:
                self.predicted_ttd_ms = t
                self.solve_ms = solve_ms
        log.info(
            "Job assignment completed",
            computation_ms=solve_ms,
            predicted_s=round(t / 1000.0, 6),
        )
        return t, self_jobs, jobs

    def _preempt_revoke(self, job: Job) -> None:
        """Mode-3 preemption revoke (docs/service.md): the new job's
        tier reclaims budget at the re-plan, but commands DISPATCHED
        under the old solve are already queued at their senders —
        without a revoke, a high-priority swap job stalls behind
        in-flight bulk traffic the solver thinks it preempted.  Revoke
        every LOWER-tier job's dispatched-but-undelivered pairs at
        their senders; the re-plan that follows re-dispatches them at
        the demoted budget."""
        if job.state != "active":
            return
        targets: Dict[NodeID, Dict[str, Set[Tuple[NodeID, LayerID]]]] = {}
        with self._lock:
            # The generation being revoked: commands from the re-plan
            # that follows carry a HIGHER one, so a slow sender applying
            # this revoke late cannot eat the re-dispatched command for
            # the same (job, dest, layer) — the wrong-eat race
            # (docs/service.md).
            gen = self._plan_gen
            for sender, job_list in self._live_jobs.items():
                for fj in job_list:
                    if not fj.job_id or fj.job_id == job.job_id:
                        continue
                    other = self.jobs.get(fj.job_id)
                    if (other is None or other.state != "active"
                            or other.priority >= job.priority):
                        continue
                    held = self.status.get(fj.dest_id, {}).get(fj.layer_id)
                    want = (self.assignment.get(fj.dest_id)
                            or {}).get(fj.layer_id)
                    if (held is not None and want is not None
                            and satisfies(held, want)):
                        continue  # already landed: nothing to revoke
                    targets.setdefault(sender, {}).setdefault(
                        fj.job_id, set()).add((fj.dest_id, fj.layer_id))
        for sender, by_job in sorted(targets.items()):
            for jid, pairs in sorted(by_job.items()):
                trace.count("jobs.revokes_sent")
                log.info("revoking demoted tier's queued sends",
                         sender=sender, job=jid, pairs=sorted(pairs),
                         preempting=job.job_id)
                if sender == self.node.my_id:
                    # The leader's own queue honors the registry
                    # directly — no wire round-trip to itself.
                    self.revokes.add(jid, sorted(pairs), gen=gen)
                    continue
                try:
                    self.node.transport.send(
                        sender, JobRevokeMsg(self.node.my_id, jid,
                                             sorted(pairs),
                                             epoch=self.epoch, gen=gen))
                except (OSError, KeyError) as e:
                    log.warn("revoke send failed (the demoted sends "
                             "simply run)", sender=sender, err=repr(e))

    # ---------------------------------------- autonomy actuators (mode 3)

    def policy_demote_link(self, src: NodeID, dest: NodeID,
                           bps: int) -> None:
        """Install a straggler-link demotion and re-plan around it
        (docs/autonomy.md): the flow solver prices the (src, dest) arc
        at the measured ``bps`` instead of infinity, so every pair that
        CAN route elsewhere does, and pairs with no alternative keep
        the slow path at an honest rate budget.  Called by the policy
        engine (never under its lock)."""
        with self._lock:
            self._link_demotions[(int(src), int(dest))] = int(bps)
        log.warn("link demoted for planning; re-planning around it",
                 src=src, dest=dest, bps=int(bps))
        trace.count("policy.link_demotions")
        self._drive(self._update_replan)

    def policy_lift_link(self, src: NodeID, dest: NodeID) -> None:
        """Lift a link demotion after a ``link_recovered`` event and
        re-plan at full modeled capacity."""
        with self._lock:
            if self._link_demotions.pop((int(src), int(dest)),
                                        None) is None:
                return
        log.info("link demotion lifted; re-planning", src=src, dest=dest)
        self._drive(self._update_replan)

    def _forget_sender_jobs(self, node: NodeID) -> None:
        """A cleanly-departed seat's dispatched sends are simply
        forgotten (docs/membership.md): unlike ``crash()``, no
        SourceDeadMsg fires and nothing enters range salvage — the
        re-plan the drain finalize runs re-dispatches any pair its
        departure left uncovered from the survivors."""
        with self._lock:
            self._live_jobs.pop(node, None)
            for job_list in self._live_jobs.values():
                job_list[:] = [j for j in job_list
                               if j.dest_id != node]

    def _job_avoid_locked(self, jid: str, asg: Assignment) -> Set[NodeID]:
        """Lock held.  The sender-avoid set for one job's tier: the
        job's explicit ``avoid_sources``, plus — for "repair" jobs —
        every rate-limited MODELED source of a wanted layer whenever an
        unlimited delivered holder also exists (the refill policy: a
        repaired node pulls from the nearest current holder, sparing
        the busy origin seeder).  Advisory: solve_joint falls back to
        all sources, loudly, if avoidance starves the tier."""
        job = self.jobs.get(jid)
        if job is None:
            return set()
        avoid = set(job.avoid_sources)
        if job.kind not in ("repair", "join"):
            return avoid
        # "join" refills (docs/membership.md) extend the repair
        # politeness with ORIGIN avoidance: a late joiner pulls from
        # current peer holders, touching the origin seeder (the
        # leader's seat) only for bytes no peer holds — admission cost
        # must not scale with origin bandwidth.
        origin: Set[NodeID] = ({self.node.my_id} if job.kind == "join"
                               else set())
        for lids in asg.values():
            for lid in lids:
                slow: Set[NodeID] = set()
                free: Set[NodeID] = set()
                for n, row in self.status.items():
                    meta = row.get(lid)
                    if meta is None or not delivered(meta):
                        continue
                    (slow if meta.limit_rate else free).add(n)
                if free - avoid - origin:
                    avoid |= slow | origin
        return avoid

    @staticmethod
    def _remap_resumed_jobs(
        jobs: FlowJobsMap, gaps_by_pair: Dict[Tuple[LayerID, NodeID], list]
    ) -> FlowJobsMap:
        """Translate jobs planned over remaining-space into absolute byte
        ranges (one job may split across several gaps)."""
        out: FlowJobsMap = {}
        for sender, job_list in jobs.items():
            for job in job_list:
                gaps = gaps_by_pair.get((job.layer_id, job.dest_id))
                if gaps is None:
                    out.setdefault(sender, []).append(job)
                    continue
                for off, size in map_through_gaps(gaps, job.offset, job.data_size):
                    out.setdefault(sender, []).append(
                        FlowJob(sender, job.layer_id, size, off,
                                job.dest_id, job_id=job.job_id)
                    )
        return out

    # Max plans per batch hint: each batched gather holds K layers'
    # tiles in flight at once, so K bounds the dest's peak HBM.
    PLAN_BATCH_MAX = 4

    def _split_fabric_jobs(self, jobs: FlowJobsMap) -> FlowJobsMap:
        """Dispatch every fabric-eligible (layer, dest) job group as ONE
        device plan — the plan's multi-sender byte-range split executes as
        device traffic (seeders upload their ranges, the dest's sharded
        ingest gathers them over ICI) — and return the jobs the fabric
        can't carry for the host-path dispatch below.  A resumed dest's
        plan covers only its gaps; the dest seeds its ingest from the
        checkpointed bytes it already holds.

        Plan batching: eligible plans with the same dest and total size
        (a model's equal-size layers — the dest's ingest tiling is a
        function of the total alone) are stamped with one batch id, so
        the dest finishes the whole group as a single batched gather
        (``parallel.ingest.finalize_many``) instead of N serial
        collectives — the per-plan dispatch latency that dominated the
        physical fabric row amortizes over the batch."""
        if self.fabric is None or self.placement is None:
            return jobs
        groups: Dict[Tuple[LayerID, NodeID], List[FlowJob]] = {}
        for job_list in jobs.values():
            for job in job_list:
                groups.setdefault((job.layer_id, job.dest_id), []).append(job)
        host_jobs: FlowJobsMap = {}
        # First pass: decide eligibility per (layer, dest) group so the
        # batch sizes stamped below are exact (a plan counted into a
        # batch but sent host-path would strand the dest's batch wait).
        eligible: List[Tuple[LayerID, NodeID, list, int]] = []
        for (layer_id, dest), group in sorted(groups.items()):
            layout = sorted(
                ((j.sender_id, j.offset, j.data_size) for j in group),
                key=lambda t: t[1],
            )
            with self._lock:
                total = self._layer_size_locked(layer_id)
                want = (self.assignment.get(dest) or {}).get(layer_id)
            if want is not None and (want.shard or want.codec):
                # Sharded and wire-codec targets ride the host path:
                # the fabric plane's ingest/collectives materialize
                # WHOLE canonical layers only (docs/sharding.md,
                # docs/codec.md, honest limits).
                for j in group:
                    host_jobs.setdefault(j.sender_id, []).append(j)
                continue
            if total > 0 and self._fabric_ok(layer_id, layout, dest, total):
                eligible.append((layer_id, dest, layout, total))
            else:
                for j in group:
                    host_jobs.setdefault(j.sender_id, []).append(j)
        # Same-dest, same-size plans batch together (bounded).
        batches: Dict[Tuple[NodeID, int], List[int]] = {}
        for i, (_, dest, _, total) in enumerate(eligible):
            batches.setdefault((dest, total), []).append(i)
        batch_of: Dict[int, Tuple[str, int]] = {}
        for (dest, total), idxs in sorted(batches.items()):
            for start in range(0, len(idxs), self.PLAN_BATCH_MAX):
                chunk = idxs[start : start + self.PLAN_BATCH_MAX]
                if len(chunk) < 2:
                    continue  # nothing to amortize
                bid = f"b{dest}.{total}.{next(self._batch_seq)}"
                for i in chunk:
                    batch_of[i] = (bid, len(chunk))
        for i, (layer_id, dest, layout, total) in enumerate(eligible):
            bid, bn = batch_of.get(i, ("", 1))
            if not self._dispatch_device_plan(layer_id, dest, layout, total,
                                              batch_id=bid, batch_n=bn):
                # A mid-batch dispatch failure leaves earlier members
                # stamped with the full batch_n; the dest's bounded
                # FABRIC_BATCH_WAIT flush processes the present members
                # — a one-off, bounded delay on a rare failure path
                # (re-stamping already-sent plans would need a second
                # protocol round for a case the flush already covers).
                for j in groups[(layer_id, dest)]:
                    host_jobs.setdefault(j.sender_id, []).append(j)
        return host_jobs

    def _dispatch(self, min_time_ms: int, self_jobs: FlowJobsMap,
                  jobs: FlowJobsMap) -> None:
        """Send every flow job as a rate-budgeted command
        (node.go:1237-1288; the budget comes from the solver's
        millisecond-granular min time, not the reference's integer
        seconds).  Fabric-eligible job groups ride the device plane
        instead."""
        jobs = self._split_fabric_jobs(jobs)
        with self._lock:
            # The generation these commands belong to: a revoke fenced
            # at an older generation must not eat them (docs/service.md).
            gen = self._plan_gen
        for dest, job_list in self_jobs.items():
            for job in job_list:
                with self._lock:
                    rate = self.status.get(job.sender_id, {}).get(
                        job.layer_id, LayerMeta()
                    ).limit_rate
                self.node.transport.send(
                    job.sender_id,
                    FlowRetransmitMsg(
                        self.node.my_id, job.layer_id, job.sender_id,
                        job.data_size, job.offset, rate, epoch=self.epoch,
                        gen=gen,
                    ),
                )
        with self._lock:
            tier_time = dict(self._tier_time)
            # Wire-codec commands (docs/codec.md): each job's byte range
            # indexes the pair's chosen form — the command must say so.
            pair_codec = {
                (dest, lid): meta.codec
                for dest, lids in self.assignment.items()
                for lid, meta in lids.items() if meta.codec}
        for sender, job_list in jobs.items():
            for job in job_list:
                dest = job.dest_id
                t_job = (tier_time.get(job.job_id, min_time_ms)
                         if job.job_id else min_time_ms)
                rate = rate_for(job.data_size, t_job or min_time_ms)
                codec = pair_codec.get((dest, job.layer_id), "")
                # Pair-lifecycle span (docs/observability.md): the pair
                # entered the solved plan NOW — planned→dispatched is
                # then the sender-side queueing the critical-path walk
                # attributes.  One event per flow job; a multi-sender
                # split's last command wins (the walk reads one planned
                # edge per pair).
                telemetry.span_event(
                    telemetry.span_id(dest, job.layer_id), "planned",
                    node=self.node.my_id, src=sender, dest=dest,
                    layer=job.layer_id, job=job.job_id, codec=codec,
                    bytes=job.data_size)
                log.debug(
                    "dispatching a job",
                    layer=job.layer_id, sender=sender, rate_mibps=rate >> 20,
                    job=job.job_id or None, codec=codec or None,
                )
                try:
                    self.node.transport.send(
                        sender,
                        FlowRetransmitMsg(
                            self.node.my_id, job.layer_id, dest,
                            job.data_size, job.offset, rate,
                            epoch=self.epoch, job_id=job.job_id,
                            codec=codec, gen=gen,
                        ),
                    )
                except (OSError, KeyError) as e:
                    log.error("couldn't dispatch job", layerID=job.layer_id, err=repr(e))
                    continue
                # Salvage index: a dispatched job is live until its
                # (layer, dest) delivers — crash(sender) consults this
                # to re-plan only the uncovered byte ranges.  The
                # dispatch time feeds health scoring: a pair is only
                # straggler-judged once it has been in flight for a
                # full metrics interval.
                with self._lock:
                    self._live_jobs.setdefault(sender, []).append(job)
                    self.__dict__.setdefault(
                        "_pair_dispatch_mono", {})[
                        (dest, job.layer_id)] = time.monotonic()

    def _modeled_link_rate(self, src: NodeID, dest: NodeID) -> int:
        """Mode 3's health-scoring link model (docs/observability.md):
        the solver's own inputs — min(src NIC, dest NIC, the serving
        holder's modeled source rate) — but ONLY while a dispatched
        (src→dest) flow job's pair is still unsatisfied AND has been in
        flight for at least one metrics interval.  Both gates exist for
        honesty: a transfer that completed within one interval averages
        far below the modeled rate over that interval, and a report
        racing a just-dispatched (or just-finishing) burst would
        otherwise mis-read a healthy link as a straggler."""
        interval = telemetry.metrics_interval() or 2.0
        with self._lock:
            dispatch_t = getattr(self, "_pair_dispatch_mono", {})
            now = time.monotonic()
            live = None
            for fj in self._live_jobs.get(src) or ():
                if fj.dest_id != dest:
                    continue
                want = (self.assignment.get(dest) or {}).get(fj.layer_id)
                if want is None:
                    continue
                t0 = dispatch_t.get((dest, fj.layer_id))
                if t0 is None or now - t0 < interval:
                    continue  # too young to judge over a full interval
                held = self.status.get(dest, {}).get(fj.layer_id)
                if held is None or not satisfies(held, want):
                    live = fj
                    break
            if live is None:
                return 0
            bw = getattr(self, "node_network_bw", None) or {}
            cands = [bw.get(src), bw.get(dest)]
            meta = self.status.get(src, {}).get(live.layer_id)
            if meta is not None and meta.limit_rate:
                cands.append(meta.limit_rate)
        rates = [int(r) for r in cands if r]
        return min(rates) if rates else 0

    def _health_expected_srcs(self, dest: NodeID):
        """Mode 3: every sender with a dispatched live job to ``dest``
        (the age/satisfaction gates stay in ``_modeled_link_rate`` —
        an expected src whose pair is too young or already satisfied
        scores as unmodeled and is skipped)."""
        with self._lock:
            return sorted({s for s, jobs in self._live_jobs.items()
                           if any(fj.dest_id == dest for fj in jobs)})

    def crash(self, node_id: NodeID) -> None:
        """Range-level salvage (docs/failover.md): a dead SOURCE's
        in-flight jobs don't re-send their whole layers — each affected
        dest is told to NACK its uncovered byte ranges of the layer to a
        surviving holder (``SourceDeadMsg`` → ``LayerNackMsg`` → the
        PR-4 byte-range retransmitter), so recovery costs exactly the
        dead source's unsent bytes.  Pairs with no surviving holder fall
        through to the base whole-layer re-plan."""
        salvage = []
        with self._lock:
            jobs = self._live_jobs.pop(node_id, [])
            # Jobs sent TO the dead node die with its assignment.
            for job_list in self._live_jobs.values():
                job_list[:] = [j for j in job_list
                               if j.dest_id != node_id]
            for job in jobs:
                dest, lid = job.dest_id, job.layer_id
                if dest == node_id or dest == self.node.my_id:
                    continue
                want = (self.assignment.get(dest) or {}).get(lid)
                held = self.status.get(dest, {}).get(lid)
                if (held is not None and delivered(held)
                        and (want is None
                             or (shard_covers(held.shard, want.shard)
                                 and codec_accepts(held.codec,
                                                   want.codec)))):
                    continue  # already landed whole (target shard covered)
                if (lid, dest) in self._salvaging:
                    continue
                # The salvage source must really hold the bytes being
                # re-requested: the target's shard for sharded pairs,
                # the whole layer otherwise — and for a wire-codec pair,
                # the exact encoded form (or raw + encode capability).
                alt = pick_salvage_source(
                    self.status, lid,
                    exclude={node_id, dest}
                    | self.membership.unverified_sources(),
                    need_shard=want.shard if want is not None else "",
                    need_codec=want.codec if want is not None else "",
                    encoders=frozenset(
                        n for n, s in self.node_codecs.items()
                        if want is not None
                        and codec_capability(want.codec) in s
                        and (not delta_base_digest(want.codec)
                             or self.content.node_has(
                                 n, delta_base_digest(want.codec))
                             or (n == self.node.my_id
                                 and delta_base_digest(want.codec)
                                 in self._delta_base_src))))
                if alt is None:
                    continue  # no surviving holder: base re-plan covers it
                self._salvaging.add((lid, dest))
                salvage.append((dest, lid, alt))
        for dest, lid, alt in salvage:
            trace.count("failover.range_salvage")
            log.warn("source crashed mid-transfer; salvaging the dest's "
                     "uncovered ranges via NACK retransmit",
                     dead=node_id, layerID=lid, dest=dest, alt=alt)
            try:
                self.node.add_node(dest)
                self.node.transport.send(
                    dest, SourceDeadMsg(self.node.my_id, lid, node_id,
                                        alt, epoch=self.epoch))
            except (OSError, KeyError) as e:
                with self._lock:
                    self._salvaging.discard((lid, dest))
                log.error("source-dead notice undeliverable; falling "
                          "back to whole-layer re-plan", dest=dest,
                          layerID=lid, err=repr(e))
        super().crash(node_id)

    def handle_flow_retransmit(self, msg: FlowRetransmitMsg) -> None:
        """The leader can be a sender in the plan too (node.go:1168-1187)."""
        t0 = time.monotonic()
        log.info(
            "start sending layer",
            layer=msg.layer_id, dest=msg.dest_id, size_mb=msg.data_size >> 20,
            expected_mibps=msg.rate >> 20,
        )
        handle_flow_retransmit(
            self.node, self.layers, self._lock,
            lambda lid, dest: fetch_from_client(self.node, lid, dest), msg,
            revokes=self.revokes, codecs=self.codecs,
        )
        dur = time.monotonic() - t0
        log.info(
            "finished sending layer",
            layer=msg.layer_id, dest=msg.dest_id,
            send_dur_ms=round(dur * 1000, 3),
            throughput_mibps=round(msg.data_size / max(dur, 1e-9) / (1 << 20), 2),
        )


class HierarchicalFlowLeaderNode(FlowRetransmitLeaderNode):
    """Mode 3 scaled out: two-level control for fleet-size fan-out
    (docs/hierarchy.md).

    The fleet partitions into GROUPS, each owned by a sub-leader
    (``runtime/hierarchy.SubLeaderController`` on an ordinary receiver
    seat).  This root keeps the FULL member goal — completion,
    satisfaction, the job plane, and replication all still speak
    (member, layer) pairs — but every hot path is reduced to groups:

    - **Planning**: ``_plan_assignment_locked`` substitutes one group
      INGRESS demand (deliver the layer to the sub-leader) for all of a
      group's member pairs, so the flow graph — and the solve wall —
      grows with the group count, not the fleet size.  Sub-leaders fan
      layers out intra-group and members ack to THEM.
    - **Upward traffic**: members announce / ack / heartbeat / report
      metrics to their sub-leader; the root handles cumulative
      ``GroupStatusMsg`` aggregates — O(groups) messages per event
      where the flat plane handled O(nodes).
    - **Failover**: the group table rides the epoch-fenced snapshot +
      ``groups`` delta, so a promoted standby keeps the hierarchy; a
      DEAD sub-leader dissolves its group back to flat delivery
      (members are told to re-point at the root and re-announce).

    Qualified targets compose (docs/hierarchy.md): a group whose live
    members all want the SAME ``(shard, codec, version)`` form of a
    layer plans ONE synthetic group ingress carrying that form — the
    encoded / shard bytes cross the fleet fabric once, and the
    sub-leader chains them member-to-member.  Mixed forms within a
    group, and forms the ingress seat's own target conflicts with,
    still plan flat (honest limit); standbys must be ungrouped seats."""

    MODE = 3

    def __init__(self, node, layers, assignment, node_network_bw,
                 groups=None, **kw):
        self.groups: Dict[int, dict] = {}
        self._dissolved: Set[int] = set()
        self._member_group: Dict[NodeID, int] = {}
        self._group_of_subleader: Dict[NodeID, int] = {}
        self._dead_members: Set[NodeID] = set()
        for gid, rec in (groups or {}).items():
            gid = int(gid)
            sub = int(rec["leader"] if "leader" in rec else rec["Leader"])
            members = sorted(int(m) for m in (
                rec.get("members") or rec.get("Members") or []))
            dissolved = bool(rec.get("dissolved") or rec.get("Dissolved"))
            self.groups[gid] = {"leader": sub, "members": members}
            self._group_of_subleader[sub] = gid
            if dissolved:
                self._dissolved.add(gid)
                continue
            for m in members:
                if m != sub:
                    self._member_group[m] = gid
        for s in kw.get("standbys") or ():
            if int(s) in self._member_group or int(s) in \
                    self._group_of_subleader:
                raise ValueError(
                    f"standby {s} is a grouped seat: standbys must be "
                    "ungrouped (a promoted sub-leader's member-facing "
                    "handlers would collide with the root's)")
        super().__init__(node, layers, assignment, node_network_bw, **kw)
        # Grouped members are their sub-leader's to monitor: the ctor
        # seeded leases for every assignee, but a member never
        # heartbeats the root, and an expiring root-side lease would
        # falsely kill it.
        for m in self._member_group:
            self.detector.forget(m)

    # --------------------------------------------------------- wiring

    def _register_handlers(self) -> None:
        super()._register_handlers()
        reg = (self.loop.register_keep if self._shared_loop
               else self.loop.register)
        reg(GroupStatusMsg, self.handle_group_status)

    def _grouped(self, node_id: NodeID) -> bool:
        return node_id in self._member_group

    def _ack_liveness(self, src_id: NodeID) -> bool:
        if self._grouped(src_id):
            # Member liveness belongs to the sub-leader's detector; an
            # aggregated ack must neither create a root-side lease nor
            # resurrect a reported-dead member.
            return src_id not in self._dead_members
        return super()._ack_liveness(src_id)

    def _touch_liveness(self, src_id: NodeID) -> None:
        if not self._grouped(src_id):
            super()._touch_liveness(src_id)

    def _await_announce_set_locked(self) -> Set[NodeID]:
        """Grouped members stay IN the await set: their announces
        arrive as sub-leader folds (which create their status rows),
        and the start-time route/codec decisions need every member's
        form and capability picture before the first stamp latches
        (docs/hierarchy.md).  A member the sub reported dead was
        crash()-dropped from the assignment, so it no longer gates."""
        return super()._await_announce_set_locked()

    def _lease_recipients_locked(self) -> Set[NodeID]:
        return (super()._lease_recipients_locked()
                - set(self._member_group))

    # ------------------------------------------------------- planning

    @staticmethod
    def _form(meta: LayerMeta) -> Tuple[str, str, str]:
        return (meta.shard or "", meta.codec or "", meta.version or "")

    def _group_route_locked(self, gid: int, lid: LayerID):
        """How (group, layer) reaches the group, lock held.  Returns
        ``None`` (every member plans FLAT) or ``(kind, ingress_meta,
        form)`` — a member pair routes through the group iff its
        ``(shard, codec, version)`` form equals ``form``:

        - ``("synthetic", meta, form)``: the root emits one ingress
          demand ``meta`` at the sub-leader carrying the group's shared
          form — full-raw when any member wants the plain layer (and
          the sub-leader's own target, if any, is plain too: a
          qualified own pair would collide with the synthetic demand in
          one plan slot), else the ONE qualified form every member
          agrees on (docs/hierarchy.md).
        - ``("own", None, form)``: the sub-leader's OWN pair already
          carries the bytes the group needs — its existing target has
          the same form, or is plain raw while the group wants a
          codec-only form the sub-leader can encode-serve — so the
          root emits nothing extra.

        Mixed member forms route only the plain subset (qualified
        members plan flat, the pre-chain behavior); two or more
        distinct qualified forms plan flat entirely (honest limit)."""
        rec = self.groups[gid]
        sub = rec["leader"]
        own = (self.assignment.get(sub) or {}).get(lid)
        own_plain = own is None or not (own.shard or own.codec
                                        or own.version)
        plain_wanted = False
        qual: Dict[Tuple[str, str, str], LayerMeta] = {}
        for m in rec["members"]:
            if m == sub or m in self._dead_members:
                continue
            meta = (self.assignment.get(m) or {}).get(lid)
            if meta is None:
                continue
            form = self._form(meta)
            if form == ("", "", ""):
                plain_wanted = True
            else:
                qual[form] = meta
        if plain_wanted:
            return (("synthetic", LayerMeta(), ("", "", ""))
                    if own_plain else None)
        if len(qual) != 1:
            return None
        (form, meta), = qual.items()
        if own is None:
            return ("synthetic", dataclasses.replace(meta), form)
        if self._form(own) == form:
            return ("own", None, form)
        if (own_plain and not form[0] and not form[2]
                and codec_capability(form[1])
                in self.node_codecs.get(sub, ())
                and (not delta_base_digest(form[1])
                     or self.content.node_has(
                         sub, delta_base_digest(form[1])))):
            # Raw own ingress; the sub-leader encode-serves the
            # group's codec form from it (docs/codec.md) — a delta
            # form only when the sub PROVABLY holds the base bytes
            # the re-encode reads.
            return ("own", None, form)
        return None

    def _ingress_ok_locked(self, gid: int, lid: LayerID) -> bool:
        """Whether (group, layer) routes through the group at all —
        the route exists.  Lock held."""
        return self._group_route_locked(gid, lid) is not None

    def _plan_assignment_locked(self) -> Assignment:
        """The reduced goal the flow graph sees: grouped members'
        still-missing pairs collapse into one ingress demand per
        (group, layer) — full-raw for plain wants, the group's SHARED
        qualified form when every member agrees on one
        (``_group_route_locked``).  Members whose form differs from the
        routed one, and ungrouped seats, plan flat.  Lock held."""
        out: Assignment = {}
        routes: Dict[Tuple[int, LayerID], Optional[tuple]] = {}
        for dest, lids in self.assignment.items():
            gid = self._member_group.get(dest)
            if gid is None:
                row = out.setdefault(dest, {})
                for lid, meta in lids.items():
                    row[lid] = meta
                continue
            ingress = self.groups[gid]["leader"]
            for lid, meta in lids.items():
                key = (gid, lid)
                if key not in routes:
                    routes[key] = self._group_route_locked(gid, lid)
                route = routes[key]
                if route is None or route[2] != self._form(meta):
                    out.setdefault(dest, {})[lid] = meta
                    continue
                held = self.status.get(dest, {}).get(lid)
                if held is not None and satisfies(held, meta):
                    continue  # the member already holds it
                if route[0] == "own":
                    continue  # the sub-leader's own pair is the ingress
                out.setdefault(ingress, {}).setdefault(lid, route[1])
        return out

    def _group_ingress_row_locked(self, gid: int
                                  ) -> Dict[LayerID, LayerMeta]:
        """The SYNTHETIC ingress demands currently routed through
        ``gid``'s sub-leader (lock held), derived from the live routing
        decision — digest stamps read this BEFORE the first planning
        pass, so it cannot be a planning-time cache."""
        rows: Dict[LayerID, LayerMeta] = {}
        rec = self.groups[gid]
        for m in rec["members"]:
            if m == rec["leader"] or m in self._dead_members:
                continue
            for lid in (self.assignment.get(m) or {}):
                if lid in rows:
                    continue
                route = self._group_route_locked(gid, lid)
                if route is not None and route[0] == "synthetic":
                    rows[lid] = route[1]
        return rows

    def _digest_row_locked(self, dest: NodeID) -> dict:
        row = dict(super()._digest_row_locked(dest))
        gid = self._group_of_subleader.get(dest)
        if gid is not None and gid not in self._dissolved:
            for lid, meta in self._group_ingress_row_locked(gid).items():
                row.setdefault(lid, meta)
        return row

    def _send_digests_to(self, dest: NodeID) -> None:
        super()._send_digests_to(dest)
        # A grouped dest's pairs may route through its group: the
        # SUB-LEADER carries the ingress and needs the same stamp
        # (digest / version / codec — _digest_row_locked merges the
        # ingress row) BEFORE the bytes, or a versioned wave's ingress
        # would land unversioned and never serve the members
        # (docs/swap.md).  Idempotent, like every stamp re-send.
        with self._lock:
            gid = self._member_group.get(dest)
            sub = (self.groups[gid]["leader"]
                   if gid is not None and gid not in self._dissolved
                   else None)
        if sub is not None and sub != dest:
            super()._send_digests_to(sub)

    def _digest_recipients_locked(self) -> list:
        dests = super()._digest_recipients_locked()
        have = set(dests)
        for gid, rec in sorted(self.groups.items()):
            if gid in self._dissolved or rec["leader"] in have:
                continue
            if self._group_ingress_row_locked(gid):
                have.add(rec["leader"])
                dests.append(rec["leader"])
        return dests

    def send_layers(self) -> None:
        super().send_layers()
        self._send_group_plans()

    def _send_group_plans(self) -> None:
        """Hand every live sub-leader its members' current targets.
        Sent with every (re-)plan — idempotent at the sub-leader, and
        its receipt-reply (full cumulative coverage) doubles as the
        reconcile channel after a root takeover."""
        with self._lock:
            plans = []
            for gid, rec in sorted(self.groups.items()):
                if gid in self._dissolved:
                    continue
                targets = {}
                for m in rec["members"]:
                    if m == rec["leader"] or m in self._dead_members:
                        continue
                    # EVERY live member target rides the plan — even
                    # pairs the root plans flat: the sub-leader is the
                    # members' ack funnel, and it can only fold their
                    # coverage for targets it knows about.  Its own
                    # fan-out is gated on what its holdings can
                    # actually serve, so unroutable forms simply wait
                    # for the root's flat delivery.
                    row = dict(self.assignment.get(m) or {})
                    if row:
                        targets[m] = row
                plans.append((gid, rec["leader"], targets))
        for gid, sub, targets in plans:
            try:
                self.node.add_node(sub)
                self.node.transport.send(
                    sub, GroupPlanMsg(self.node.my_id, gid, targets,
                                      epoch=self.epoch))
                trace.count("hier.group_plans_sent")
            except (OSError, KeyError) as e:
                log.error("group plan send failed (next re-plan "
                          "re-sends)", group=gid, sub=sub, err=repr(e))

    # ----------------------------------------------------- aggregates

    def handle_group_status(self, msg: GroupStatusMsg) -> None:
        """One sub-leader aggregate: member announce inventories, member
        deaths, cumulative coverage, batched member telemetry — each
        applied through the SAME machinery the flat plane uses, so jobs,
        content index, replication, and completion are unchanged.

        Sender-gated like the swap fence's foreign-control check
        (docs/swap.md): only the REGISTERED sub-leader of a live group
        may speak for it, and only about its OWN members — any other
        seat could otherwise crash a healthy member or overwrite its
        status row with one message."""
        with self._lock:
            rec = self.groups.get(msg.group_id)
            foreign = (rec is None or rec["leader"] != msg.src_id
                       or msg.group_id in self._dissolved)
            group_members = set(rec["members"]) if rec else set()
        if foreign:
            trace.count("hier.foreign_status_dropped")
            log.warn("group status from a seat that does not own the "
                     "group; dropped", group=msg.group_id,
                     src=msg.src_id)
            return
        self.detector.touch(msg.src_id)
        replan_for = []
        if msg.codecs:
            # Folded member codec capabilities (docs/codec.md), applied
            # through the same grant/revoke discipline as the flat
            # announce path, so the planner can choose quantized
            # transfers for grouped members — the capability half of
            # letting codec-qualified pairs route THROUGH a group.
            # Absorbed BEFORE the announced rows: anything gated on a
            # member's status row existing (the start latch, the first
            # codec stamp) must see the member's capabilities too.
            changed = False
            with self._lock:
                for m, caps in sorted(msg.codecs.items()):
                    m = int(m)
                    if not self._grouped(m) or m not in group_members:
                        continue
                    new_caps = (frozenset(str(c) for c in caps)
                                if caps else None)
                    old_caps = self.node_codecs.get(m)
                    if new_caps:
                        self.node_codecs[m] = new_caps
                    else:
                        self.node_codecs.pop(m, None)
                    changed = changed or new_caps != old_caps
            if changed:
                self._replicate_codecs()
        if msg.announced:
            with self._lock:
                started = self._started
            for m, row in sorted(msg.announced.items()):
                if not self._grouped(m) or m not in group_members:
                    continue  # dissolved meanwhile / not this group's
                              # member: a direct announce supersedes
                self._revive_member(m)
                with self._lock:
                    known = m in self.status
                    self.status[m] = dict(row)
                # A fold IS the member's restart channel: like the flat
                # announce path, a re-announced member stops vouching
                # for its dead incarnation's bytes, and a JOINING
                # member's folded digest inventory is the verification
                # evidence the flat path reads off AnnounceMsg — the
                # same quarantine applies (docs/membership.md): a
                # grouped joiner whose holdings digest-verify becomes a
                # source; one that conflicts stays a dest.
                digs = {int(l): str(dg) for l, dg in
                        ((msg.digests or {}).get(m) or {}).items()}
                if self._verify_member_source(m, digs):
                    self._merge_announced_digests(m, digs)
                    self.content.reset_node(m, digs)
                else:
                    self.content.reset_node(m, {})
                self._replicate("status", Node=m,
                                Layers=layer_ids_to_json(row))
                with self._lock:
                    pending_want = self._join_pending.pop(m, None)
                if pending_want is not None:
                    # A grouped joiner's first announce arrives through
                    # its sub-leader's fold: admit its refill job here,
                    # exactly like the flat announce path
                    # (docs/membership.md).
                    self._admit_join_job(m, pending_want)
                if started and known:
                    replan_for.append(m)
            trace.count("hier.announce_aggregates")
            # The fold IS the members' announce-gate arrival: the start
            # latch waits on their status rows exactly like the flat
            # path waits on direct announces — and like that path, a
            # successful start must drive the first sends (and the
            # already-satisfied check) itself.
            if self._maybe_start():
                self.send_layers()
                self._maybe_finish()
        for m in msg.dead:
            with self._lock:
                fresh = (m in group_members and self._grouped(m)
                         and m not in self._dead_members)
                if fresh:
                    self._dead_members.add(m)
            if fresh:
                log.error("sub-leader reported member dead",
                          member=m, group=msg.group_id)
                trace.count("hier.member_crashes")
                self.crash(m)
        if msg.covered:
            for lid, members in sorted(msg.covered.items()):
                for m in members:
                    if int(m) in group_members:
                        self._apply_member_ack(
                            int(m), int(lid),
                            span=(msg.spans.get(int(lid)) or {}).get(
                                int(m), ""))
        for m, snap in sorted(msg.metrics.items()):
            if int(m) in group_members:
                self._fold_member_metrics(int(m), snap)
        if replan_for:
            # A restarted member re-announced (through the fold): its
            # RAM holdings are gone — re-plan its missing layers like a
            # direct re-announce would.
            log.info("aggregated re-announce; re-planning",
                     members=replan_for)
            self._maybe_finish()
            with self._lock:
                finished = self._startup_sent
            if not finished:
                self._recover()
        self._maybe_finish()

    def _revive_member(self, m: NodeID) -> None:
        """An announce fold naming a written-off member is its restart
        coming back through the sub-leader: restore its dropped pairs
        (pre-startup) exactly like a direct revival announce."""
        with self._lock:
            if m not in self._dead_members:
                return
            self._dead_members.discard(m)
            dropped = self._dropped_assignment.pop(m, None)
            if dropped and not self._startup_sent:
                self._restore_assignment(m, dropped)
        log.warn("dead-reported member announced again; reviving",
                 member=m)
        if dropped:
            self._replicate("revive", Node=m)

    def _apply_member_ack(self, m: NodeID, lid: LayerID,
                          span: str = "") -> None:
        """Apply one aggregated (member, layer) completion.  Reports
        are CUMULATIVE, so already-satisfied pairs short-circuit before
        touching replication or the job plane.  ``span``: the
        sub-leader's advisory fan-out child span id for the pair
        (docs/observability.md) — the synthesized ack carries it so the
        root's ``acked`` event files on the member's own span."""
        with self._lock:
            held = self.status.get(m, {}).get(lid)
            if held is not None and delivered(held):
                return
        self.handle_ack(AckMsg(m, lid, LayerLocation.INMEM, span_id=span))

    def _fold_member_metrics(self, member: NodeID, snap: dict) -> None:
        rec = {"counters": dict(snap.get("Counters") or {}),
               "gauges": dict(snap.get("Gauges") or {}),
               "links": dict(snap.get("Links") or {}),
               "hists": {k: dict(h)
                         for k, h in (snap.get("Hists") or {}).items()},
               "spans": [dict(ev) for ev in snap.get("Spans") or []],
               "t_wall_ms": float(snap.get("T", 0.0)),
               "proc": str(snap.get("Proc", "")),
               "_recv_mono": time.monotonic()}
        with self._lock:
            self.cluster_metrics[member] = rec
        self._replicate("metrics", Node=member, Counters=rec["counters"],
                        Gauges=rec["gauges"], Links=rec["links"],
                        Hists=rec["hists"], Spans=rec["spans"],
                        T=rec["t_wall_ms"], Proc=rec["proc"])
        self._health_observe(member, rec)

    # ------------------------------------------------------- failover

    def _groups_json(self) -> dict:
        return {str(g): {"Leader": rec["leader"],
                         "Members": list(rec["members"]),
                         "Dissolved": g in self._dissolved}
                for g, rec in sorted(self.groups.items())}

    def _snapshot_extra_locked(self) -> dict:
        extra = dict(super()._snapshot_extra_locked())
        extra["Groups"] = self._groups_json()
        return extra

    def crash(self, node_id: NodeID) -> None:
        gid = self._group_of_subleader.get(node_id)
        with self._lock:
            dissolve = gid is not None and gid not in self._dissolved
        if dissolve:
            self._dissolve_group(gid, node_id)
        super().crash(node_id)

    def _dissolve_group(self, gid: int, dead_sub: NodeID) -> None:
        """A dead sub-leader's group degrades to FLAT delivery: members
        re-point their control parent at this root and re-announce;
        the re-plan (riding the crash that got us here) then plans them
        directly.  Replicated, so a later takeover doesn't resurrect
        the dead hierarchy.  Mutations run under ``_lock``: a re-plan
        reading ``_member_group``/``_dissolved`` concurrently must see
        either the grouped or the fully-dissolved state, never a
        half-popped one."""
        with self._lock:
            rec = self.groups[gid]
            self._dissolved.add(gid)
            members = [m for m in rec["members"]
                       if m != dead_sub and m not in self._dead_members]
            for m in members:
                self._member_group.pop(m, None)
        for m in members:
            # Flat now: the root monitors them directly (their announce
            # refreshes the lease; one that never re-points expires).
            self.detector.touch(m)
        trace.count("hier.groups_dissolved")
        log.error("sub-leader crashed; dissolving group to flat",
                  group=gid, dead=dead_sub, members=members)
        self._replicate("groups", Groups=self._groups_json())
        out = GroupPlanMsg(self.node.my_id, gid, dissolve=True,
                           epoch=self.epoch)
        # The (declared-dead) sub-leader gets the notice too: a FALSE
        # positive — a partitioned-but-alive sub-leader — must stand
        # down (stop fanning out, stop dead-reporting members it no
        # longer hears from) instead of running a zombie group forever.
        for m in members + [dead_sub]:
            try:
                self.node.add_node(m)
                self.node.transport.send(m, out)
            except (OSError, KeyError) as e:
                log.warn("dissolve notice undeliverable (the seat's own "
                         "timeout will surface it)", member=m,
                         err=repr(e))

    # --------------------------------------------- elastic membership

    def _place_joiner(self, node: NodeID) -> NodeID:
        """Grouped clusters absorb joiners (docs/membership.md): the
        joiner lands in the least-loaded live group — bounded by the
        ``partition_groups`` sqrt sizing, so churn can't melt one
        sub-leader — and its control parent becomes that group's
        sub-leader; the next re-plan's ``GroupPlanMsg`` hands the
        sub-leader its targets.  A re-joining sub-leader seat, and a
        fleet whose groups are all dissolved/oversubscribed, plan
        flat (parent = this root)."""
        import math

        with self._lock:
            gid = self._member_group.get(node)
            if gid is not None:
                return self.groups[gid]["leader"]
            if node in self._group_of_subleader:
                return self.node.my_id
            candidates = sorted(
                (len([m for m in rec["members"]
                      if m not in self._dead_members]), g)
                for g, rec in self.groups.items()
                if g not in self._dissolved)
            if not candidates:
                return self.node.my_id
            n_seats = len(self._member_group) + len(self.groups) + 2
            cap = max(2, math.isqrt(n_seats) + 1)
            size, gid = candidates[0]
            if size >= 2 * cap:
                return self.node.my_id  # every group oversubscribed
            rec = self.groups[gid]
            if node not in rec["members"]:
                rec["members"] = sorted(set(rec["members"]) | {node})
            self._member_group[node] = gid
            self._dead_members.discard(node)
            sub = rec["leader"]
            gj = self._groups_json()
        trace.count("hier.joiners_grouped")
        log.info("joiner absorbed into group", node=node, group=gid,
                 sub=sub)
        self._replicate("groups", Groups=gj)
        return sub

    def handle_join(self, msg: JoinMsg) -> None:
        super().handle_join(msg)
        if not msg.admitted:
            # A re-admitted sub-leader seat re-forms its dissolved
            # group (the named PR 11 follow-up, docs/membership.md).
            self._maybe_reform(msg.src_id)

    def handle_announce(self, msg: AnnounceMsg) -> None:
        super().handle_announce(msg)
        # A dead sub-leader's seat coming back through a plain revival
        # announce re-forms its group too.
        if not self.membership.is_left(msg.src_id):
            self._maybe_reform(msg.src_id)

    def _maybe_reform(self, node: NodeID) -> None:
        """Re-form a dissolved group when its sub-leader seat is
        re-admitted: surviving members move back under it (re-point
        notices), the root stops monitoring them directly, and the
        next re-plan hands the sub-leader its targets again."""
        gid = self._group_of_subleader.get(node)
        if gid is None:
            return
        with self._lock:
            if gid not in self._dissolved:
                return
            self._dissolved.discard(gid)
            rec = self.groups[gid]
            members = [m for m in rec["members"]
                       if m != node and m not in self._dead_members
                       and not self.membership.is_left(m)]
            for m in members:
                self._member_group[m] = gid
            gj = self._groups_json()
        for m in members:
            # Their liveness belongs to the sub-leader's detector again.
            self.detector.forget(m)
        trace.count("hier.groups_reformed")
        log.warn("sub-leader seat re-admitted; re-forming its "
                 "dissolved group", group=gid, sub=node,
                 members=members)
        self._replicate("groups", Groups=gj)
        addr = self.membership.addr_of(node)
        out = JoinMsg(self.node.my_id, node=node, addr=addr,
                      admitted=True, parent=node, parent_addr=addr,
                      epoch=self.epoch)
        for m in members:
            try:
                self.node.add_node(m)
                self.node.transport.send(m, out)
            except (OSError, KeyError, ConnectionError) as e:
                log.warn("re-point notice undeliverable (the member "
                         "stays flat until the next lease)", member=m,
                         err=repr(e))
        self._send_group_plans()

    def _finalize_drain(self, node: NodeID) -> None:
        """A drained SUB-LEADER's group dissolves (members re-point at
        the root — the same degradation a sub-leader crash runs, minus
        the crash); a drained grouped MEMBER simply leaves its group's
        roster so fan-out stops chasing it."""
        gid = self._group_of_subleader.get(node)
        with self._lock:
            dissolve = gid is not None and gid not in self._dissolved
        if dissolve:
            self._dissolve_group(gid, node)
        gj = None
        with self._lock:
            mg = self._member_group.pop(node, None)
            if mg is not None:
                rec = self.groups.get(mg)
                if rec and node in rec["members"]:
                    rec["members"] = [m for m in rec["members"]
                                      if m != node]
                gj = self._groups_json()
        if gj is not None:
            self._replicate("groups", Groups=gj)
        super()._finalize_drain(node)
