"""Zero-downtime weight swap: stage v2 while v1 serves, flip atomically
(docs/swap.md).

A weight update used to mean stop-serving → re-disseminate → re-boot —
exactly the downtime a fleet serving live traffic cannot afford.  This
module is the RECEIVER half of the live-swap subsystem: the v2 bytes
ride the existing data plane as a ``kind="swap"`` job (version-tagged
holdings/acks, ``sched/jobs.py`` + ``runtime/leader.py`` own the
leader half), and :class:`SwapController` turns them into a serving
flip with three invariants:

1. **v1 never stops.** Staging runs on a daemon worker concurrent with
   the serving path; the flip itself is one attribute swap under the
   receiver's serve gate — in-flight decodes finish on the v1 params
   they captured, new requests read v2.  No request is ever dropped.
2. **v2 never aliases v1.** v2 blobs arrive under their own layer ids
   (``swap_base + slot``) into the ordinary layer store — staging
   decodes them into SEPARATE buffers; the serving params are replaced,
   never mutated.  The version guard
   (:func:`~..models.generate.ensure_uniform_version`) refuses to
   assemble a serving tree from blobs with mismatched version tags, so
   a forward can never run across mixed versions.
3. **Unhappy paths keep v1.** A digest mismatch that exhausts its retry
   budget reports the failure to the leader (which aborts the swap
   cluster-wide); an abort releases the staged v2 set and leaves v1
   serving untouched; a node that staged everything but never saw the
   commit fence re-requests it (``SwapCommitMsg(query=True)``) on a
   bounded timer instead of serving a stale version forever.

HBM headroom policy: each v2 blob's decoded leaves stage into device
memory only when ``parallel.ingest.hbm_headroom_bytes`` reports
comfortable headroom at that moment (``HEADROOM_FACTOR`` × the blob's
bytes); otherwise the leaves stay in host RAM and pay their device_put
at flip time — a bounded tokens/s dip instead of an OOM'd serving
process.  ``None`` (platform reports no stats — notably the CPU
backend, where device memory IS host memory) stages to device.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from ..utils import telemetry, trace
from ..utils.logging import log

# States of one tracked version at this node.
STAGING = "staging"      # prepare seen; v2 layers accumulating
PREPARED = "prepared"    # full set verified; params built, flip-ready
COMMITTED = "committed"  # flip applied; this version is serving
ABORTED = "aborted"      # rollout failed; staged set released
REVERTED = "reverted"    # flipped, then rolled BACK (SLO breach)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class SwapController:
    """Per-receiver live-swap state machine (docs/swap.md).

    Thread model: ``on_commit``/``on_layer`` are called from receiver
    handler threads and only mutate the tracked-version table under the
    controller lock; the expensive work — per-blob decode + staging,
    and the flip's wait-for-prepare — runs on dedicated daemon threads
    so the handler pool (and the serving path) never blocks behind a
    multi-second decode."""

    # Device staging wants this × blob bytes of free HBM; below it the
    # blob's decoded leaves stay host-resident until the flip.
    HEADROOM_FACTOR = 2.0
    # How long a PREPARED node waits for the commit fence before
    # re-requesting it, and how many re-requests before going quiet
    # (the leader's own commit watchdog re-sends from its side too).
    QUERY_RETRIES = 8
    # Bound on the flip's wait for an in-flight prepare.
    FLIP_WAIT_S = 300.0

    def __init__(self, receiver):
        self.r = receiver
        self._lock = threading.Lock()
        # version -> record: {"swap_base", "state", "per_slot", "head",
        # "host_slots", "params", "prepare_s", "event", "queries"}
        self._versions: Dict[str, dict] = {}
        self.query_interval = _env_float("DLD_SWAP_QUERY_S", 10.0)

    # ------------------------------------------------------------- intake

    def on_commit(self, msg) -> None:
        """Route one ``SwapCommitMsg`` from the leader.  Every outcome
        answers (the serving invariant): a flip or an abort confirms
        with ``applied=True``; an impossible commit reports ``error``
        so the leader aborts instead of re-sending forever."""
        if msg.finalize:
            # The rollback window is over (the wave's soak verdict
            # passed, or a plain swap's fleet flip completed): release
            # the retained pre-flip tree.  Advisory — no answer.
            self._finalize(msg.version)
            return
        if msg.abort:
            self._abort(msg.version, revert=msg.revert)
            self._answer(version=msg.version, applied=True)
            return
        with self._lock:
            rec = self._versions.get(msg.version)
            if rec is not None and rec["state"] in (ABORTED, REVERTED):
                # A retry rollout re-uses an aborted version name
                # (docs/swap.md): start over with a fresh record — the
                # released v2 set was re-announced away, so the retry
                # job redelivers it.
                log.warn("fresh fence for a previously aborted version; "
                         "re-tracking", version=msg.version)
                del self._versions[msg.version]
                rec = None
            if rec is None:
                if msg.swap_base < 0:
                    # Commit for a version this node never saw a prepare
                    # (or any v2 byte) for, and the fence carries no blob
                    # mapping: nothing stageable here.
                    self._answer(version=msg.version,
                                 error="unknown swap version at this node")
                    return
                rec = self._track_locked(msg.version, msg.swap_base)
            elif msg.swap_base >= 0 and rec["swap_base"] < 0:
                rec["swap_base"] = msg.swap_base
            if rec["state"] == COMMITTED:
                # Re-sent fence (our confirm was lost): re-confirm.
                self._answer(version=msg.version, applied=True)
                return
            already = rec.get("flip_pending", False)
            rec["flip_pending"] = True
        self._maybe_prepare(msg.version)
        if not already:
            threading.Thread(target=self._flip_when_ready,
                             args=(msg.version,), daemon=True,
                             name=f"swap-flip-{self.r.node.my_id}").start()

    def on_prepare(self, version: str, swap_base: int) -> None:
        """The leader announced a swap at admission: start tracking, so
        staging overlaps the rollout instead of serializing after it.
        A prepare for a previously ABORTED version is a retry rollout —
        re-track from scratch (docs/swap.md)."""
        with self._lock:
            rec = self._versions.get(version)
            if rec is not None and rec["state"] in (ABORTED, REVERTED):
                log.warn("prepare for a previously aborted version; "
                         "re-tracking for the retry", version=version)
                del self._versions[version]
                rec = None
            if rec is None:
                rec = self._track_locked(version, swap_base)
            elif swap_base >= 0 and rec["swap_base"] < 0:
                rec["swap_base"] = swap_base
        self._maybe_prepare(version)

    def on_layer(self, lid: int) -> None:
        """A layer completed + verified (any path: wire, content
        resolve, retransmit) — it may have completed a tracked
        version's v2 set."""
        version = self.r._layer_versions.get(lid)
        if not version:
            return
        self._maybe_prepare(version)

    def on_staging_failed(self, lid: int, reason: str) -> None:
        """A versioned layer is unrecoverable here (digest retry budget
        exhausted): the swap cannot complete on this replica — report
        to the leader, which aborts cluster-wide (rollback = keep
        serving v1)."""
        version = self.r._layer_versions.get(lid)
        if not version:
            return
        trace.count("swap.staging_failed")
        log.error("swap staging failed; reporting to leader for abort",
                  version=version, layerID=lid, reason=reason)
        self._answer(version=version,
                     error=f"layer {lid} unrecoverable: {reason}")

    # ------------------------------------------------------------ queries

    def state_of(self, version: str) -> Optional[str]:
        with self._lock:
            rec = self._versions.get(version)
            return rec["state"] if rec is not None else None

    # ----------------------------------------------------------- internal

    def _track_locked(self, version: str, swap_base: int) -> dict:
        rec = {
            "swap_base": int(swap_base),
            "state": STAGING,
            "per_slot": {},     # slot -> {name: leaf (np or jnp)}
            "host_slots": set(),  # slots staged host-side (headroom)
            "head": None,
            "params": None,     # fully assembled tree when all-device
            "prepare_s": 0.0,
            "prepare_started": False,
            "flip_pending": False,
            "event": threading.Event(),  # set at PREPARED (or terminal)
            "queries": 0,
            # The pre-flip serving state, retained from the flip until
            # the leader's FINALIZE fence: (boot_result, version,
            # tree-version map) — the rollback window of
            # docs/rollout.md.  None before the flip / after finalize.
            "prev": None,
        }
        self._versions[version] = rec
        log.info("tracking swap version", version=version,
                 swap_base=swap_base)
        return rec

    def _expected_ids(self, swap_base: int):
        from ..models import serde

        head = serde.head_blob_id(self.r.boot_cfg)
        return [swap_base + b for b in range(head + 1)]

    def _set_complete(self, swap_base: int) -> bool:
        """Whether every v2 blob is held AND digest-verified (a stamped
        digest must have passed the gate; unstamped layers verified by
        per-fragment CRC alone — the integrity plane's usual trust)."""
        r = self.r
        for lid in self._expected_ids(swap_base):
            with r._lock:
                src = r.layers.get(lid)
            if src is None:
                return False
            if src.meta.shard or src.meta.codec:
                # A shard slice or a still-ENCODED holding (codec form,
                # or a delta stream awaiting reconstruction) must never
                # enter the serving tree — the full canonical bytes
                # have to land first (docs/swap.md, docs/codec.md).
                return False
            if (r._expected_digest(lid) is not None
                    and lid not in r._digest_ok):
                return False
        return True

    def _maybe_prepare(self, version: str) -> None:
        with self._lock:
            rec = self._versions.get(version)
            if (rec is None or rec["state"] != STAGING
                    or rec["prepare_started"] or rec["swap_base"] < 0):
                return
            if not self._set_complete(rec["swap_base"]):
                return
            rec["prepare_started"] = True
        threading.Thread(target=self._prepare, args=(version,),
                         daemon=True,
                         name=f"swap-prepare-{self.r.node.my_id}").start()

    def _prepare(self, version: str) -> None:
        """Decode the full v2 set into flip-ready params on a worker
        thread, concurrent with v1 serving.  Per-blob headroom probe:
        roomy blobs decode straight onto device; tight ones stay host
        and pay their device_put at flip time."""
        t0 = time.monotonic()
        try:
            self._prepare_inner(version)
        except Exception as e:  # noqa: BLE001 — must report, never wedge
            log.error("swap prepare failed", version=version, err=repr(e))
            trace.count("swap.staging_failed")
            with self._lock:
                rec = self._versions.get(version)
                if rec is not None:
                    rec["prepare_started"] = False
                    rec["event"].set()  # release any flip waiter...
                    rec["event"] = threading.Event()  # ...then re-arm
            self._answer(version=version, error=f"prepare failed: {e!r}")
            return
        dt = time.monotonic() - t0
        with self._lock:
            rec = self._versions.get(version)
            if rec is None or rec["state"] != STAGING:
                return
            rec["state"] = PREPARED
            rec["prepare_s"] = dt
            rec["event"].set()
            n_host = len(rec["host_slots"])
            pending = rec["flip_pending"]
        trace.count("swap.prepared")
        log.info("swap version staged and flip-ready", version=version,
                 prepare_ms=round(dt * 1000, 1), host_staged_blobs=n_host)
        if not pending:
            # Staged but unfenced: arm the commit re-request timer — a
            # node that missed the fence must ask, not serve v1 forever
            # while the rest of the fleet moved to v2.
            self._arm_query(version)

    def _prepare_inner(self, version: str) -> None:
        import numpy as np

        from ..models import quant, serde
        from ..models.generate import ensure_uniform_version
        from ..parallel.ingest import hbm_headroom_bytes

        r = self.r
        cfg = r.boot_cfg
        with self._lock:
            rec = self._versions[version]
            swap_base = rec["swap_base"]
        head_id = serde.head_blob_id(cfg)
        ids = self._expected_ids(swap_base)
        # The mixed-version guard: every blob entering the serving tree
        # must carry THIS version's tag — a forward across layers of
        # two rollouts must be impossible by construction.
        ensure_uniform_version(
            {lid: r._layer_versions.get(lid, "") for lid in ids}, version)
        per_slot: Dict[int, dict] = {}
        head_leaves = None
        host_slots = set()
        for lid in ids:
            with r._lock:
                src = r.layers.get(lid)
            if src is None:
                raise RuntimeError(f"v2 blob {lid} vanished mid-prepare")
            data = (bytes(src.inmem_data) if src.inmem_data is not None
                    else src.read_bytes())
            slot = lid - swap_base
            leaves = quant.decode_blob_host(cfg, slot, data, r.boot_codec)
            # Probe against the DECODED leaf bytes, not the wire size:
            # a quantized blob decodes to several times its encoded
            # bytes, and sizing the check by the wire would OOM the
            # serving device — the exact failure this policy prevents.
            decoded = sum(getattr(v, "nbytes", len(data))
                          for v in leaves.values())
            headroom = hbm_headroom_bytes()
            tight = (headroom is not None
                     and headroom < decoded * self.HEADROOM_FACTOR)
            if tight:
                host_slots.add(slot)
                staged = {k: np.asarray(v) for k, v in leaves.items()}
            else:
                import jax.numpy as jnp

                staged = {k: jnp.asarray(v) for k, v in leaves.items()}
            if slot == head_id:
                head_leaves = staged
            else:
                per_slot[slot] = staged
        params = None
        if not host_slots:
            # Everything device-resident: assemble NOW so the flip is a
            # pure pointer swap (no device work between decode steps).
            params = self._assemble(per_slot, head_leaves)
        with self._lock:
            rec = self._versions.get(version)
            if rec is None or rec["state"] != STAGING:
                # An abort (or anything terminal) landed while this
                # prepare was decoding: storing the freshly decoded
                # leaves would re-pin the very memory the abort just
                # released — drop them instead (GC frees host + HBM).
                log.warn("discarding staged leaves for a no-longer-"
                         "staging version", version=version,
                         state=rec["state"] if rec else None)
                return
            rec["per_slot"] = per_slot
            rec["head"] = head_leaves
            rec["host_slots"] = host_slots
            rec["params"] = params

    def _assemble(self, per_slot: dict, head_leaves: dict):
        """The serving tree ``models.generate.generate`` consumes:
        stacked layer leaves [L, ...] + the head."""
        import jax.numpy as jnp

        n = self.r.boot_cfg.n_layers
        names = list(per_slot[0])
        layers = {name: jnp.stack([jnp.asarray(per_slot[i][name])
                                   for i in range(n)])
                  for name in names}
        return {"embed": jnp.asarray(head_leaves["embed"]),
                "layers": layers,
                "ln_f": jnp.asarray(head_leaves["ln_f"]),
                "lm_head": jnp.asarray(head_leaves["lm_head"])}

    def _flip_when_ready(self, version: str) -> None:
        """The commit fence's flip half: wait (bounded) for the prepare,
        then atomically swap the serving params and confirm."""
        with self._lock:
            rec = self._versions.get(version)
            if rec is None:
                return
            ev = rec["event"]
        if not ev.wait(timeout=self.FLIP_WAIT_S):
            log.error("swap flip timed out waiting for staging",
                      version=version)
            self._answer(version=version,
                         error="staging never completed at this node")
            with self._lock:
                rec = self._versions.get(version)
                if rec is not None:
                    rec["flip_pending"] = False
            return
        t0 = time.monotonic()
        with self._lock:
            rec = self._versions.get(version)
            if rec is None:
                return
            if rec["state"] == COMMITTED:
                self._answer(version=version, applied=True)
                return
            if rec["state"] != PREPARED:
                # Aborted, or the prepare failed (its error report is
                # already on the wire): nothing to flip.
                rec["flip_pending"] = False
                return
            params = rec["params"]
            per_slot, head = rec["per_slot"], rec["head"]
            n_host = len(rec["host_slots"])
        if params is None:
            # Host-staged blobs pay their device_put here — the flip's
            # bounded dip, logged so the live_swap row can attribute it.
            params = self._assemble(per_slot, head)
        with self._lock:
            rec = self._versions.get(version)
            if rec is None or rec["state"] != PREPARED:
                # An abort landed during the assemble (it already
                # answered the leader): abandon the flip — applying it
                # now would put THIS replica on v2 while the leader
                # records a clean fleet-wide rollback.
                if rec is not None:
                    rec["flip_pending"] = False
                log.warn("flip abandoned; version left the prepared "
                         "state mid-assemble", version=version,
                         state=rec["state"] if rec else None)
                return
            # Claim the commit ATOMICALLY before applying: an abort
            # arriving from here on sees COMMITTED and refuses, loudly.
            rec["state"] = COMMITTED
            rec["flip_pending"] = False
            flip_slots = sorted(rec["per_slot"]) or sorted(per_slot)
            flip_base = rec["swap_base"]
            # The flipped-in tree owns the staged leaves now.
            rec["per_slot"] = {}
            rec["head"] = None
            rec["params"] = None
        # Retain the pre-flip serving state until the leader finalizes:
        # an SLO-breach rollback (docs/rollout.md) restores it with one
        # pointer swap instead of a re-dissemination.
        with self.r._lock:
            prev = (getattr(self.r, "boot_result", None),
                    getattr(self.r, "serving_version", ""),
                    dict(getattr(self.r, "_serving_tree_versions", {})))
        with self._lock:
            rec = self._versions.get(version)
            if rec is not None:
                rec["prev"] = prev
        self.r._apply_swap_result(version, params)
        dt = time.monotonic() - t0
        trace.count("swap.flips")
        # Pair-lifecycle spans (docs/observability.md): each staged v2
        # pair's terminal edge for swap/rollout pairs — acked→flipped is
        # the commit-fence propagation + flip cost the critical-path
        # walk attributes to the rollout plane.
        if flip_base >= 0:
            for slot in flip_slots:
                telemetry.span_event(
                    telemetry.span_id(self.r.node.my_id,
                                      flip_base + slot),
                    "flipped", node=self.r.node.my_id,
                    dest=self.r.node.my_id, layer=flip_base + slot,
                    version=version)
        log.info("swap committed: serving flipped atomically",
                 version=version, flip_ms=round(dt * 1000, 1),
                 host_staged_blobs=n_host)
        self._answer(version=version, applied=True)

    def _abort(self, version: str, revert: bool = False) -> None:
        """Rollback = don't flip: release the staged v2 set (decoded
        leaves AND the store's v2 blob entries) and keep serving v1.
        With ``revert`` (docs/rollout.md), an already-COMMITTED version
        rolls BACK: the retained pre-flip tree is restored with one
        pointer swap (the SLO-breach rollback); without it a committed
        version refuses the abort, as ever."""
        reverted = False
        kept_for_boot = False
        with self._lock:
            rec = self._versions.get(version)
            if rec is None:
                rec = self._track_locked(version, -1)
            if (rec["state"] == COMMITTED and revert and rec["prev"]
                    and rec["prev"][0] is None):
                # The flip WAS this replica's boot (it joined mid-
                # rollout and never served the pre-flip version):
                # "reverting" would restore a None tree and refuse
                # every request.  Keep the flipped tree serving —
                # degraded-but-serving beats dark — and release the
                # retained marker so duplicate reverts stay no-ops.
                rec["prev"] = None
                rec_state = COMMITTED
                kept_for_boot = True
            elif rec["state"] == COMMITTED and revert and rec["prev"]:
                prev = rec["prev"]
                rec["prev"] = None
                rec["state"] = REVERTED
                rec["event"].set()
                rec_state = REVERTED
                reverted = True
            elif rec["state"] in (COMMITTED, ABORTED, REVERTED):
                rec_state = rec["state"]
            else:
                rec["state"] = ABORTED
                rec["per_slot"] = {}
                rec["head"] = None
                rec["params"] = None
                rec["event"].set()
                rec_state = ABORTED
            swap_base = rec["swap_base"]
        if rec_state == COMMITTED:
            if kept_for_boot:
                trace.count("swap.revert_no_prev")
                log.error("revert refused: the flip was this replica's "
                          "boot (no pre-flip tree exists); keeping the "
                          "flipped tree serving", version=version)
            else:
                log.error("abort for an already-committed version "
                          "ignored (the flip happened; a plain abort "
                          "cannot undo it"
                          + (" and no retained pre-flip tree remains to "
                             "revert to" if revert else "") + ")",
                          version=version)
            return
        if rec_state == REVERTED and not reverted:
            return  # duplicate revert fence: already rolled back
        if reverted:
            prev_res, prev_version, prev_tree = prev
            with self.r._lock:
                self.r.boot_result = prev_res
                self.r.serving_version = prev_version
                self.r._serving_tree_versions = prev_tree
            trace.count("swap.reverted")
            log.warn("swap ROLLED BACK: serving restored to the "
                     "pre-flip tree", version=version,
                     restored_version=prev_version or "v1")
        dropped = 0
        if swap_base >= 0 and self.r.boot_cfg is not None:
            for lid in self._expected_ids(swap_base):
                with self.r._lock:
                    had = self.r.layers.pop(lid, None) is not None
                    self.r._own_digests.pop(lid, None)
                    self.r._digest_ok.discard(lid)
                if had:
                    dropped += 1
                    self.r.content_store.forget(lid)
        trace.count("swap.aborted")
        log.warn("swap aborted; v1 keeps serving, staged v2 released",
                 version=version, released_blobs=dropped)
        if dropped:
            # The leader's status rows still show the released v2 set
            # as delivered here (the acks landed): re-announce the
            # authoritative inventory, or a RETRY rollout under this
            # version would resolve its pairs at admit against bytes
            # this node no longer holds and wedge at the flip.
            try:
                self.r.announce()
            except (OSError, KeyError) as e:
                log.error("post-abort re-announce failed", err=repr(e))

    def _finalize(self, version: str) -> None:
        """Release the retained pre-flip tree: the rollback window is
        over (docs/rollout.md).  A finalize for an unknown/unflipped
        version is a harmless no-op — the fence is advisory."""
        with self._lock:
            rec = self._versions.get(version)
            if rec is None or rec["state"] != COMMITTED or not rec["prev"]:
                return
            rec["prev"] = None
        trace.count("swap.finalized")
        log.info("swap finalized; retained pre-flip tree released",
                 version=version)

    def _arm_query(self, version: str) -> None:
        if self.query_interval <= 0:
            return
        with self._lock:
            rec = self._versions.get(version)
            if rec is None or rec["state"] != PREPARED:
                return
            if rec["queries"] >= self.QUERY_RETRIES:
                log.error("commit fence re-request budget exhausted; "
                          "staying on the serving version", version=version)
                return
            rec["queries"] += 1
            n = rec["queries"]
        timer = threading.Timer(self.query_interval,
                                self._query_fire, args=(version, n))
        timer.daemon = True
        timer.start()

    def _query_fire(self, version: str, n: int) -> None:
        with self._lock:
            rec = self._versions.get(version)
            if rec is None or rec["state"] != PREPARED or rec["flip_pending"]:
                return
        trace.count("swap.fence_requeried")
        log.warn("staged swap never saw its commit fence; re-requesting",
                 version=version, attempt=n)
        self._answer(version=version, query=True)
        self._arm_query(version)

    def _answer(self, version: str, applied: bool = False,
                query: bool = False, error: str = "") -> None:
        from ..transport.messages import SwapCommitMsg

        self.r._send_to_leader(
            SwapCommitMsg(self.r.node.my_id, version, applied=applied,
                          query=query, error=error))
