"""Transfer checkpoint/resume: crash-durable partial-layer progress.

The reference has no checkpointing; its nearest machinery is layer files
on disk (``/root/reference/cmd/config.go:133-157``) and the mode-3
receiver's (non-durable) incremental byte accounting
(``distributor/node.go:1542-1554``).  Here every received fragment is
also written at its offset into ``<dir>/<layer_id>.part`` with the
covered intervals journaled in ``<dir>/<layer_id>.meta.json``; a
restarted receiver reloads its partial buffers, announces the covered
ranges, and the mode-3 leader schedules **only the gaps** — a crash
costs at most the fragments in flight, not the transfer.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..core.types import LayerID
from ..utils import integrity, intervals, trace
from ..utils.logging import log

# layer -> (buffer, covered intervals, total size)
PartialState = Dict[LayerID, Tuple[bytearray, List[Tuple[int, int]], int]]


class LayerCheckpointStore:
    """Durable fragment journal for one receiver.

    ``write_fragment`` is crash-ordered: bytes land in the ``.part`` file
    before the meta journal records them as covered, so a crash between
    the two writes only *under*-reports progress (the range is re-sent,
    which interval reassembly absorbs) — never the fatal inverse.  Both
    writes are fsync'd, so the ordering holds across host power loss, not
    just process crashes: the journal can never claim bytes the disk lost.
    """

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _part(self, layer_id: LayerID) -> str:
        return os.path.join(self.dir, f"{layer_id}.part")

    def _meta(self, layer_id: LayerID) -> str:
        return os.path.join(self.dir, f"{layer_id}.meta.json")

    def write_bytes(
        self, layer_id: LayerID, offset: int, data: bytes, total: int
    ) -> None:
        """Persist one fragment's bytes into the ``.part`` file, fsync'd —
        data durable before any journal update covers it.  Safe for
        concurrent writers: O_CREAT|O_RDWR never truncates (a racing
        'w+b'-style create would zero a sibling's already-fsync'd range),
        and the grow-only ftruncate is idempotent."""
        fd = os.open(self._part(layer_id), os.O_CREAT | os.O_RDWR, 0o644)
        try:
            if os.fstat(fd).st_size < total:
                os.ftruncate(fd, total)  # extend-only: never destroys data
            # Loop to completion: a single pwrite caps at ~2 GiB on Linux,
            # and a silently short write would let write_meta journal bytes
            # the file holds as zeros.
            view = memoryview(data)
            written = 0
            while written < len(view):
                written += os.pwrite(fd, view[written:], offset + written)
            os.fsync(fd)
        finally:
            os.close(fd)

    def write_meta(
        self,
        layer_id: LayerID,
        covered: List[Tuple[int, int]],
        total: int,
        frag_crcs: Optional[List[Tuple[int, int, int]]] = None,
    ) -> None:
        """Journal the durably-covered ranges.  Callers must pass only
        ranges whose ``write_bytes`` has already returned — the journal can
        never claim bytes the disk might not hold (a racing older snapshot
        landing later only under-reports, which re-sending absorbs).  The
        tmp name is per-writer (pid + thread), so concurrent journalers of
        one layer never truncate each other's half-written JSON.

        ``frag_crcs``: per-journaled-fragment ``(offset, length, crc32)``
        records (integrity hardening, docs/integrity.md) — ``load``
        re-reads each range from the ``.part`` file and verifies it, so
        a corrupted disk (bit rot, torn write the fsync ordering can't
        see, foreign truncation) can never resume as "covered": bad
        ranges fall out of the restored coverage and are re-fetched."""
        tmp = (f"{self._meta(layer_id)}.{os.getpid()}"
               f".{threading.get_ident()}.tmp")
        doc = {"Total": total, "Covered": [list(iv) for iv in covered]}
        if frag_crcs:
            doc["FragCrcs"] = [[int(o), int(n), int(c)]
                               for o, n, c in frag_crcs]
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta(layer_id))  # atomic journal update

    def write_fragment(
        self,
        layer_id: LayerID,
        offset: int,
        data: bytes,
        covered: List[Tuple[int, int]],
        total: int,
        frag_crcs: Optional[List[Tuple[int, int, int]]] = None,
    ) -> None:
        """Persist one fragment + coverage (single-writer convenience;
        concurrent writers must use write_bytes + write_meta with a
        durable-only coverage union)."""
        self.write_bytes(layer_id, offset, data, total)
        self.write_meta(layer_id, covered, total, frag_crcs=frag_crcs)

    def complete(self, layer_id: LayerID) -> None:
        """Drop checkpoint state for a fully assembled layer."""
        for path in (self._part(layer_id), self._meta(layer_id)):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass

    def load(self) -> PartialState:
        """Restore all partial layers recorded in this directory."""
        state: PartialState = {}
        if not os.path.isdir(self.dir):
            return state
        for name in sorted(os.listdir(self.dir)):
            # Meta tmp files are per-writer named, so ones orphaned by a
            # crash never self-overwrite; load() runs before any journaler
            # exists, making this the one safe point to sweep them.
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass
                continue
            if not name.endswith(".meta.json"):
                continue
            try:
                layer_id = int(name.split(".", 1)[0])
            except ValueError:
                log.warn("ignoring foreign file in checkpoint dir", file=name)
                continue
            try:
                with open(self._meta(layer_id)) as f:
                    meta = json.load(f)
                total = int(meta["Total"])
                covered = [(int(s), int(e)) for s, e in meta["Covered"]]
                frag_crcs = [(int(o), int(n), int(c))
                             for o, n, c in meta.get("FragCrcs") or []]
                with open(self._part(layer_id), "rb") as f:
                    buf = bytearray(total)
                    for s, e in covered:
                        f.seek(s)
                        chunk = f.read(e - s)
                        if len(chunk) != e - s and not frag_crcs:
                            # Truncated .part (disk full, partial copy):
                            # a short slice assignment would silently
                            # SHRINK the buffer — corrupt restore.  With
                            # CRCs the verify pass below demotes what
                            # the short read left unfilled to missing.
                            raise ValueError(
                                f"part file truncated: wanted [{s}, {e})"
                            )
                        buf[s:s + len(chunk)] = chunk
                if frag_crcs:
                    # Verify from the restored bytes — disk is read ONCE
                    # (physical-size resumes were paying a doubled read).
                    covered = self._verify_ranges(
                        layer_id, buf, covered, frag_crcs)
            except (OSError, ValueError, KeyError) as e:
                log.warn("dropping unreadable checkpoint", layer=layer_id,
                         err=repr(e))
                self.complete(layer_id)  # clear the corrupt pair
                continue
            state[layer_id] = (buf, covered, total)
            log.info("restored partial layer from checkpoint",
                     layer=layer_id, covered_bytes=intervals.covered(covered),
                     total=total)
        return state

    @staticmethod
    def _verify_ranges(layer_id, buf, covered, frag_crcs):
        """Resume verification: check every journaled fragment range's
        recorded crc32 against the restored bytes; the restored coverage
        is the journal's coverage INTERSECTED with the union of ranges
        that verified — tampered/rotted disk bytes fall back to
        "missing" (re-fetched by the resumed plan) instead of resuming
        as covered.  A range outside the filled coverage (torn meta,
        truncated ``.part``) hashes unfilled zeroes and so demotes the
        same way."""
        ok_union: List[Tuple[int, int]] = []
        bad = 0
        view = memoryview(buf)
        for off, n, crc in frag_crcs:
            if (off + n <= len(buf)
                    and integrity.fragment_crc(view[off:off + n]) == crc):
                ok_union = intervals.insert(ok_union, off, off + n)
            else:
                bad += 1
                log.error("checkpoint range failed CRC on resume; "
                          "re-opening it", layer=layer_id, offset=off,
                          size=n)
                trace.count("integrity.journal_bad_range")
                trace.count("integrity.journal_bad_bytes", n)
        verified = intervals.intersect(covered, ok_union)
        if bad:
            log.warn("journal resume dropped corrupt ranges",
                     layer=layer_id, bad_ranges=bad,
                     kept_bytes=intervals.covered(verified),
                     journaled_bytes=intervals.covered(covered))
        return verified


def map_through_gaps(
    gaps: List[Tuple[int, int]], offset: int, size: int
) -> List[Tuple[int, int]]:
    """Translate a job span over *remaining* bytes into real byte ranges.

    When a layer is partially delivered, the flow solver plans over its
    remaining size R; a per-sender job tiles ``[offset, offset+size)`` of
    that compacted space.  This maps the span back through the gap list
    (the uncovered ranges, in order) to absolute (offset, size) pairs —
    possibly several, when the span crosses a gap boundary.
    """
    out: List[Tuple[int, int]] = []
    pos = 0  # position in compacted remaining-space
    for s, e in gaps:
        glen = e - s
        lo = max(offset, pos)
        hi = min(offset + size, pos + glen)
        if lo < hi:
            out.append((s + (lo - pos), hi - lo))
        pos += glen
        if pos >= offset + size:
            break
    return out
