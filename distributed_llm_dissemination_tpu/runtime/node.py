"""Node identity core + message-loop plumbing.

Re-design of the reference's ``N`` struct and per-node goroutine fan-out
(``/root/reference/distributor/node.go:17-126, 271-287``): every node owns a
transport, a leader pointer, and a (1-hop, forward-provisioned) routing
table; incoming messages are drained from the transport's delivery queue by
one loop thread and dispatched to handlers on a small pool so long layer
transfers never block control traffic.
"""

from __future__ import annotations

import os
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional, Type

from ..core.types import NodeID, RoutingInfo
from ..transport.base import Transport
from ..utils import telemetry
from ..utils.logging import log


class Node:
    """Identity + leader pointer + routing table (node.go:35-126)."""

    def __init__(self, my_id: NodeID, leader_id: NodeID, transport: Transport):
        self.my_id = my_id
        self.leader_id = leader_id
        self.transport = transport
        # Bind this node's identity onto the transport so its per-frame
        # accounting can file rx bytes under a (src, MY id) link in the
        # telemetry flight recorder (utils/telemetry.py) — the transport
        # otherwise only knows addresses.  Advisory: a transport used
        # without a Node (raw tests) records nothing rather than
        # misfiling bytes.
        try:
            transport.node_id = my_id
        except AttributeError:  # a wrapper may proxy it read-only
            pass
        self.routing_table: Dict[NodeID, RoutingInfo] = {}
        self._lock = threading.Lock()
        if my_id != leader_id:
            self.add_node(leader_id)

    def get_next_hop(self, goal: NodeID) -> NodeID:
        with self._lock:
            info = self.routing_table.get(goal)
        if info is None:
            raise KeyError(f"no routing entry for {goal}")
        return info.next_hop

    def add_node(self, goal: NodeID) -> None:
        self.add_routing_table(goal, goal, 1)

    def add_routing_table(
        self, goal: NodeID, next_hop: NodeID, remaining_hops: int = 1
    ) -> None:
        with self._lock:
            self.routing_table[goal] = RoutingInfo(next_hop, remaining_hops)

    def update_leader(self, leader_id: NodeID) -> None:
        with self._lock:
            if leader_id not in self.routing_table:
                raise KeyError("routing entry for the specified leader does not exist")
            self.leader_id = leader_id


class MessageLoop:
    """Drains a transport's delivery queue and dispatches by message type.

    The reference runs one goroutine per node reading ``Deliver()`` and
    spawns a goroutine per message (node.go:271-287); here a single loop
    thread feeds a bounded pool, which gives the same never-block-control
    property with tamer thread counts.
    """

    def __init__(self, transport: Transport,
                 max_workers: Optional[int] = None):
        if max_workers is None:
            # Fleet-scale knob: an N-node in-process harness (the inmem
            # fan-out rows) would otherwise lazily grow N x 16 handler
            # threads.
            try:
                max_workers = int(os.environ.get("DLD_MSGLOOP_WORKERS",
                                                 "16"))
            except ValueError:
                max_workers = 16
        self._transport = transport
        self._handlers: Dict[Type, Callable] = {}
        self._stop = threading.Event()
        # Control-plane threads carry stable names (utils/threads.py
        # census buckets them by prefix; docs/observability.md).
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="ctl-worker")
        self._thread: Optional[threading.Thread] = None

    def register(self, msg_cls: Type, handler: Callable) -> None:
        self._handlers[msg_cls] = handler

    def register_keep(self, msg_cls: Type, handler: Callable) -> None:
        """Register only when no handler exists for the type.  A standby
        promoted to leader shares the WORKER's already-running loop
        (two loops draining one transport queue would steal each other's
        messages), and its leader-side registrations must fill the
        control-plane gaps (announce/ack/heartbeat/...) without
        clobbering the worker's data-plane handlers (layer reassembly,
        flow jobs) — see runtime/failover.py."""
        self._handlers.setdefault(msg_cls, handler)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="msgloop")
        self._thread.start()

    def _run(self) -> None:
        q = self._transport.deliver()
        # Per-seat control-traffic accounting: the fan-out rows compare
        # how many control messages the ROOT handled flat vs
        # hierarchically (docs/hierarchy.md) — keyed by the transport's
        # bound node id so co-resident inmem nodes don't pool into one
        # counter.  The id is bound before start() in every real path
        # and invariant for the loop's lifetime: resolve the counter
        # key ONCE, off the per-message hot path.
        node_id = getattr(self._transport, "node_id", None)
        handled_key = (f"ctrl.handled.{node_id}"
                       if node_id is not None else None)
        while not self._stop.is_set():
            try:
                msg = q.get(timeout=0.1)
            except queue.Empty:
                continue
            if handled_key is not None:
                telemetry.count(handled_key)
            handler = self._handlers.get(type(msg))
            if handler is None:
                log.debug("unhandled message", kind=type(msg).__name__)
                continue
            self.submit(handler, msg)

    def submit(self, fn: Callable, *args) -> None:
        """Run ``fn(*args)`` on the pool, tolerating a concurrent
        ``stop()`` (work arriving mid-shutdown is dropped, not raised)."""
        try:
            self._pool.submit(self._safe, fn, *args)
        except RuntimeError:
            if not self._stop.is_set():
                raise

    @staticmethod
    def _safe(handler: Callable, *args) -> None:
        try:
            handler(*args)
        except Exception as e:  # noqa: BLE001 — a handler crash must not kill the loop
            log.error("handler failed", fn=getattr(handler, "__name__", "?"),
                      err=repr(e))

    def stop(self) -> None:
        self._stop.set()
        self._pool.shutdown(wait=False)
