"""Content-addressed layer store (docs/service.md).

Layers are keyed by their PR-4 digest stamp (``xxh3:<hex>`` /
blake2b hex — ``utils.integrity.layer_digest``), which turns the layer
store into a content store: a v2 rollout whose layer 103 hashes the same
as the v1 layer 3 a node already holds resolves LOCALLY — zero wire
bytes — and a repaired node refills from whichever CURRENT holder is
nearest/fastest, not the original seeder.

Two small classes, two sides of the same key:

- :class:`ContentStore` — the NODE half: digest → locally held layer
  ids.  Populated wherever this process provably knows a layer's bytes
  hash to a digest (its own announce-time hash, an ack-gate verify);
  consulted when the leader's digest stamp names an ASSIGNED layer this
  node doesn't hold — a digest hit aliases the held bytes under the new
  layer id and acks instantly.
- :class:`ContentIndex` — the LEADER half: digest → (node, layer)
  holders, rebuilt from announces (authoritative per node) and extended
  by acks (the leader stamped the digest, so a delivered copy provably
  carries it).  The planner skips shipping a (dest, layer) pair whose
  digest the dest already holds under ANY layer id — the dest's own
  resolve-and-ack completes the pair.

Shard scoping (docs/sharding.md): every entry is keyed by
``(digest, shard, codec)`` — a SHARD holding's digest is the digest of
its byte RANGE, verified over exactly those bytes, so it can only ever
vouch for (and alias to) a target with the SAME range.  A full-layer
query (``shard=""``) never matches a shard-vouched entry: a
shard-holder can never ack a full-layer pair.

Codec scoping (docs/codec.md): the third key component is the
wire-codec form.  A quantized holding's digest is the digest of the
ENCODED bytes — a different byte string than the canonical layer — so
it vouches only under ``(digest, shard, codec)``: a raw query can never
match an int8-vouched entry, and a quantized copy can never
alias-complete (or be planned as already-holding) a raw pair.

Digest trust model: both sides only index digests that were locally
verified (node) or announced/stamped through the PR-4 integrity plane
(leader) — the same trust the digest verification gate already places
in those sources (docs/integrity.md).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from ..core.types import LayerID, NodeID

# The (digest, shard, codec) content key; shard "" = the whole layer,
# codec "" = canonical bytes.
ContentKey = Tuple[str, str, str]


class ContentStore:
    """(digest, shard, codec) → layer ids this node holds with those
    exact bytes over exactly that range in exactly that form."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_key: Dict[ContentKey, Set[LayerID]] = {}
        self._by_layer: Dict[LayerID, ContentKey] = {}

    def index(self, lid: LayerID, digest: str, shard: str = "",
              codec: str = "") -> None:
        if not digest:
            return
        key = (str(digest), str(shard), str(codec))
        with self._lock:
            old = self._by_layer.get(lid)
            if old == key:
                return
            if old is not None:
                ids = self._by_key.get(old)
                if ids is not None:
                    ids.discard(lid)
                    if not ids:
                        del self._by_key[old]
            self._by_layer[lid] = key
            self._by_key.setdefault(key, set()).add(lid)

    def forget(self, lid: LayerID) -> None:
        """Drop a layer (demoted as corrupt, evicted): its bytes can no
        longer vouch for the digest."""
        with self._lock:
            key = self._by_layer.pop(lid, None)
            if key is not None:
                ids = self._by_key.get(key)
                if ids is not None:
                    ids.discard(lid)
                    if not ids:
                        del self._by_key[key]

    def lookup(self, digest: str, shard: str = "",
               codec: str = "") -> Optional[LayerID]:
        """A local layer id holding these bytes over this exact range
        in this exact form (lowest id for determinism), or None.  A
        full-layer raw lookup only matches full-layer raw holdings."""
        with self._lock:
            ids = self._by_key.get((str(digest), str(shard), str(codec)))
            return min(ids) if ids else None

    def digest_of(self, lid: LayerID) -> Optional[str]:
        with self._lock:
            key = self._by_layer.get(lid)
            return key[0] if key is not None else None

    def shard_of(self, lid: LayerID) -> Optional[str]:
        with self._lock:
            key = self._by_layer.get(lid)
            return key[1] if key is not None else None

    def codec_of(self, lid: LayerID) -> Optional[str]:
        with self._lock:
            key = self._by_layer.get(lid)
            return key[2] if key is not None else None

    def size(self) -> int:
        with self._lock:
            return len(self._by_layer)


class ContentIndex:
    """Leader-side (digest, shard, codec) → holders map.

    An announce is the node's authoritative inventory, so
    :meth:`reset_node` replaces that node's contribution wholesale
    (a restarted node no longer vouches for its dead incarnation's
    bytes); an ack extends it (the delivered copy verified against the
    stamped digest before acking)."""

    def __init__(self):
        self._lock = threading.Lock()
        # node -> {layer: (digest, shard, codec)}; digest->holders is
        # derived.
        self._node_layers: Dict[NodeID, Dict[LayerID, ContentKey]] = {}

    def reset_node(self, node: NodeID,
                   digests: Optional[Dict[LayerID, str]] = None) -> None:
        """Replace a node's vouching with its announce-time FULL-layer
        canonical digests (shard and codec holdings announce no layer
        digest — a range or encoded-form hash as a layer digest would
        poison the stamp collection)."""
        with self._lock:
            if digests:
                self._node_layers[node] = {
                    int(l): (str(d), "", "") for l, d in digests.items()}
            else:
                self._node_layers.pop(node, None)

    def add(self, node: NodeID, lid: LayerID, digest: Optional[str],
            shard: str = "", codec: str = "") -> None:
        if not digest:
            return
        with self._lock:
            self._node_layers.setdefault(node, {})[lid] = (
                str(digest), str(shard), str(codec))

    def drop_node(self, node: NodeID) -> None:
        with self._lock:
            self._node_layers.pop(node, None)

    def node_has(self, node: NodeID, digest: str, shard: str = "",
                 codec: str = "") -> bool:
        """Whether ``node`` provably holds bytes hashing to ``digest``
        over exactly ``shard``'s range in exactly ``codec``'s form,
        under ANY layer id.  A full-layer raw query never matches a
        shard- or codec-vouched holding."""
        if not digest:
            return False
        key = (str(digest), str(shard), str(codec))
        with self._lock:
            return key in (self._node_layers.get(node) or {}).values()

    def node_digests(self, node: NodeID) -> Dict[LayerID, str]:
        """``node``'s FULL-layer canonical holdings: {layer: digest}
        (shard- and codec-vouched entries excluded) — the delta base
        CANDIDATE set: any of these digests names bytes the dest can
        provably reconstruct against (docs/codec.md)."""
        with self._lock:
            return {lid: k[0]
                    for lid, k in (self._node_layers.get(node)
                                   or {}).items()
                    if not k[1] and not k[2]}

    def holders(self, digest: str, shard: str = "",
                codec: str = "") -> List[Tuple[NodeID, LayerID]]:
        """Every (node, layer) currently vouched for (digest, shard,
        codec), sorted."""
        key = (str(digest), str(shard), str(codec))
        out: List[Tuple[NodeID, LayerID]] = []
        with self._lock:
            for node in sorted(self._node_layers):
                for lid, k in sorted(self._node_layers[node].items()):
                    if k == key:
                        out.append((node, lid))
        return out
