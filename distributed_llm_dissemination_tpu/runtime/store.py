"""Content-addressed layer store (docs/service.md).

Layers are keyed by their PR-4 digest stamp (``xxh3:<hex>`` /
blake2b hex — ``utils.integrity.layer_digest``), which turns the layer
store into a content store: a v2 rollout whose layer 103 hashes the same
as the v1 layer 3 a node already holds resolves LOCALLY — zero wire
bytes — and a repaired node refills from whichever CURRENT holder is
nearest/fastest, not the original seeder.

Two small classes, two sides of the same key:

- :class:`ContentStore` — the NODE half: digest → locally held layer
  ids.  Populated wherever this process provably knows a layer's bytes
  hash to a digest (its own announce-time hash, an ack-gate verify);
  consulted when the leader's digest stamp names an ASSIGNED layer this
  node doesn't hold — a digest hit aliases the held bytes under the new
  layer id and acks instantly.
- :class:`ContentIndex` — the LEADER half: digest → (node, layer)
  holders, rebuilt from announces (authoritative per node) and extended
  by acks (the leader stamped the digest, so a delivered copy provably
  carries it).  The planner skips shipping a (dest, layer) pair whose
  digest the dest already holds under ANY layer id — the dest's own
  resolve-and-ack completes the pair.

Digest trust model: both sides only index digests that were locally
verified (node) or announced/stamped through the PR-4 integrity plane
(leader) — the same trust the digest verification gate already places
in those sources (docs/integrity.md).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from ..core.types import LayerID, NodeID


class ContentStore:
    """digest → layer ids this node holds with those exact bytes."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_digest: Dict[str, Set[LayerID]] = {}
        self._by_layer: Dict[LayerID, str] = {}

    def index(self, lid: LayerID, digest: str) -> None:
        if not digest:
            return
        with self._lock:
            old = self._by_layer.get(lid)
            if old == digest:
                return
            if old is not None:
                ids = self._by_digest.get(old)
                if ids is not None:
                    ids.discard(lid)
                    if not ids:
                        del self._by_digest[old]
            self._by_layer[lid] = digest
            self._by_digest.setdefault(digest, set()).add(lid)

    def forget(self, lid: LayerID) -> None:
        """Drop a layer (demoted as corrupt, evicted): its bytes can no
        longer vouch for the digest."""
        with self._lock:
            digest = self._by_layer.pop(lid, None)
            if digest is not None:
                ids = self._by_digest.get(digest)
                if ids is not None:
                    ids.discard(lid)
                    if not ids:
                        del self._by_digest[digest]

    def lookup(self, digest: str) -> Optional[LayerID]:
        """A local layer id holding these bytes (lowest id for
        determinism), or None."""
        with self._lock:
            ids = self._by_digest.get(digest)
            return min(ids) if ids else None

    def digest_of(self, lid: LayerID) -> Optional[str]:
        with self._lock:
            return self._by_layer.get(lid)

    def size(self) -> int:
        with self._lock:
            return len(self._by_layer)


class ContentIndex:
    """Leader-side digest → holders map.

    An announce is the node's authoritative inventory, so
    :meth:`reset_node` replaces that node's contribution wholesale
    (a restarted node no longer vouches for its dead incarnation's
    bytes); an ack extends it (the delivered copy verified against the
    stamped digest before acking)."""

    def __init__(self):
        self._lock = threading.Lock()
        # node -> {layer: digest}; the digest->holders view is derived.
        self._node_layers: Dict[NodeID, Dict[LayerID, str]] = {}

    def reset_node(self, node: NodeID,
                   digests: Optional[Dict[LayerID, str]] = None) -> None:
        with self._lock:
            if digests:
                self._node_layers[node] = {
                    int(l): str(d) for l, d in digests.items()}
            else:
                self._node_layers.pop(node, None)

    def add(self, node: NodeID, lid: LayerID, digest: Optional[str]) -> None:
        if not digest:
            return
        with self._lock:
            self._node_layers.setdefault(node, {})[lid] = digest

    def drop_node(self, node: NodeID) -> None:
        with self._lock:
            self._node_layers.pop(node, None)

    def node_has(self, node: NodeID, digest: str) -> bool:
        """Whether ``node`` provably holds bytes hashing to ``digest``
        under ANY layer id."""
        if not digest:
            return False
        with self._lock:
            return digest in (self._node_layers.get(node) or {}).values()

    def holders(self, digest: str) -> List[Tuple[NodeID, LayerID]]:
        """Every (node, layer) currently vouched for the digest, sorted."""
        out: List[Tuple[NodeID, LayerID]] = []
        with self._lock:
            for node in sorted(self._node_layers):
                for lid, d in sorted(self._node_layers[node].items()):
                    if d == digest:
                        out.append((node, lid))
        return out
