"""The negotiated wire-codec plane (docs/codec.md).

``models/quant.py`` defines the codecs (deterministic int8/int4 encodings
of a model blob); this module is the RUNTIME half every node role shares:

- **capability**: what codecs this process can encode/decode — announced
  to the leader (``AnnounceMsg.codecs``), consulted when the leader
  chooses a codec per (dest, layer) transfer;
- **sender service**: the bounded encoded-form cache.  A raw holder
  commanded to ship (or NACK-retransmit) a layer at codec ``c`` encodes
  ONCE and serves every byte range — flow fragments, stripe splits,
  retransmits — from the cached encoded blob, so the encoded byte space
  is stable across re-sends (a re-encoded range must be byte-identical,
  which quant's deterministic round-to-nearest guarantees, but caching
  also keeps the encode cost off every retransmit);
- **identity**: the codec-qualified digest of a layer's encoded form,
  cached per (layer, codec) — what the leader stamps so a quantized
  copy verifies (and acks) under its OWN byte identity, never raw's.

Everything degrades to raw: a plane that can't encode a layer (size
mismatch — dummy bytes, not model blobs), a dest that never advertised
the codec, or a missing model config all leave the transfer canonical.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..core.types import LayerID, LayerLocation, LayerMeta, LayerSrc
from ..utils import integrity, trace
from ..utils.logging import log

# Links whose modeled bottleneck rate (bytes/s) is at or below this ship
# quantized when a WireCodec is configured; faster links stay raw — at
# NIC rates the wire is cheaper than the encode/decode pass (measure on
# the running host with quant.codec_bench; TTD_MATRIX records it).
CODEC_MIN_RATE_DEFAULT = 64 << 20  # 64 MiB/s

# Sender-side encoded-form cache budget (bytes).  One entry per
# (layer, codec) actively being served; eviction is LRU.
CODEC_CACHE_BYTES_DEFAULT = 1 << 30


class WireCodecPlane:
    """Per-process wire-codec capability + encoded-form cache."""

    def __init__(self, cfg, model_codec: str = "raw",
                 wire_codec: str = "raw"):
        """``cfg``: the run's ``models.llama.ModelConfig`` (blob layouts
        — encoded sizes derive from it).  ``model_codec``: the canonical
        form the run's blobs are fabricated in; wire codecs only apply
        over raw canonicals (core/config.py refuses the combination at
        parse time, this just re-checks).  ``wire_codec``: the codec
        this run ALLOWS on slow links ("raw" = the plane is
        capability-only: this node can decode/serve codecs a leader
        chooses, but a leader built with it never chooses one)."""
        self.cfg = cfg
        self.model_codec = model_codec
        self.wire_codec = wire_codec if model_codec == "raw" else "raw"
        self._lock = threading.Lock()
        self._cache: Dict[Tuple[LayerID, str], bytes] = {}
        self._cache_bytes = 0
        self._digests: Dict[Tuple[LayerID, str], str] = {}
        try:
            self.min_rate = int(os.environ.get(
                "DLD_CODEC_MIN_RATE", str(CODEC_MIN_RATE_DEFAULT)))
        except ValueError:
            self.min_rate = CODEC_MIN_RATE_DEFAULT
        try:
            self.cache_budget = int(os.environ.get(
                "DLD_CODEC_CACHE_BYTES", str(CODEC_CACHE_BYTES_DEFAULT)))
        except ValueError:
            self.cache_budget = CODEC_CACHE_BYTES_DEFAULT

    # ------------------------------------------------------------ capability

    @property
    def enabled(self) -> bool:
        """Whether this run may CHOOSE quantized transfers (leader
        side).  Capability (decode/serve) is independent — see
        :meth:`decode_codecs`."""
        return (self.wire_codec in ("int8", "int4")
                and self.model_codec == "raw"
                and os.environ.get("DLD_WIRE_CODEC", "1") != "0")

    def decode_codecs(self) -> List[str]:
        """The codecs this process can DECODE (and encode — both need
        only quant + the model config), announced to the leader.  Empty
        when the canonical form isn't raw (a decoded int8-of-int8 blob
        would be meaningless) or the plane is env-disabled."""
        if (self.model_codec != "raw"
                or os.environ.get("DLD_WIRE_CODEC", "1") == "0"):
            return []
        return ["int8", "int4"]

    # --------------------------------------------------------------- sizing

    def nbytes(self, lid: LayerID, codec: str) -> Optional[int]:
        """Exact wire size of layer ``lid`` under ``codec``, or None for
        ids outside the model's blob range (those transfers stay raw)."""
        from ..models import quant, serde

        if lid > serde.head_blob_id(self.cfg):
            return None
        try:
            return quant.blob_nbytes_codec(self.cfg, lid, codec)
        except (ValueError, KeyError):
            return None

    def decoded_nbytes(self, lid: LayerID) -> Optional[int]:
        """The canonical (raw) byte count of layer ``lid`` — what a
        quantized delivery decodes back into."""
        return self.nbytes(lid, "raw")

    # ------------------------------------------------------- encoded serving

    def encoded_src(self, lid: LayerID, layer: LayerSrc,
                    codec: str) -> Optional[LayerSrc]:
        """A ``LayerSrc`` over the ENCODED form of a raw holding —
        cached, so flow fragments, stripes, and NACK retransmits all
        read byte ranges of ONE stable encoded blob.  None when the
        layer can't encode (wrong size for the model's blob layout, or
        unreadable bytes) — the caller must refuse, loudly, rather than
        ship raw bytes a dest will account in encoded space."""
        enc = self._encoded_bytes(lid, layer, codec)
        if enc is None:
            return None
        return LayerSrc(
            inmem_data=enc, data_size=len(enc), offset=0,
            meta=LayerMeta(location=LayerLocation.INMEM,
                           limit_rate=layer.meta.limit_rate,
                           source_type=layer.meta.source_type,
                           codec=codec),
        )

    def _encoded_bytes(self, lid: LayerID, layer: LayerSrc,
                       codec: str) -> Optional[bytearray]:
        want = self.nbytes(lid, codec)
        raw_size = self.decoded_nbytes(lid)
        if want is None or raw_size is None:
            return None
        if getattr(layer.meta, "codec", ""):
            return None  # only canonical bytes encode
        key = (lid, codec)
        # One canonical content per layer id per process (the layer
        # store holds one record per id), so (lid, codec) keys the
        # cache; the deterministic encode makes every producer agree.
        with self._lock:
            enc = self._cache.get(key)
            if enc is not None:
                self._cache[key] = self._cache.pop(key)  # LRU touch
                return enc
        try:
            raw = layer.read_range()
        except (OSError, ValueError) as e:
            log.error("wire-codec encode: layer bytes unreadable",
                      layerID=lid, err=repr(e))
            return None
        if len(raw) != raw_size:
            log.error("wire-codec encode refused: holding is not a "
                      "model blob (size mismatch)", layerID=lid,
                      have=len(raw), want=raw_size)
            return None
        from ..models import quant

        t0 = time.monotonic()
        enc = bytearray(quant.encode_blob(self.cfg, lid, raw, codec))
        dt = time.monotonic() - t0
        trace.count("codec.encoded_blobs")
        trace.count("codec.encoded_bytes", len(enc))
        trace.add_phase("codec_encode", dt)
        log.info("layer encoded for wire codec", layerID=lid, codec=codec,
                 raw_bytes=len(raw), encoded_bytes=len(enc),
                 encode_ms=round(dt * 1000, 1))
        with self._lock:
            if key not in self._cache:
                self._cache[key] = enc
                self._cache_bytes += len(enc)
                while (self._cache_bytes > self.cache_budget
                       and len(self._cache) > 1):
                    old_key = next(iter(self._cache))
                    if old_key == key:
                        break
                    self._cache_bytes -= len(self._cache.pop(old_key))
            return self._cache[key]

    # -------------------------------------------------------------- identity

    def encoded_digest(self, lid: LayerID, layer: LayerSrc,
                       codec: str) -> Optional[str]:
        """The codec-qualified digest the leader stamps for a quantized
        transfer: the digest of exactly the encoded bytes, cached per
        (layer, codec).  None when the layer can't encode here — the
        pair then stays raw (docs/codec.md, honest limits)."""
        key = (lid, codec)
        with self._lock:
            d = self._digests.get(key)
        if d is not None:
            return d
        enc = self._encoded_bytes(lid, layer, codec)
        if enc is None:
            return None
        d = integrity.layer_digest(memoryview(enc))
        with self._lock:
            self._digests[key] = d
        return d
