"""The negotiated wire-codec plane (docs/codec.md).

``models/quant.py`` defines the codecs (deterministic int8/int4 encodings
of a model blob); this module is the RUNTIME half every node role shares:

- **capability**: what codecs this process can encode/decode — announced
  to the leader (``AnnounceMsg.codecs``), consulted when the leader
  chooses a codec per (dest, layer) transfer;
- **sender service**: the bounded encoded-form cache.  A raw holder
  commanded to ship (or NACK-retransmit) a layer at codec ``c`` encodes
  ONCE and serves every byte range — flow fragments, stripe splits,
  retransmits — from the cached encoded blob, so the encoded byte space
  is stable across re-sends (a re-encoded range must be byte-identical,
  which quant's deterministic round-to-nearest guarantees, but caching
  also keeps the encode cost off every retransmit);
- **identity**: the codec-qualified digest of a layer's encoded form,
  cached per (layer, codec) — what the leader stamps so a quantized
  copy verifies (and acks) under its OWN byte identity, never raw's.

Everything degrades to raw: a plane that can't encode a layer (size
mismatch — dummy bytes, not model blobs), a dest that never advertised
the codec, or a missing model config all leave the transfer canonical.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..core.types import (
    LayerID,
    LayerLocation,
    LayerMeta,
    LayerSrc,
    codec_capability,
    delta_base_digest,
)
from ..utils import integrity, trace
from ..utils.logging import log

# Links whose modeled bottleneck rate (bytes/s) is at or below this ship
# quantized when a WireCodec is configured; faster links stay raw — at
# NIC rates the wire is cheaper than the encode/decode pass (measure on
# the running host with quant.codec_bench; TTD_MATRIX records it).
CODEC_MIN_RATE_DEFAULT = 64 << 20  # 64 MiB/s

# The entropy forms' threshold: the DLE1 pass costs an extra host
# byte-walk both ways, so they only pay on links at least as slow as
# the plain quantized forms' crossover (codec_bench measures the real
# rates on the running container).
ENTROPY_MIN_RATE_DEFAULT = CODEC_MIN_RATE_DEFAULT

# The content-delta threshold: XOR + DLE1 runs at GB/s and the byte win
# on a lightly-perturbed v2 is order-of-magnitude, so delta pays on much
# faster links than whole-form quantization does.
DELTA_MIN_RATE_DEFAULT = 256 << 20  # 256 MiB/s

# Sender-side encoded-form cache budget (bytes).  One entry per
# (layer, codec) actively being served; eviction is LRU.
CODEC_CACHE_BYTES_DEFAULT = 1 << 30

# The whole-form codec ids this plane can announce, choose, and serve.
# "delta" is the announced CAPABILITY behind the parameterized
# "delta:<base_digest_hex>" codec strings a leader actually stamps
# (core/types.codec_capability).
WHOLE_FORM_CODECS = ("int8", "int4", "int8e", "int4e")
ENTROPY_FORMS = ("int8e", "int4e")


class WireCodecPlane:
    """Per-process wire-codec capability + encoded-form cache."""

    def __init__(self, cfg, model_codec: str = "raw",
                 wire_codec: str = "raw"):
        """``cfg``: the run's ``models.llama.ModelConfig`` (blob layouts
        — encoded sizes derive from it).  ``model_codec``: the canonical
        form the run's blobs are fabricated in; wire codecs only apply
        over raw canonicals (core/config.py refuses the combination at
        parse time, this just re-checks).  ``wire_codec``: the codec
        this run ALLOWS on slow links ("raw" = the plane is
        capability-only: this node can decode/serve codecs a leader
        chooses, but a leader built with it never chooses one)."""
        self.cfg = cfg
        self.model_codec = model_codec
        self.wire_codec = wire_codec if model_codec == "raw" else "raw"
        self._lock = threading.Lock()
        self._cache: Dict[Tuple[LayerID, str], bytes] = {}
        self._cache_bytes = 0
        self._digests: Dict[Tuple[LayerID, str], str] = {}
        # True encoded sizes for DATA-DEPENDENT forms (entropy, delta):
        # (lid, codec) -> len(encoded).  Model-derivable codecs never
        # land here — ``nbytes`` computes them from the config.
        self._sizes: Dict[Tuple[LayerID, str], int] = {}
        # digest -> LayerSrc of locally VERIFIED canonical bytes, wired
        # by the owning role (leader: its goal digests + store;
        # receiver: its content store).  The delta encode/decode base
        # lookup — None means this process can neither produce nor
        # price delta forms.
        self.base_resolver = None
        self.min_rate = self._env_rate(
            "DLD_CODEC_MIN_RATE", CODEC_MIN_RATE_DEFAULT)
        self.entropy_min_rate = self._env_rate(
            "DLD_ENTROPY_MIN_RATE", ENTROPY_MIN_RATE_DEFAULT)
        self.delta_min_rate = self._env_rate(
            "DLD_DELTA_MIN_RATE", DELTA_MIN_RATE_DEFAULT)
        self.cache_budget = self._env_rate(
            "DLD_CODEC_CACHE_BYTES", CODEC_CACHE_BYTES_DEFAULT)

    @staticmethod
    def _env_rate(name: str, default: int) -> int:
        try:
            return int(os.environ.get(name, str(default)))
        except ValueError:
            return default

    # ------------------------------------------------------------ capability

    @property
    def enabled(self) -> bool:
        """Whether this run may CHOOSE quantized transfers (leader
        side).  Capability (decode/serve) is independent — see
        :meth:`decode_codecs`."""
        return (self.wire_codec in WHOLE_FORM_CODECS
                and self.model_codec == "raw"
                and os.environ.get("DLD_WIRE_CODEC", "1") != "0")

    @property
    def delta_enabled(self) -> bool:
        """Whether this run may CHOOSE content-delta transfers (leader
        side).  On by default — a delta is only ever chosen when the
        dest PROVABLY holds the base, so there is no cold-start
        regression to opt out of — env-gated for operators who want the
        old behavior (``DLD_DELTA_CODEC=0``)."""
        return (self.model_codec == "raw"
                and os.environ.get("DLD_WIRE_CODEC", "1") != "0"
                and os.environ.get("DLD_DELTA_CODEC", "1") != "0")

    def decode_codecs(self) -> List[str]:
        """The codecs this process can DECODE (and encode — quantized
        forms need quant + the model config; "delta" is the generic
        capability behind ``"delta:<hex>"`` strings and needs only the
        entropy coder + a verified base), announced to the leader.
        Empty when the canonical form isn't raw (a decoded int8-of-int8
        blob would be meaningless) or the plane is env-disabled."""
        if (self.model_codec != "raw"
                or os.environ.get("DLD_WIRE_CODEC", "1") == "0"):
            return []
        return list(WHOLE_FORM_CODECS) + ["delta"]

    def min_rate_for(self, codec: str) -> int:
        """The negotiation threshold for ``codec``'s family: a pair only
        ships this form when its modeled bottleneck is at or below the
        family's measured crossover (quant.codec_bench)."""
        cap = codec_capability(codec)
        if cap == "delta":
            return self.delta_min_rate
        if cap in ENTROPY_FORMS:
            return self.entropy_min_rate
        return self.min_rate

    # --------------------------------------------------------------- sizing

    def nbytes(self, lid: LayerID, codec: str) -> Optional[int]:
        """Exact wire size of layer ``lid`` under ``codec``, or None
        when it isn't knowable here: ids outside the model's blob range,
        and DATA-DEPENDENT forms (entropy, delta) that haven't been
        sized by an actual encode yet (:meth:`ensure_sized`) — callers
        seeing None keep the transfer raw."""
        if not codec or codec == "raw":
            return self.decoded_nbytes(lid)
        with self._lock:
            sized = self._sizes.get((lid, codec))
        if sized is not None:
            return sized
        if self.cfg is None:
            return None
        from ..models import quant, serde

        if lid > serde.head_blob_id(self.cfg):
            return None
        try:
            return quant.blob_nbytes_codec(self.cfg, lid, codec)
        except (ValueError, KeyError):
            return None

    def ensure_sized(self, lid: LayerID, layer: Optional[LayerSrc],
                     codec: str) -> Optional[int]:
        """The TRUE wire size of ``lid`` under ``codec``, encoding the
        held ``layer`` once (cached — both the bytes and the size) when
        the size is data-dependent.  This is how the solver prices
        entropy/delta pairs at their real encoded size instead of a
        guess; None = can't encode here, the pair must not ship this
        form."""
        n = self.nbytes(lid, codec)
        if n is not None or layer is None:
            return n
        enc = self._encoded_bytes(lid, layer, codec)
        return len(enc) if enc is not None else None

    def decoded_nbytes(self, lid: LayerID) -> Optional[int]:
        """The canonical (raw) byte count of layer ``lid`` — what a
        quantized delivery decodes back into.  None when no model config
        is attached (synthetic layers: raw size is the holding's own)."""
        if self.cfg is None:
            return None
        from ..models import quant, serde

        if lid > serde.head_blob_id(self.cfg):
            return None
        try:
            return quant.blob_nbytes_codec(self.cfg, lid, "raw")
        except (ValueError, KeyError):
            return None

    # ------------------------------------------------------- encoded serving

    def encoded_src(self, lid: LayerID, layer: LayerSrc,
                    codec: str) -> Optional[LayerSrc]:
        """A ``LayerSrc`` over the ENCODED form of a raw holding —
        cached, so flow fragments, stripes, and NACK retransmits all
        read byte ranges of ONE stable encoded blob.  None when the
        layer can't encode (wrong size for the model's blob layout, or
        unreadable bytes) — the caller must refuse, loudly, rather than
        ship raw bytes a dest will account in encoded space."""
        enc = self._encoded_bytes(lid, layer, codec)
        if enc is None:
            return None
        return LayerSrc(
            inmem_data=enc, data_size=len(enc), offset=0,
            meta=LayerMeta(location=LayerLocation.INMEM,
                           limit_rate=layer.meta.limit_rate,
                           source_type=layer.meta.source_type,
                           codec=codec),
        )

    def _encoded_bytes(self, lid: LayerID, layer: LayerSrc,
                       codec: str) -> Optional[bytearray]:
        cap = codec_capability(codec)
        delta = cap == "delta"
        raw_size = self.decoded_nbytes(lid)
        if not delta:
            # Whole-form codecs need the model's blob layout; entropy
            # forms are sized by this very encode, the rest up front.
            if raw_size is None:
                return None
            if cap not in ENTROPY_FORMS and self.nbytes(lid, codec) is None:
                return None
        if getattr(layer.meta, "codec", ""):
            return None  # only canonical bytes encode
        key = (lid, codec)
        # One canonical content per layer id per process (the layer
        # store holds one record per id), so (lid, codec) keys the
        # cache; the deterministic encode makes every producer agree.
        # Delta strings carry their base digest, so a re-based choice
        # is simply a different key.
        with self._lock:
            enc = self._cache.get(key)
            if enc is not None:
                self._cache[key] = self._cache.pop(key)  # LRU touch
                return enc
        try:
            raw = layer.read_range()
        except (OSError, ValueError) as e:
            log.error("wire-codec encode: layer bytes unreadable",
                      layerID=lid, err=repr(e))
            return None
        if not delta and len(raw) != raw_size:
            log.error("wire-codec encode refused: holding is not a "
                      "model blob (size mismatch)", layerID=lid,
                      have=len(raw), want=raw_size)
            return None

        t0 = time.monotonic()
        if delta:
            enc = self._delta_bytes(lid, raw, codec)
            if enc is None:
                return None
        else:
            from ..models import quant

            enc = bytearray(quant.encode_blob(self.cfg, lid, raw, codec))
        dt = time.monotonic() - t0
        trace.count("codec.encoded_blobs")
        trace.count("codec.encoded_bytes", len(enc))
        trace.add_phase("codec_encode", dt)
        log.info("layer encoded for wire codec", layerID=lid, codec=codec,
                 raw_bytes=len(raw), encoded_bytes=len(enc),
                 encode_ms=round(dt * 1000, 1))
        with self._lock:
            self._sizes[key] = len(enc)
            if key not in self._cache:
                self._cache[key] = enc
                self._cache_bytes += len(enc)
                while (self._cache_bytes > self.cache_budget
                       and len(self._cache) > 1):
                    old_key = next(iter(self._cache))
                    if old_key == key:
                        break
                    self._cache_bytes -= len(self._cache.pop(old_key))
            return self._cache[key]

    # ----------------------------------------------------------------- delta

    def resolve_base(self, digest: str) -> Optional[LayerSrc]:
        """The locally VERIFIED canonical bytes hashing to ``digest``,
        via the role-wired ``base_resolver`` — None when this process
        can't vouch for any such holding (delta encode/decode refused,
        loudly, by the callers)."""
        resolver = self.base_resolver
        if resolver is None or not digest:
            return None
        try:
            return resolver(digest)
        except Exception as e:  # noqa: BLE001 — a resolver bug degrades
            log.error("delta base resolver failed", digest=digest,
                      err=repr(e))  # to raw, never crashes the plane
            return None

    def _delta_bytes(self, lid: LayerID, raw,
                     codec: str) -> Optional[bytearray]:
        """Encode ``raw`` against the base the codec string names.  The
        sender must hold a VERIFIED copy of the base — encoding against
        unverified bytes would ship a well-formed delta that
        reconstructs garbage (caught by the full-form digest, but only
        after burning the transfer)."""
        from ..models import entropy

        base_digest = delta_base_digest(codec)
        base = self.resolve_base(base_digest)
        if base is None:
            log.warn("delta encode refused: base not held/verified "
                     "here", layerID=lid, base=base_digest)
            return None
        try:
            base_raw = base.read_range()
        except (OSError, ValueError) as e:
            log.error("delta encode: base bytes unreadable",
                      layerID=lid, base=base_digest, err=repr(e))
            return None
        if len(base_raw) != len(raw):
            log.warn("delta encode refused: base length mismatch",
                     layerID=lid, base=base_digest,
                     base_bytes=len(base_raw), layer_bytes=len(raw))
            return None
        return bytearray(entropy.delta_encode(raw, base_raw))

    def delta_reconstruct(self, lid: LayerID, data,
                          codec: str) -> Optional[bytes]:
        """Receiver side: full raw bytes from a delivered delta stream —
        the base comes from THIS node's verified holdings (the leader
        only stamps a delta when the dest provably holds the base, so a
        miss here means local state regressed; None sends the pair back
        for a raw replan)."""
        from ..models import entropy

        base_digest = delta_base_digest(codec)
        base = self.resolve_base(base_digest)
        if base is None:
            log.warn("delta reconstruct refused: base not held here",
                     layerID=lid, base=base_digest)
            return None
        try:
            base_raw = base.read_range()
            out = entropy.delta_decode(data, base_raw)
        except (OSError, ValueError) as e:
            log.error("delta reconstruct failed", layerID=lid,
                      base=base_digest, err=repr(e))
            return None
        trace.count("codec.delta_reconstructed")
        return out

    # -------------------------------------------------------------- identity

    def encoded_digest(self, lid: LayerID, layer: LayerSrc,
                       codec: str) -> Optional[str]:
        """The codec-qualified digest the leader stamps for a quantized
        transfer: the digest of exactly the encoded bytes, cached per
        (layer, codec).  None when the layer can't encode here — the
        pair then stays raw (docs/codec.md, honest limits)."""
        key = (lid, codec)
        with self._lock:
            d = self._digests.get(key)
        if d is not None:
            return d
        enc = self._encoded_bytes(lid, layer, codec)
        if enc is None:
            return None
        d = integrity.layer_digest(memoryview(enc))
        with self._lock:
            self._digests[key] = d
        return d
