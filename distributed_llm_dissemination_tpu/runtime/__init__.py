from .checkpoint import LayerCheckpointStore, map_through_gaps  # noqa: F401
from .client import Client  # noqa: F401
from .failover import (  # noqa: F401
    ControlReplicator,
    ShadowLeaderState,
    StandbyController,
)
from .failure import FailureDetector, HeartbeatSender  # noqa: F401
from .hierarchy import (  # noqa: F401
    SubLeaderController,
    groups_from_config,
    partition_groups,
)
from .leader import (  # noqa: F401
    FlowRetransmitLeaderNode,
    HierarchicalFlowLeaderNode,
    LeaderNode,
    PullRetransmitLeaderNode,
    RetransmitLeaderNode,
    assignment_satisfied,
)
from .membership import MembershipTable, MemberRecord  # noqa: F401
from .node import MessageLoop, Node  # noqa: F401
from .receiver import (  # noqa: F401
    FlowRetransmitReceiverNode,
    ReceiverNode,
    RetransmitReceiverNode,
)
from .send import fetch_from_client, handle_flow_retransmit, send_layer  # noqa: F401
from .store import ContentIndex, ContentStore  # noqa: F401
