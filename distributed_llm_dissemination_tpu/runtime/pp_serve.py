"""Pod-level pipelined serving from disseminated stage weights.

The Assignment IS a pipeline placement (SURVEY §2.3): dissemination lands
each stage's layer slice on that stage's devices and the per-node boot
proves the slice usable (``runtime/boot.py`` stage boots).  This module
closes the last gap — the POD serves as one model:

1. ``assemble_pp_params`` lifts each stage's resident stacked params
   (``BootResult.params``, already on the stage's devices) into global
   pipeline-sharded arrays — ``make_array_from_single_device_arrays``
   over the full mesh, so NO weight bytes move; the head leaves (held by
   whichever stage received the head blob) are broadcast mesh-wide over
   ICI (the one small replicated piece).
2. ``pod_forward`` runs ``models.sharded.build_pp_forward``: activations
   hand off stage→stage by ``ppermute``, logits valid on stage 0.

Single-controller scope (``cli/podrun.py``), like the pod fabric: one
process addresses the mesh.  The multi-controller analogue is the same
program entered by every process — the lockstep machinery exists
(``parallel/spmd_fabric.py``) but serving over it is future work.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..utils.logging import log


def _stage_order(cfg, placement, results) -> Optional[list]:
    """Stage-ordered list of (node, stacked-params) when the boots form a
    full, even partition of the layers; None (with a log) otherwise."""
    staged = {n: r for n, r in results.items()
              if r is not None and r.kind == "stage" and r.params is not None}
    if not staged:
        return None
    by_stage = sorted(staged, key=lambda n: placement.node_to_stage[n])
    covered = [lid for n in by_stage for lid in staged[n].layer_ids]
    if covered != list(range(cfg.n_layers)):
        log.info("pod serve skipped: stage boots don't partition the "
                 "layers", covered=covered)
        return None
    counts = {len(staged[n].layer_ids) for n in by_stage}
    if len(counts) != 1:
        log.info("pod serve skipped: uneven stage sizes", counts=counts)
        return None
    return [(n, staged[n].params) for n in by_stage]


def _head_leaves(cfg, stores, codec: str):
    """Decode embed/ln_f/lm_head from whichever node's store holds the
    head blob (device path when it landed in HBM)."""
    from ..models import serde
    from .boot import decode_head

    head_id = serde.head_blob_id(cfg)
    for node_id, layers in stores.items():
        src = layers.get(head_id)
        if src is not None:
            return decode_head(cfg, src, codec)
    return None


def assemble_pp_params(cfg, placement, results: Dict[int, Any],
                       stores: Dict[int, Any], codec: str = "raw"):
    """Global pipeline-sharded params from the stage boots' resident
    arrays; None when the pod doesn't form a servable pipeline."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    order = _stage_order(cfg, placement, results)
    if order is None:
        return None
    head = _head_leaves(cfg, stores, codec)
    if head is None:
        log.info("pod serve skipped: no node holds the head blob")
        return None
    pp_axis = placement.pipeline_axis
    # Serve on the SUB-mesh of exactly the booted stages: a pod fabric
    # maps seeders and the leader onto stages too, and those hold no
    # model slice.
    from jax.sharding import Mesh

    k = list(placement.mesh.axis_names).index(pp_axis)
    stage_idx = [placement.node_to_stage[n] for n, _ in order]
    mesh = Mesh(np.take(placement.mesh.devices, stage_idx, axis=k),
                placement.mesh.axis_names)

    flat_devices = list(np.ravel(mesh.devices))
    layers_global = {}
    leaf_names = list(order[0][1].keys())
    for name in leaf_names:
        shards = {}
        for node_id, stacked in order:
            stage = placement.node_to_stage[node_id]
            leaf = jax.device_put(
                stacked[name],
                NamedSharding(placement.stage_mesh(stage), P()),
            )
            for s in leaf.addressable_shards:
                shards[s.device] = s.data
        per_dev = [shards[d] for d in flat_devices]
        slice_shape = per_dev[0].shape
        global_shape = (cfg.n_layers,) + slice_shape[1:]
        spec = P(*([pp_axis] + [None] * (len(slice_shape) - 1)))
        layers_global[name] = jax.make_array_from_single_device_arrays(
            global_shape, NamedSharding(mesh, spec), per_dev
        )
    head = {
        name: jax.device_put(jnp.asarray(a), NamedSharding(mesh, P()))
        for name, a in head.items()
    }
    return mesh, layers_global, head


def pod_forward(cfg, placement, results, stores, tokens=None,
                codec: str = "raw"):
    """One pipelined forward across the pod's stages from the landed
    weights; returns (logits, seconds) or None when not servable."""
    import time

    import jax
    import jax.numpy as jnp

    from ..models.sharded import build_pp_forward

    assembled = assemble_pp_params(cfg, placement, results, stores, codec)
    if assembled is None:
        return None
    mesh, layers_global, head = assembled
    if tokens is None:
        tokens = jnp.zeros((1, 16), jnp.int32)
    t0 = time.monotonic()
    fwd = build_pp_forward(cfg, mesh, placement.pipeline_axis)
    logits = fwd(layers_global, head, tokens)
    jax.block_until_ready(logits)
    dt = time.monotonic() - t0
    log.info("pod pipelined forward from staged weights",
             stages=mesh.shape[placement.pipeline_axis],
             seconds=round(dt, 3))
    return logits, dt
