"""Pod-level pipelined serving from disseminated stage weights.

The Assignment IS a pipeline placement (SURVEY §2.3): dissemination lands
each stage's layer slice on that stage's devices and the per-node boot
proves the slice usable (``runtime/boot.py`` stage boots).  This module
closes the last gap — the POD serves as one model:

1. ``assemble_pp_params`` lifts each stage's resident stacked params
   (``BootResult.params``, already on the stage's devices) into global
   pipeline-sharded arrays — ``make_array_from_single_device_arrays``
   over the full mesh, so NO weight bytes move; the head leaves (held by
   whichever stage received the head blob) are broadcast mesh-wide over
   ICI (the one small replicated piece).
2. ``pod_forward`` runs ``models.sharded.build_pp_forward``: activations
   hand off stage→stage by ``ppermute``, logits valid on stage 0.

Two controller shapes, like the fabric itself:

- single-controller (``cli/podrun.py``): ``pod_forward`` — one process
  addresses the whole mesh;
- multi-controller (``spmd_pod_forward`` / ``spmd_pod_decode``): after
  boots, the leader broadcasts a ``ServeMsg`` and every MEMBER process
  (one per stage) enters the same compiled collective over the sub-mesh
  of the member stages, feeding its local shards — the serving analogue
  of the SPMD fabric's lockstep (``parallel/spmd_fabric.py``).  The head
  blob must be assigned to EVERY stage (the config convention for
  multi-controller serving), since a process can only decode what its
  own store holds.

Generation is first-class: ``pod_decode`` / ``spmd_pod_decode`` run the
KV-cached greedy loop (``models.sharded.build_pp_decode``) across the
stages, and UNEVEN contiguous stage slices serve (padded to the deepest
stage; the counts vector masks the tail) — both lifted in round 4.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..utils.logging import log


def _stage_order(cfg, placement, results) -> Optional[list]:
    """Stage-ordered list of (node, stacked-params, depth) when the boots
    form a full contiguous partition of the layers (UNEVEN slices are
    fine — they pad to the deepest stage); None (with a log) otherwise."""
    staged = {n: r for n, r in results.items()
              if r is not None and r.kind == "stage" and r.params is not None}
    if not staged:
        return None
    by_stage = sorted(staged, key=lambda n: placement.node_to_stage[n])
    covered = [lid for n in by_stage for lid in staged[n].layer_ids]
    if covered != list(range(cfg.n_layers)):
        log.info("pod serve skipped: stage boots don't partition the "
                 "layers", covered=covered)
        return None
    return [(n, staged[n].params, len(staged[n].layer_ids))
            for n in by_stage]


def _pad_stack(leaf, l_max: int):
    """Zero-pad a stacked layer leaf [L, ...] to [l_max, ...] (the padded
    tail is masked out of the pipeline by the counts vector)."""
    import jax.numpy as jnp

    l = leaf.shape[0]
    if l == l_max:
        return leaf
    return jnp.pad(leaf, [(0, l_max - l)] + [(0, 0)] * (leaf.ndim - 1))


def _head_leaves(cfg, stores, codec: str):
    """Decode embed/ln_f/lm_head from whichever node's store holds the
    head blob (device path when it landed in HBM)."""
    from ..models import serde
    from .boot import decode_head

    head_id = serde.head_blob_id(cfg)
    for node_id, layers in stores.items():
        src = layers.get(head_id)
        if src is not None:
            return decode_head(cfg, src, codec)
    return None


def assemble_pp_params(cfg, placement, results: Dict[int, Any],
                       stores: Dict[int, Any], codec: str = "raw"):
    """Global pipeline-sharded params from the stage boots' resident
    arrays; None when the pod doesn't form a servable pipeline.  Returns
    (mesh, layers, counts, head) — slices padded to the deepest stage,
    ``counts`` [pp] carrying each stage's real depth."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    order = _stage_order(cfg, placement, results)
    if order is None:
        return None
    head = _head_leaves(cfg, stores, codec)
    if head is None:
        log.info("pod serve skipped: no node holds the head blob")
        return None
    pp_axis = placement.pipeline_axis
    # Serve on the SUB-mesh of exactly the booted stages: a pod fabric
    # maps seeders and the leader onto stages too, and those hold no
    # model slice.
    mesh = _submesh(placement,
                    [placement.node_to_stage[n] for n, _, _ in order])
    l_max = max(depth for _, _, depth in order)

    flat_devices = list(np.ravel(mesh.devices))
    layers_global = {}
    leaf_names = list(order[0][1].keys())
    for name in leaf_names:
        shards = {}
        for node_id, stacked, _depth in order:
            stage = placement.node_to_stage[node_id]
            leaf = jax.device_put(
                _pad_stack(stacked[name], l_max),
                NamedSharding(placement.stage_mesh(stage), P()),
            )
            for s in leaf.addressable_shards:
                shards[s.device] = s.data
        per_dev = [shards[d] for d in flat_devices]
        slice_shape = per_dev[0].shape
        global_shape = (len(order) * l_max,) + slice_shape[1:]
        spec = P(*([pp_axis] + [None] * (len(slice_shape) - 1)))
        layers_global[name] = jax.make_array_from_single_device_arrays(
            global_shape, NamedSharding(mesh, spec), per_dev
        )
    counts = jax.device_put(
        jnp.asarray([depth for _, _, depth in order], jnp.int32),
        NamedSharding(mesh, P(pp_axis)),
    )
    head = {
        name: jax.device_put(jnp.asarray(a), NamedSharding(mesh, P()))
        for name, a in head.items()
    }
    return mesh, layers_global, counts, head


def _submesh(placement, stage_idx):
    import numpy as np
    from jax.sharding import Mesh

    k = list(placement.mesh.axis_names).index(placement.pipeline_axis)
    return Mesh(np.take(placement.mesh.devices, stage_idx, axis=k),
                placement.mesh.axis_names)


def _spmd_assemble(cfg, placement, members, my_node, stacked, store,
                   codec: str, member_counts=None):
    """Shared multi-controller assembly: this process's resident stage
    params (padded to the deepest member stage) lifted into the global
    pipeline-sharded tree over the members' sub-mesh, plus the counts
    vector, the replicated head leaves, and a ``replicated`` helper.

    ``member_counts``: per-member stage depths aligned with ``members``
    (from the leader's ServeMsg); defaults to even n_layers/len(members)
    — the pre-round-4 convention."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import serde
    from .boot import decode_head

    pp_axis = placement.pipeline_axis
    mesh = _submesh(placement,
                    [placement.node_to_stage[n] for n in members])
    if member_counts is None:
        member_counts = [cfg.n_layers // len(members)] * len(members)
    l_max = max(member_counts)

    def replicated(a):
        """A mesh-global replicated array from this process's local value
        (each process contributes identical content for its devices)."""
        local = [d for d in np.ravel(mesh.devices)
                 if d.process_index == jax.process_index()]
        arr = jnp.asarray(a)
        shards = [jax.device_put(arr, d) for d in local]
        return jax.make_array_from_single_device_arrays(
            arr.shape, NamedSharding(mesh, P()), shards
        )

    stage = placement.node_to_stage[my_node]
    stage_sharding = NamedSharding(placement.stage_mesh(stage), P())
    layers_global = {}
    for name, leaf in stacked.items():
        leaf = jax.device_put(_pad_stack(leaf, l_max), stage_sharding)
        shards = {s.device: s.data for s in leaf.addressable_shards}
        local = [d for d in np.ravel(mesh.devices) if d in shards]
        global_shape = (len(members) * l_max,) + tuple(leaf.shape[1:])
        spec = P(*([pp_axis] + [None] * (leaf.ndim - 1)))
        layers_global[name] = jax.make_array_from_single_device_arrays(
            global_shape, NamedSharding(mesh, spec),
            [shards[d] for d in local],
        )

    # Per-stage depth vector, sharded along the pipeline axis: each
    # process contributes its OWN count for its local devices (a plain
    # device_put can't address the other processes' devices).
    my_count = jnp.asarray(
        [member_counts[members.index(my_node)]], jnp.int32)
    local = [d for d in np.ravel(mesh.devices)
             if d.process_index == jax.process_index()]
    counts = jax.make_array_from_single_device_arrays(
        (len(members),), NamedSharding(mesh, P(pp_axis)),
        [jax.device_put(my_count, d) for d in local],
    )

    head_src = store.get(serde.head_blob_id(cfg))
    if head_src is None:
        raise RuntimeError(
            "multi-controller serving needs the head blob assigned to "
            "every stage; this node's store has none"
        )
    head = {name: replicated(a)
            for name, a in decode_head(cfg, head_src, codec).items()}
    return mesh, layers_global, counts, head, replicated


def spmd_pod_forward(cfg, placement, members, my_node, stacked, store,
                     codec: str = "raw", batch: int = 1, seq_len: int = 16,
                     member_counts=None):
    """Multi-controller serving: called by EVERY member process on
    ``ServeMsg``.  ``stacked`` is this process's resident stage params
    (``BootResult.params``); ``store`` its layer store (holds the head
    blob — assigned to every stage by convention).  Returns
    (logits, seconds) on members, None on non-members."""
    import time

    import jax
    import jax.numpy as jnp

    from ..models.sharded import build_pp_forward

    if my_node not in members:
        return None
    t0 = time.monotonic()
    mesh, layers_global, counts, head, replicated = _spmd_assemble(
        cfg, placement, members, my_node, stacked, store, codec,
        member_counts)
    tokens = replicated(jnp.zeros((batch, seq_len), jnp.int32))

    fwd = build_pp_forward(cfg, mesh, placement.pipeline_axis)
    logits = fwd(layers_global, counts, head, tokens)
    jax.block_until_ready(logits)
    dt = time.monotonic() - t0
    log.info("pod pipelined forward from staged weights", spmd=True,
             stages=len(members), seconds=round(dt, 3))
    return logits, dt


def spmd_pod_decode(cfg, placement, members, my_node, stacked, store,
                    max_new: int, codec: str = "raw", batch: int = 1,
                    prompt_len: int = 16, member_counts=None):
    """Multi-controller KV-cached GREEDY decode: every member process
    enters the same compiled pipelined decode collective
    (``models.sharded.build_pp_decode``) and emits identical token ids —
    the pod serves generation, not just one forward.  Returns
    (tokens [batch, max_new], seconds) on members, None on non-members."""
    import time

    import jax
    import jax.numpy as jnp

    from ..models.sharded import build_pp_decode

    if my_node not in members:
        return None
    t0 = time.monotonic()
    mesh, layers_global, counts, head, replicated = _spmd_assemble(
        cfg, placement, members, my_node, stacked, store, codec,
        member_counts)
    # The boot prompt (decode_after_boot's convention): deterministic on
    # every process, so the replicated greedy loop cannot diverge.
    prompt = replicated(jnp.zeros((batch, prompt_len), jnp.int32))

    dec = build_pp_decode(cfg, mesh, placement.pipeline_axis, max_new)
    toks = dec(layers_global, counts, head, prompt)
    jax.block_until_ready(toks)
    dt = time.monotonic() - t0
    log.info("pod decoded tokens from staged weights", spmd=True,
             stages=len(members), generated=int(toks.shape[1]),
             seconds=round(dt, 3))
    return toks, dt


def pod_forward(cfg, placement, results, stores, tokens=None,
                codec: str = "raw", assembled=None):
    """One pipelined forward across the pod's stages from the landed
    weights; returns (logits, seconds) or None when not servable.
    ``assembled``: a prior ``assemble_pp_params`` result to reuse (a
    -gen run otherwise re-assembles the whole model for the decode)."""
    import time

    import jax
    import jax.numpy as jnp

    from ..models.sharded import build_pp_forward

    if assembled is None:
        assembled = assemble_pp_params(cfg, placement, results, stores,
                                       codec)
    if assembled is None:
        return None
    mesh, layers_global, counts, head = assembled
    if tokens is None:
        tokens = jnp.zeros((1, 16), jnp.int32)
    t0 = time.monotonic()
    fwd = build_pp_forward(cfg, mesh, placement.pipeline_axis)
    logits = fwd(layers_global, counts, head, tokens)
    jax.block_until_ready(logits)
    dt = time.monotonic() - t0
    log.info("pod pipelined forward from staged weights",
             stages=mesh.shape[placement.pipeline_axis],
             seconds=round(dt, 3))
    return logits, dt


def pod_decode(cfg, placement, results, stores, max_new: int,
               prompt=None, codec: str = "raw", assembled=None):
    """Single-controller pod generation: KV-cached greedy decode across
    the stages from the landed weights; (tokens, seconds) or None."""
    import time

    import jax
    import jax.numpy as jnp

    from ..models.sharded import build_pp_decode

    if assembled is None:
        assembled = assemble_pp_params(cfg, placement, results, stores,
                                       codec)
    if assembled is None:
        return None
    mesh, layers_global, counts, head = assembled
    if prompt is None:
        prompt = jnp.zeros((1, 16), jnp.int32)  # the boot prompt
    t0 = time.monotonic()
    dec = build_pp_decode(cfg, mesh, placement.pipeline_axis, max_new)
    toks = dec(layers_global, counts, head, prompt)
    jax.block_until_ready(toks)
    dt = time.monotonic() - t0
    log.info("pod decoded tokens from staged weights",
             stages=mesh.shape[placement.pipeline_axis],
             generated=int(toks.shape[1]), seconds=round(dt, 3))
    return toks, dt
