"""SLO-guarded fleet rollout pipeline (docs/rollout.md).

PR 9's zero-downtime swap flips a whole fleet at once — correct, but
the last place one bad checkpoint can take down every serving replica
simultaneously.  This module is the leader half of the staged
alternative: a ``kind="rollout"`` job expands into ORDERED WAVES
(canary → 1% → 25% → 100%), each wave a ``kind="swap"`` job over its
declared replica subset, chained so that

- the next wave's **dissemination overlaps** the current wave's serving
  and soak (the MPMD-pipeline pattern: the network never idles while
  the fleet soaks),
- a wave **commits** (flips its replicas to v2) only after the previous
  wave's soak verdict PASSED,
- after each wave's flip, an **SLO guard** evaluates the telemetry
  plane's per-replica p99 serve latency and failure counters over the
  soak window against the declared SLO — a breach auto-PAUSES the
  pipeline and rolls the breached wave BACK to v1 through the swap
  plane's first-class abort path (``SwapCommitMsg(abort, revert)``:
  the replicas restore their retained pre-flip tree), while earlier
  committed-and-finalized waves keep serving v2,
- a PASSED wave is **finalized** (``SwapCommitMsg(finalize)``): the
  replicas release the retained pre-flip tree — the rollback window is
  over.

A/B serving rides the existing version vocabulary: flipped waves serve
v2, unflipped waves serve v1, and the leader owns a **traffic-split
knob** (``split``: the fraction of eligible traffic a router should aim
at the v2 pool during soak) exposed — with the derived v1/v2 pools —
through ``RolloutCtlMsg`` and the rollout table.  The knob is
advisory-by-design: requests flow client → replica directly, so the
leader publishes routing intent rather than proxying bytes
(docs/rollout.md, honest limits).

Failover: every record mutation replicates (ControlDeltaMsg kind
``rollout`` + the snapshot's ``Rollouts`` section), so a promoted
standby resumes a half-finished rollout MID-WAVE: disseminating waves
ride the resumed job plane, committing waves ride the re-driven swap
fence, and a soaking wave re-baselines and re-soaks its FULL window at
the new leader (the guard stays armed; the cost is a longer soak, never
a skipped one).

Locking: ``RolloutDriver._lock`` is a LEAF lock — no leader method is
ever called while holding it (the leader's snapshot path acquires it
UNDER the leader lock, so the reverse order would deadlock)."""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..core.types import Assignment, LayerMeta
from ..utils import telemetry, trace
from ..utils.logging import log

# Wave lifecycle.
W_PENDING = "pending"            # declared, job not yet submitted
W_DISSEMINATING = "disseminating"  # swap job rolling (v2 on the wire)
W_STAGED = "staged"              # every replica staged+verified; held
W_COMMITTING = "committing"      # commit fence issued; flips in flight
W_SOAKING = "soaking"            # all replicas flipped; SLO window open
W_PASSED = "passed"              # verdict PASS; finalized (v1 released)
W_FAILED = "failed"              # SLO breach; rolled back to v1
W_ABORTED = "aborted"            # wave job degraded (crash mid-wave)

# Rollout lifecycle.
RUNNING = "running"
PAUSED = "paused"
DONE = "done"

_TERMINAL_WAVES = (W_PASSED, W_FAILED, W_ABORTED)

DEFAULT_SOAK_S = 2.0
DEFAULT_SPLIT = 0.5


def wave_version(version: str, wave: int) -> str:
    """The wave-qualified fence version: one swap record per wave, all
    delivering the same base ``version``'s bytes."""
    return f"{version}#w{wave}"


def base_version(version: str) -> str:
    return version.split("#", 1)[0]


def parse_slo(slo: Optional[dict]) -> dict:
    """Normalize a declared SLO.  ``p99_ms`` 0 disables the latency
    bar; ``max_failures`` is the count of errored answers a replica may
    produce inside one soak window (0 = any failure breaches);
    ``soak_s`` is the per-wave observation window.

    The guard reads p99 off fixed histogram bucket UPPER bounds
    (utils/telemetry.HIST_BUCKETS_MS), so a breach fires exactly when
    the true p99 exceeds the largest bucket bound <= the declared
    threshold — ``effective_p99_ms`` records that bound so the
    enforcement granularity is disclosed at admission and in every
    breach verdict instead of silently rounding the operator's number
    down (docs/rollout.md)."""
    slo = slo or {}

    def pick(*names, default=0.0):
        for n in names:
            if n in slo:
                return float(slo[n])
        return float(default)

    p99 = pick("P99Ms", "p99_ms")
    return {
        "p99_ms": p99,
        "effective_p99_ms": effective_p99_bound(p99),
        "max_failures": int(pick("MaxFailures", "max_failures")),
        "soak_s": pick("SoakS", "soak_s", default=DEFAULT_SOAK_S),
    }


def effective_p99_bound(p99_ms: float) -> float:
    """The largest histogram bucket bound <= the declared threshold:
    the latency the guard actually enforces at.  A threshold below the
    smallest bucket bound enforces at 0 (any sample breaches); 0 stays
    0 (latency bar disabled)."""
    if p99_ms <= 0:
        return 0.0
    return max((b for b in telemetry.HIST_BUCKETS_MS if b <= p99_ms),
               default=0.0)


def serve_view(metrics_row: Optional[dict], node: int) -> dict:
    """Extract one replica's cumulative serve telemetry from its
    metrics snapshot: the per-node latency histogram + request/failure
    counters (utils/telemetry.py vocabulary, stamped per node id
    because co-resident nodes share a registry)."""
    snap = metrics_row or {}
    counters = snap.get("counters") or {}
    return {
        "hist": dict((snap.get("hists") or {}).get(
            f"serve.latency_ms.n{node}") or {}),
        "requests": int(counters.get(f"serve.requests.n{node}", 0)),
        "failures": int(counters.get(f"serve.failures.n{node}", 0)),
    }


def slo_verdict(base: dict, now: dict, slo: dict) -> dict:
    """One replica's soak-window verdict: the cumulative views diffed,
    p99 read conservatively off the bucket bounds.  A window with no
    samples is ``no_data`` — recorded loudly, counted as pass (the
    guard must not wedge a rollout on lost telemetry; docs/rollout.md
    owns the limit)."""
    delta = telemetry.hist_delta(now.get("hist"), base.get("hist"))
    p99 = telemetry.percentile_from_hist(delta, 0.99)
    failures = max(0, now.get("failures", 0) - base.get("failures", 0))
    requests = max(0, now.get("requests", 0) - base.get("requests", 0))
    out = {"requests": requests, "failures": failures,
           "p99_ms": (round(p99, 1) if p99 not in (None, float("inf"))
                      else p99)}
    if requests <= 0 and p99 is None:
        out["verdict"] = "no_data"
        return out
    breaches = []
    if slo["p99_ms"] > 0 and p99 is not None and p99 > slo["p99_ms"]:
        eff = slo.get("effective_p99_ms", slo["p99_ms"])
        breaches.append(
            f"p99 {p99}ms > {slo['p99_ms']}ms"
            + (f" (enforced at bucket bound {eff}ms)"
               if eff != slo["p99_ms"] else ""))
    if failures > slo["max_failures"]:
        breaches.append(f"failures {failures} > {slo['max_failures']}")
    out["verdict"] = "breach" if breaches else "pass"
    if breaches:
        out["breaches"] = breaches
    return out


class RolloutDriver:
    """The leader's rollout-pipeline state machine.  All mutation goes
    through leader-thread callbacks (job completion, fence confirms,
    soak timers); the records replicate on every transition."""

    def __init__(self, leader):
        self.leader = leader
        self._lock = threading.Lock()  # LEAF lock: no leader calls under it
        self._recs: Dict[str, dict] = {}
        self._by_wave_version: Dict[str, tuple] = {}  # wv -> (rid, idx)
        # Leader-local soak bookkeeping (never replicated: a promoted
        # leader re-baselines and re-soaks).
        self._baselines: Dict[tuple, Dict[int, dict]] = {}
        self._soak_tokens: Dict[tuple, int] = {}

    # ------------------------------------------------------------ admission

    def admit(self, rollout_id: str, assignment: Assignment,
              waves: Optional[List[List[int]]], version: str,
              swap_base: int, priority: int = 0,
              digests: Optional[dict] = None, slo: Optional[dict] = None,
              split: float = -1.0) -> dict:
        """Expand one ``kind="rollout"`` submission into its wave plan
        and start wave 0's dissemination.  Idempotent per rollout id.
        Raises ValueError on an unusable declaration — the submit
        handler answers the error (the serving invariant)."""
        if not version:
            raise ValueError("a rollout needs a Version")
        if swap_base < 0:
            raise ValueError("a rollout needs a SwapBase")
        dests = sorted(int(d) for d in assignment)
        if not dests:
            raise ValueError("a rollout needs a non-empty Assignment")
        if waves:
            waves = [sorted(int(n) for n in w) for w in waves if w]
            named = [n for w in waves for n in w]
            if len(named) != len(set(named)):
                raise ValueError("rollout waves must be disjoint")
            unknown = set(named) - set(dests)
            if unknown:
                raise ValueError(
                    f"rollout waves name non-assignment replicas: "
                    f"{sorted(unknown)}")
            missing = set(dests) - set(named)
            if missing:
                # Unwaved assignees ride one trailing wave: every
                # declared dest ends up covered, canary-first.
                waves = waves + [sorted(missing)]
        else:
            # Default plan: one replica per wave, canary-style.
            waves = [[d] for d in dests]
        layer_ids = sorted({int(lid) for row in assignment.values()
                            for lid in row})
        if not layer_ids:
            raise ValueError("a rollout needs target layers")
        with self._lock:
            prior = self._recs.get(rollout_id)
            if prior is not None:
                return self._summary_locked(rollout_id)
            # A version's wave fence names (v#wN) are the swap plane's
            # identity: two rollouts sharing one would cross-wire each
            # other's flip confirms and verdicts.  One rollout per
            # version, ever — a retry rides resume(), not a re-submit.
            for rid2, r2 in self._recs.items():
                if r2["version"] == version:
                    raise ValueError(
                        f"version {version!r} is already claimed by "
                        f"rollout {rid2!r}; pick a new version name")
            rec = {
                "rollout_id": str(rollout_id),
                "version": str(version),
                "swap_base": int(swap_base),
                "priority": int(priority),
                "digests": {int(l): str(d)
                            for l, d in (digests or {}).items()},
                "layer_ids": layer_ids,
                "waves": waves,
                "wave_states": [W_PENDING] * len(waves),
                "retries": [0] * len(waves),
                "state": RUNNING,
                "paused_reason": "",
                # An EXPLICIT 0.0 is honored (no eligible v2 traffic
                # during soak); only the -1 unset sentinel defaults.
                "split": float(split) if split >= 0 else DEFAULT_SPLIT,
                "slo": parse_slo(slo),
                "verdicts": {},
                "admit_ms": time.time() * 1000.0,
            }
            self._recs[rollout_id] = rec
            for i in range(len(waves)):
                self._by_wave_version[wave_version(version, i)] = (
                    rollout_id, i)
        trace.count("rollout.admitted")
        log.info("rollout admitted: staged waves armed",
                 rollout=rollout_id, version=version,
                 waves=waves, slo=rec["slo"], split=rec["split"])
        if rec["slo"]["p99_ms"] > 0 and (rec["slo"]["effective_p99_ms"]
                                         != rec["slo"]["p99_ms"]):
            log.warn("declared p99 threshold is not a histogram bucket "
                     "bound; the guard enforces at the bound below it",
                     rollout=rollout_id,
                     declared_p99_ms=rec["slo"]["p99_ms"],
                     effective_p99_ms=rec["slo"]["effective_p99_ms"])
        self._replicate(rollout_id)
        self._submit_wave(rollout_id, 0)
        return self.summary(rollout_id)

    # --------------------------------------------------------- wave driving

    def _submit_wave(self, rid: str, idx: int) -> None:
        """Submit wave ``idx``'s swap job (dissemination starts; the
        commit is HELD until the pipeline releases it)."""
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None or idx >= len(rec["waves"]):
                return
            if rec["wave_states"][idx] not in (W_PENDING, W_FAILED,
                                               W_ABORTED):
                return
            retry = rec["retries"][idx]
            rec["wave_states"][idx] = W_DISSEMINATING
            wv = wave_version(rec["version"], idx)
            dests = list(rec["waves"][idx])
            target = {d: {lid: LayerMeta() for lid in rec["layer_ids"]}
                      for d in dests}
            jid = (f"{rid}:w{idx}" if retry == 0
                   else f"{rid}:w{idx}.r{retry}")
            priority = rec["priority"]
            digests = dict(rec["digests"])
            swap_base = rec["swap_base"]
        trace.count("rollout.wave_submitted")
        log.info("rollout wave disseminating", rollout=rid, wave=idx,
                 job=jid, dests=dests)
        self._replicate(rid)
        # The hold marker tells _register_swap this swap's commit
        # belongs to the pipeline, not the job-completion path.
        self.leader._swap_holds[wv] = rid
        self.leader.submit_job(jid, target, priority=priority,
                               kind="swap", digests=digests,
                               version=wv, swap_base=swap_base)

    def on_wave_staged(self, wv: str) -> None:
        """A wave's swap job completed cleanly (every replica staged +
        verified, zero drops): commit it if the pipeline says it is
        this wave's turn, else hold."""
        with self._lock:
            key = self._by_wave_version.get(wv)
            if key is None:
                return
            rid, idx = key
            rec = self._recs.get(rid)
            if rec is None:
                return
            if rec["wave_states"][idx] == W_DISSEMINATING:
                rec["wave_states"][idx] = W_STAGED
            commit = self._should_commit_locked(rec, idx)
            if commit:
                rec["wave_states"][idx] = W_COMMITTING
        log.info("rollout wave staged on every replica", rollout=rid,
                 wave=idx, committing=commit)
        self._replicate(rid)
        if commit:
            self._commit_wave(rid, idx)

    def _should_commit_locked(self, rec: dict, idx: int) -> bool:
        if rec["state"] != RUNNING:
            return False
        if rec["wave_states"][idx] not in (W_STAGED,):
            return False
        return idx == 0 or rec["wave_states"][idx - 1] == W_PASSED

    def _commit_wave(self, rid: str, idx: int) -> None:
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None:
                return
            if (rec["state"] != RUNNING
                    or rec["wave_states"][idx] != W_COMMITTING):
                # A pause (operator or breach) landed between the
                # caller's locked check and here: flipping now would
                # commit a wave AFTER the operator was told no further
                # waves commit.  Back to held-staged — the next resume
                # re-commits it through _should_commit_locked.
                if rec["wave_states"][idx] == W_COMMITTING:
                    rec["wave_states"][idx] = W_STAGED
                paused = True
            else:
                paused = False
            wv = wave_version(rec["version"], idx)
            nxt = idx + 1 if idx + 1 < len(rec["waves"]) else None
            if nxt is not None and rec["wave_states"][nxt] != W_PENDING:
                nxt = None
        if paused:
            log.warn("rollout wave commit withheld: pipeline paused "
                     "under the fence; wave back to held-staged",
                     rollout=rid, wave=idx)
            self._replicate(rid)
            return
        trace.count("rollout.wave_committed")
        log.info("rollout wave committing: flip fence issued",
                 rollout=rid, wave=idx)
        self.leader._commit_swap(wv)
        # Pipeline overlap (the MPMD pattern): the NEXT wave's
        # dissemination starts the moment this wave's commit is on the
        # wire — v2 bytes move while this wave flips and soaks.
        if nxt is not None:
            self._submit_wave(rid, nxt)

    def on_wave_flipped(self, wv: str) -> None:
        """Every replica of a committed wave confirmed its flip: open
        the SLO soak window."""
        with self._lock:
            key = self._by_wave_version.get(wv)
            if key is None:
                return
            rid, idx = key
            rec = self._recs.get(rid)
            if rec is None or rec["wave_states"][idx] != W_COMMITTING:
                return
            rec["wave_states"][idx] = W_SOAKING
            soak_s = rec["slo"]["soak_s"]
            dests = list(rec["waves"][idx])
            token = self._soak_tokens.get((rid, idx), 0) + 1
            self._soak_tokens[(rid, idx)] = token
        log.info("rollout wave flipped fleet-wide; soak window open",
                 rollout=rid, wave=idx, soak_s=soak_s)
        self._replicate(rid)
        self._baseline_wave(rid, idx, dests)
        timer = threading.Timer(soak_s, self._evaluate,
                                args=(rid, idx, token))
        timer.daemon = True
        timer.start()

    def _metrics_row(self, node: int) -> dict:
        with self.leader._lock:
            return dict(self.leader.cluster_metrics.get(node) or {})

    def _quarantined(self) -> set:
        """The policy engine's serve-rotation mask (docs/autonomy.md);
        empty when no engine is armed (or on a pre-policy leader)."""
        getter = getattr(self.leader, "serve_quarantined", None)
        return set(getter()) if getter is not None else set()

    def _baseline_wave(self, rid: str, idx: int, dests) -> None:
        # A quarantined replica's latency is exactly what the policy
        # engine flagged — baselining on it would poison the SLO
        # verdicts for the whole wave (docs/autonomy.md).
        quarantined = self._quarantined()
        views = {d: serve_view(self._metrics_row(d), d) for d in dests
                 if d not in quarantined}
        with self._lock:
            self._baselines[(rid, idx)] = views

    # ------------------------------------------------------------ SLO guard

    def _evaluate(self, rid: str, idx: int, token: int) -> None:
        """The soak window closed: per-replica verdicts against the
        declared SLO.  PASS finalizes the wave and advances the
        pipeline; BREACH pauses the pipeline and rolls the wave back."""
        if self.leader._closed():
            return
        # Freshness: wait for one report round RECEIVED AFTER the soak
        # window closed, so the verdict sees the window's tail instead
        # of a snapshot that predates it (a stale view would read as
        # no_data and silently pass a breaching wave).  Bounded: a dead
        # reporter degrades this to a loud best-effort, never a wedge.
        try:
            self.leader.await_metrics(newer_than=time.monotonic(),
                                      timeout=3.0)
        except Exception:  # noqa: BLE001 — advisory freshness only
            pass
        with self._lock:
            rec = self._recs.get(rid)
            if (rec is None or rec["wave_states"][idx] != W_SOAKING
                    or self._soak_tokens.get((rid, idx)) != token):
                return
            dests = list(rec["waves"][idx])
            slo = dict(rec["slo"])
            baseline = self._baselines.get((rid, idx)) or {}
        # Quarantined replicas get no verdict: the policy engine
        # already flagged them, and counting their latency here would
        # conflate "this WAVE regressed" with "this REPLICA is sick"
        # (docs/autonomy.md).
        quarantined = self._quarantined()
        dests = [d for d in dests if d not in quarantined]
        replicas = {}
        breached = []
        for d in dests:
            v = slo_verdict(baseline.get(d) or {},
                            serve_view(self._metrics_row(d), d), slo)
            replicas[d] = v
            if v["verdict"] == "breach":
                breached.append(d)
        verdict = {
            "wave": idx,
            "verdict": "breach" if breached else "pass",
            "replicas": {str(d): v for d, v in replicas.items()},
            "t_ms": time.time() * 1000.0,
        }
        if any(v["verdict"] == "no_data" for v in replicas.values()):
            verdict["no_data"] = sorted(
                d for d, v in replicas.items()
                if v["verdict"] == "no_data")
            log.warn("SLO guard saw no serve traffic on some replicas "
                     "this soak window; counted as pass, recorded",
                     rollout=rid, wave=idx, replicas=verdict["no_data"])
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None or rec["wave_states"][idx] != W_SOAKING:
                return
            rec["verdicts"][str(idx)] = verdict
            if breached:
                rec["wave_states"][idx] = W_FAILED
                rec["state"] = PAUSED
                rec["paused_reason"] = (
                    f"wave {idx} SLO breach on replicas {breached}")
                wv = wave_version(rec["version"], idx)
            else:
                rec["wave_states"][idx] = W_PASSED
        if breached:
            trace.count("rollout.slo_breach")
            trace.count("rollout.paused")
            log.error("rollout wave BREACHED its SLO: pipeline paused, "
                      "wave rolling back to the pre-flip version",
                      rollout=rid, wave=idx, replicas=breached,
                      verdict=verdict["replicas"])
            self._replicate(rid)
            # First-class rollback through the swap abort path: the
            # replicas restore their retained pre-flip tree.
            self.leader._abort_swap(
                wv, f"rollout {rid} wave {idx} SLO breach",
                revert=True)
            return
        trace.count("rollout.wave_passed")
        log.info("rollout wave passed its SLO soak; finalizing",
                 rollout=rid, wave=idx, verdict=verdict["replicas"])
        self._replicate(rid)
        self._finalize_wave(rid, idx)
        self._advance(rid, idx)

    def _finalize_wave(self, rid: str, idx: int) -> None:
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None:
                return
            wv = wave_version(rec["version"], idx)
        self.leader._swap_send_round(wv, finalize=True)

    def _advance(self, rid: str, idx: int) -> None:
        """Wave ``idx`` passed: release the next wave's hold (commit it
        if already staged; submit it if it never started), or complete
        the rollout."""
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None or rec["state"] not in (RUNNING, PAUSED):
                return
            nxt = idx + 1
            if nxt >= len(rec["waves"]):
                # The terminal edge runs even PAUSED: completion
                # commits nothing, and without it a rollout whose last
                # wave passed mid-pause would report "running" forever
                # (resume would find no wave left to drive).
                rec["state"] = DONE
                done = True
                action = None
            elif rec["state"] != RUNNING:
                return  # paused: no further waves commit or submit
            else:
                done = False
                st = rec["wave_states"][nxt]
                if st == W_STAGED:
                    rec["wave_states"][nxt] = W_COMMITTING
                    action = "commit"
                elif st == W_PENDING:
                    action = "submit"
                elif st in (W_FAILED, W_ABORTED):
                    # The next wave died while THIS one was still
                    # soaking (its dissemination overlapped); this
                    # pass is the pipeline's hand-off, so retry it
                    # here — nothing later would.
                    rec["retries"][nxt] += 1
                    rec["wave_states"][nxt] = W_PENDING
                    action = "submit"
                else:
                    action = None  # disseminating: commits when staged
        if done:
            trace.count("rollout.done")
            log.info("rollout complete: every wave serving the new "
                     "version", rollout=rid)
            self._prune_done(rid)
            self._replicate(rid)
            return
        self._replicate(rid)
        if action == "commit":
            self._commit_wave(rid, nxt)
        elif action == "submit":
            self._submit_wave(rid, nxt)

    def _prune_done(self, rid: str) -> None:
        """A rollout reached DONE: release its per-wave pipeline
        bookkeeping.  The hold markers especially must not outlive the
        pipeline — a later plain swap whose version happens to collide
        with a wave fence key would otherwise register HELD and never
        flip.  The record itself stays for ``-rollouts`` history."""
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None:
                return
            for i in range(len(rec["waves"])):
                wv = wave_version(rec["version"], i)
                self.leader._swap_holds.pop(wv, None)
                self._baselines.pop((rid, i), None)
                self._soak_tokens.pop((rid, i), None)

    def on_replica_crashed(self, node: int) -> None:
        """A serving replica died while its wave was mid-flip or
        SOAKING.  Without this hook the dead replica's empty soak
        window reads ``no_data`` → pass, and the pipeline ships the
        very v2 that may have killed it fleet-wide — a canary that
        CRASHES is the strongest possible breach.  The wave fails, the
        pipeline pauses, and the surviving wave replicas revert to
        their retained pre-flip tree."""
        actions = []
        with self._lock:
            for rid in sorted(self._recs):
                rec = self._recs[rid]
                for idx, dests in enumerate(rec["waves"]):
                    if (node not in dests
                            or rec["wave_states"][idx]
                            not in (W_COMMITTING, W_SOAKING)):
                        continue
                    rec["wave_states"][idx] = W_FAILED
                    if rec["state"] == RUNNING:
                        rec["state"] = PAUSED
                    rec["paused_reason"] = (
                        f"wave {idx} replica {node} crashed mid-wave")
                    actions.append(
                        (rid, idx, wave_version(rec["version"], idx)))
        for rid, idx, wv in actions:
            trace.count("rollout.replica_crashed")
            trace.count("rollout.paused")
            log.error("rollout wave replica CRASHED mid-flip/soak; "
                      "pipeline paused, survivors reverting",
                      rollout=rid, wave=idx, replica=node)
            self._replicate(rid)
            self.leader._abort_swap(
                wv, f"rollout {rid} wave {idx}: replica {node} "
                "crashed", revert=True)

    def on_wave_aborted(self, wv: str, reason: str) -> None:
        """A wave's swap aborted outside the guard's own rollback (a
        replica crashed mid-wave, a staging digest gave up): the wave
        is failed and the pipeline pauses — operator resume retries."""
        with self._lock:
            key = self._by_wave_version.get(wv)
            if key is None:
                return
            rid, idx = key
            rec = self._recs.get(rid)
            if rec is None:
                return
            if rec["wave_states"][idx] in (W_FAILED, W_ABORTED):
                return  # the guard's own rollback already recorded it
            rec["wave_states"][idx] = W_ABORTED
            if rec["state"] == RUNNING:
                rec["state"] = PAUSED
                rec["paused_reason"] = (
                    f"wave {idx} aborted: {reason}")
        trace.count("rollout.wave_aborted")
        trace.count("rollout.paused")
        log.error("rollout wave aborted; pipeline paused",
                  rollout=rid, wave=idx, reason=reason)
        self._replicate(rid)

    # ------------------------------------------------------- operator verbs

    def pause(self, rid: str) -> str:
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None:
                return f"unknown rollout {rid!r}"
            if rec["state"] == DONE:
                return f"rollout {rid!r} already complete"
            rec["state"] = PAUSED
            rec["paused_reason"] = "operator pause"
        trace.count("rollout.paused")
        log.warn("rollout paused by operator", rollout=rid)
        self._replicate(rid)
        return ""

    def resume(self, rid: str) -> str:
        """Re-arm a paused pipeline.  A failed/aborted wave is
        re-submitted as a retry job (the swap plane's retry-after-abort
        path redelivers the released v2 set); a held staged wave whose
        predecessor passed commits."""
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None:
                return f"unknown rollout {rid!r}"
            if rec["state"] != PAUSED:
                return f"rollout {rid!r} is not paused ({rec['state']})"
            rec["state"] = RUNNING
            rec["paused_reason"] = ""
            actions = []
            for i, st in enumerate(rec["wave_states"]):
                if st in (W_FAILED, W_ABORTED):
                    rec["retries"][i] += 1
                    rec["wave_states"][i] = W_PENDING
                    actions.append(("submit", i))
                    break
                if st == W_STAGED and self._should_commit_locked(rec, i):
                    rec["wave_states"][i] = W_COMMITTING
                    actions.append(("commit", i))
                    break
                if st == W_PENDING:
                    if i == 0 or rec["wave_states"][i - 1] == W_PASSED:
                        actions.append(("submit", i))
                    break
            if not actions and all(st == W_PASSED
                                   for st in rec["wave_states"]):
                # Every wave passed while the pipeline sat paused (the
                # last soak's verdict landed post-pause): the rollout
                # is COMPLETE, not "running" with nothing to drive it.
                rec["state"] = DONE
                completed = True
            else:
                completed = False
        if completed:
            trace.count("rollout.done")
            log.info("rollout resumed into completion: every wave "
                     "already passed", rollout=rid)
            self._prune_done(rid)
            self._replicate(rid)
            return ""
        trace.count("rollout.resumed")
        log.info("rollout resumed by operator", rollout=rid,
                 actions=actions)
        self._replicate(rid)
        for verb, i in actions:
            if verb == "submit":
                self._submit_wave(rid, i)
            else:
                self._commit_wave(rid, i)
        return ""

    def set_split(self, rid: str, split: float) -> str:
        if not 0.0 <= split <= 1.0:
            return f"split must be in [0, 1], got {split}"
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None:
                return f"unknown rollout {rid!r}"
            rec["split"] = float(split)
        log.info("rollout traffic split set", rollout=rid, split=split)
        self._replicate(rid)
        return ""

    # ------------------------------------------------------------- queries

    def _traffic_locked(self, rec: dict) -> dict:
        """The A/B pools the split knob routes between: replicas of
        flipped waves serve v2, everyone else v1 (a FAILED wave rolled
        back, so its replicas are v1 again).  Policy-quarantined
        replicas (docs/autonomy.md) are masked OUT of both pools and
        surfaced under their own key — traffic routes around a
        breacher, operators still see it."""
        quarantined = self._quarantined()
        v2 = []
        v1 = []
        for i, dests in enumerate(rec["waves"]):
            st = rec["wave_states"][i]
            pool = v2 if st in (W_COMMITTING, W_SOAKING, W_PASSED) else v1
            pool.extend(d for d in dests if d not in quarantined)
        return {"split": rec["split"], "v2": sorted(v2), "v1": sorted(v1),
                "quarantined": sorted(quarantined)}

    def _summary_locked(self, rid: str) -> dict:
        rec = self._recs[rid]
        return {
            "RolloutID": rec["rollout_id"],
            "Version": rec["version"],
            "State": rec["state"],
            "PausedReason": rec["paused_reason"],
            "Waves": [list(w) for w in rec["waves"]],
            "WaveStates": list(rec["wave_states"]),
            "Wave": self._current_wave_locked(rec),
            "Split": rec["split"],
            "SLO": dict(rec["slo"]),
            "Verdicts": {k: dict(v) for k, v in rec["verdicts"].items()},
            "Traffic": self._traffic_locked(rec),
        }

    @staticmethod
    def _current_wave_locked(rec: dict) -> int:
        """The frontier wave index: the first wave not yet terminal
        (== len(waves) when every wave passed)."""
        for i, st in enumerate(rec["wave_states"]):
            if st != W_PASSED:
                return i
        return len(rec["waves"])

    def summary(self, rid: str) -> dict:
        with self._lock:
            if rid not in self._recs:
                return {}
            return self._summary_locked(rid)

    def table(self) -> Dict[str, dict]:
        with self._lock:
            return {rid: self._summary_locked(rid)
                    for rid in sorted(self._recs)}

    def traffic_table(self, rid: str) -> dict:
        with self._lock:
            rec = self._recs.get(rid)
            return self._traffic_locked(rec) if rec is not None else {}

    # ---------------------------------------------------------- replication

    def record_json(self, rid: str) -> dict:
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None:
                return {}
            return {
                "RolloutID": rec["rollout_id"],
                "Version": rec["version"],
                "SwapBase": rec["swap_base"],
                "Priority": rec["priority"],
                "Digests": {str(l): d
                            for l, d in rec["digests"].items()},
                "LayerIDs": list(rec["layer_ids"]),
                "Waves": [list(w) for w in rec["waves"]],
                "WaveStates": list(rec["wave_states"]),
                "Retries": list(rec["retries"]),
                "State": rec["state"],
                "PausedReason": rec["paused_reason"],
                "Split": rec["split"],
                "SLO": dict(rec["slo"]),
                "Verdicts": {k: dict(v)
                             for k, v in rec["verdicts"].items()},
                "AdmitMs": rec["admit_ms"],
            }

    def _replicate(self, rid: str) -> None:
        data = self.record_json(rid)
        if data:
            self.leader._replicate("rollout", **data)

    def to_json(self) -> Dict[str, dict]:
        with self._lock:
            rids = sorted(self._recs)
        return {rid: self.record_json(rid) for rid in rids}

    def load(self, records: Dict[str, dict]) -> None:
        """Restore replicated records (takeover).  Malformed records
        are skipped loudly — one corrupt delta must not sink the other
        rollouts' recovery."""
        for rid, data in sorted((records or {}).items()):
            try:
                waves = [[int(n) for n in w]
                         for w in data.get("Waves") or []]
                rec = {
                    "rollout_id": str(data.get("RolloutID", rid)),
                    "version": str(data["Version"]),
                    "swap_base": int(data.get("SwapBase", -1)),
                    "priority": int(data.get("Priority", 0)),
                    "digests": {int(l): str(d) for l, d in
                                (data.get("Digests") or {}).items()},
                    "layer_ids": [int(l)
                                  for l in data.get("LayerIDs") or []],
                    "waves": waves,
                    "wave_states": [
                        str(s) for s in data.get("WaveStates") or []]
                    or [W_PENDING] * len(waves),
                    "retries": [int(r) for r in data.get("Retries") or []]
                    or [0] * len(waves),
                    "state": str(data.get("State", RUNNING)),
                    "paused_reason": str(data.get("PausedReason", "")),
                    "split": float(data.get("Split", DEFAULT_SPLIT)),
                    "slo": parse_slo(data.get("SLO")),
                    "verdicts": {str(k): dict(v) for k, v in
                                 (data.get("Verdicts") or {}).items()},
                    "admit_ms": float(data.get("AdmitMs", 0.0)),
                }
            except (KeyError, ValueError, TypeError) as e:
                log.error("unloadable replicated rollout record; "
                          "skipped", rollout=rid, err=repr(e))
                continue
            with self._lock:
                self._recs[rec["rollout_id"]] = rec
                for i in range(len(rec["waves"])):
                    wv = wave_version(rec["version"], i)
                    self._by_wave_version[wv] = (rec["rollout_id"], i)
            # The hold markers must survive the takeover: a retry
            # re-submission of any wave must register HELD.  A DONE
            # rollout's were pruned at completion — keep them pruned.
            if rec["state"] != DONE:
                for i in range(len(rec["waves"])):
                    self.leader._swap_holds[
                        wave_version(rec["version"], i)] = rec["rollout_id"]

    def resume_all(self) -> None:
        """Takeover re-drive (docs/rollout.md): pick every adopted
        rollout up MID-WAVE.  Disseminating waves ride the resumed job
        plane; a committing wave's fence was re-driven by
        ``_resume_swaps`` (confirms will open the soak); a SOAKING wave
        re-baselines and re-soaks its full window HERE — the guard
        stays armed across the takeover."""
        with self._lock:
            rids = sorted(self._recs)
        for rid in rids:
            actions = []
            with self._lock:
                rec = self._recs[rid]
                if rec["state"] not in (RUNNING,):
                    continue
                for i, st in enumerate(rec["wave_states"]):
                    if st in (W_SOAKING, W_COMMITTING):
                        # Reopen the window: flip-confirm state was
                        # adopted, but the old leader's baseline died
                        # with it.  Full re-soak, never a skipped one.
                        rec["wave_states"][i] = W_COMMITTING
                        actions.append(("resoak", i))
                    elif st == W_STAGED and self._should_commit_locked(
                            rec, i):
                        rec["wave_states"][i] = W_COMMITTING
                        actions.append(("commit", i))
                    elif st == W_PENDING and (
                            i == 0 or rec["wave_states"][i - 1]
                            == W_PASSED):
                        actions.append(("submit", i))
                        break
            for verb, i in actions:
                log.info("resuming adopted rollout mid-wave",
                         rollout=rid, wave=i, action=verb)
                if verb == "submit":
                    self._submit_wave(rid, i)
                elif verb == "commit":
                    self._commit_wave(rid, i)
                else:  # resoak: the committed fence re-send is already
                    # on the wire (_resume_swaps); when every replica
                    # re-confirms, on_wave_flipped reopens the window.
                    with self._lock:
                        rec = self._recs.get(rid)
                        if rec is None:
                            continue
                        wv = wave_version(rec["version"], i)
                    # A fully-confirmed adopted fence re-sends to no
                    # one (everyone confirmed pre-kill): synthesize the
                    # flip edge so the soak reopens regardless.
                    with self.leader._lock:
                        srec = self.leader._swaps.get(wv)
                        all_confirmed = (
                            srec is not None
                            and set(srec["dests"])
                            <= set(srec["confirmed"]))
                    if all_confirmed:
                        self.on_wave_flipped(wv)
            if actions:
                self._replicate(rid)
