"""Per-layer receive-to-device streaming boot staging.

The boot used to be strictly SEQUENCED after delivery: every blob lands,
startup fires, and only then does the boot decode all n wire blobs and
place the params — at physical scale that serial tail is several times
the transfer it follows (VERDICT r5 item 4).  Redistribution work hides
that class of latency by overlapping data movement with the downstream
compute (arXiv:2112.01075, arXiv:2412.14374); this module is the boot's
version of the same move.

``StreamingBootStager`` accepts each blob THE MOMENT its interval set
completes (the receiver's completion commit — mid-wire for every blob
but the last) and immediately runs that blob's share of the boot work on
a dedicated worker thread:

- **device path** (``-hbm``): the HBM-resident wire blob is decoded
  per-blob under the same codec jits the bulk boot uses, 1-blob
  programs — one compile (or persistent-cache read) covers every layer,
  and each decode overlaps the remaining transfers;
- **host path**: the blob is decoded on host (numpy views) and each
  leaf ``device_put`` — asynchronous, so the host→device DMA of layer k
  rides under the receive of layer k+1.

``boot_from_layers`` then assembles the staged leaves with one
device-local concatenate per leaf (HBM-bandwidth work) — bit-identical
to the bulk assembly regardless of COMPLETION ORDER, because each blob
decodes independently and the concat is in layer-id order.

Leaves are stored with a leading length-1 axis (the decode jits'
natural output for a 1-tuple; host leaves get ``[None]``) so assembly is
a plain ``jnp.concatenate`` for both paths.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Optional

from ..utils import env as env_util, trace
from ..utils.logging import log

# Phase buckets (utils.trace): summed per-blob staging seconds, and the
# subset that ran while the wire was still active (before startup) —
# the stage-overlap-achieved evidence the TTFT breakdown table reads.
PHASE_STREAM_STAGE = "boot_stream_stage"
PHASE_STREAM_IN_WIRE = "boot_stream_in_wire"


class StreamingBootStager:
    """Decode/stage completed blobs concurrently with the receive.

    Thread model: ``submit`` is called from receiver handler threads
    (idempotent per blob — re-plan duplicates are no-ops) and enqueues;
    ONE worker daemon drains the queue (per-blob decodes are big device
    dispatches — a pool would just thrash the link).  ``collect`` blocks
    until every submitted blob is processed and returns the staged
    leaves; the boot calls it once at startup.  Failures are per-blob
    and non-fatal: a blob that fails to stage is simply absent from
    ``collect`` and the boot falls back to bulk assembly."""

    def __init__(self, cfg, codec: str = "raw", placement=None,
                 node_id=None, digest_lookup=None, digest_verified=None):
        """``digest_lookup``/``digest_verified`` (integrity plane): a
        ``blob_id -> expected hex digest (or None)`` callable and the
        receiver's already-verified id set.  Each blob with host bytes
        re-verifies before its decode is dispatched UNLESS the ack path
        already verified it (the set) — defense in depth for bytes that
        reached the stager without crossing the ack gate, at zero cost
        on the normal path."""
        self.cfg = cfg
        self.codec = codec
        self.placement = placement
        self.node_id = node_id
        self.digest_lookup = digest_lookup
        self.digest_verified = digest_verified
        # Pod-delivery hook (docs/fabric.md): called from the worker
        # thread after every shard-gather attempt with
        # ``(blob_id, full_wire_bytes_or_None, codec)`` — the receiver
        # stores the materialized holding and acks the FULL layer (or
        # degrades loudly) the moment ITS layer gathers, in any
        # completion order across layers.
        self.on_gathered = None
        self._q: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._lock = threading.Lock()
        self._done = threading.Condition(self._lock)
        self._staged: Dict[int, dict] = {}
        # Shard-gather state (docs/sharding.md): blob -> accumulated
        # shard parts, and blob -> the materialized full layer's bytes.
        self._shards: Dict[int, dict] = {}
        self._gathered: Dict[int, bytes] = {}
        self._submitted: set = set()
        self._pending = 0
        self._closed = False
        self._startup_seen = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- intake

    def submit(self, blob_id: int, src) -> bool:
        """Queue a completed blob for staging; False for duplicates,
        closed stagers, or ids the boot can never use."""
        from ..models import serde

        if self.cfg is None or blob_id > serde.head_blob_id(self.cfg):
            # cfg-less stagers exist purely as shard-gather drivers
            # (pod delivery on a -boot none run): nothing boots here.
            return False
        with self._lock:
            if self._closed or blob_id in self._submitted:
                return False
            self._submitted.add(blob_id)
            self._pending += 1
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"boot-stream-{self.node_id}")
                self._thread.start()
            # Enqueue INSIDE the lock: a racing close() must not slot
            # its None sentinel ahead of this item, or the worker exits
            # with _pending stuck > 0 and collect() waits out its whole
            # timeout.
            self._q.put((blob_id, src))
        return True

    def invalidate(self, blob_id: int) -> None:
        """Forget a blob whose bytes turned out corrupt AFTER submission
        (a digest stamp arriving late demotes the layer): drops both the
        staged leaves and the dedup marker so the redelivered copy
        re-stages.  The worker re-checks the marker before storing, so a
        stage already in flight for the corrupt bytes is discarded
        instead of landing in ``_staged``."""
        with self._lock:
            self._submitted.discard(blob_id)
            self._staged.pop(blob_id, None)

    def mark_startup(self) -> None:
        """Startup arrived: blobs staged from here on no longer overlap
        the wire (accounting only — staging itself continues)."""
        with self._lock:
            self._startup_seen = True

    @property
    def staged_count(self) -> int:
        with self._lock:
            return len(self._staged)

    # ------------------------------------------------------------ consume

    def collect(self, blob_ids, timeout: float = 300.0) -> Dict[int, dict]:
        """Wait for all in-flight staging, then return {blob_id: leaves}
        for the requested ids that staged successfully.  The returned
        leaves carry a leading length-1 axis (module docstring)."""
        with self._lock:
            self._done.wait_for(lambda: self._pending == 0, timeout=timeout)
            if self._pending:
                log.warn("streamed staging still in flight at collect; "
                         "boot falls back to bulk assembly",
                         pending=self._pending)
                return {}
            return {b: self._staged[b] for b in blob_ids
                    if b in self._staged}

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            started = self._thread is not None
        if started:
            self._q.put(None)

    # ------------------------------------------------------------- worker

    def _run(self) -> None:
        from .boot import ensure_compile_cache

        ensure_compile_cache()
        while True:
            item = self._q.get()
            if item is None:
                return
            if item[0] == "gather":
                self._gather_one(item[1])
                continue
            blob_id, src = item
            leaves = None
            t0 = time.monotonic()
            try:
                leaves = self._stage_one(blob_id, src)
            except Exception as e:  # noqa: BLE001 — boot falls back to bulk
                log.warn("streamed boot staging failed for blob; bulk "
                         "assembly will cover it", blobID=blob_id,
                         err=repr(e))
            dt = time.monotonic() - t0
            with self._lock:
                # Store only while the submission marker stands — an
                # invalidate() (corrupt blob demoted mid-stage) discards
                # this result; the redelivered copy re-stages.
                if leaves is not None and blob_id not in self._submitted:
                    log.warn("discarding staged leaves for invalidated "
                             "blob", blobID=blob_id)
                    leaves = None
                if leaves is not None:
                    self._staged[blob_id] = leaves
                in_wire = not self._startup_seen
                self._pending -= 1
                if self._pending == 0:
                    self._done.notify_all()
            if leaves is not None:
                trace.add_phase(PHASE_STREAM_STAGE, dt)
                if in_wire:
                    trace.add_phase(PHASE_STREAM_IN_WIRE, dt)
                log.info("layer boot-staged (streamed)", blobID=blob_id,
                         stage_ms=round(dt * 1000, 1), in_wire=in_wire)

    def _sharding(self):
        if (self.placement is not None
                and self.node_id in self.placement.node_to_stage):
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            return NamedSharding(
                self.placement.stage_mesh(
                    self.placement.node_to_stage[self.node_id]), P()
            )
        return None

    def submit_shard(self, blob_id: int, spec: str, data, total: int,
                     expected_digest: str = "", codec: str = "") -> bool:
        """Feed one completed SHARD of a layer to the shard gather
        (docs/sharding.md) — callable the moment the shard's interval
        set completes, in ANY completion order across shards.  Returns
        False for duplicates/closed stagers.  When the last shard of a
        layer arrives, the worker thread runs the on-mesh all-gather
        (``parallel.collectives.gather_byte_shards``) and the
        materialized FULL layer becomes available via
        ``collect_gathered`` — verified against ``expected_digest``
        (the stamped full-layer digest) when one is known.

        ``codec`` (docs/codec.md, docs/fabric.md): the WIRE form the
        shards slice ("" = canonical) — ``total``, the specs' ranges,
        and ``expected_digest`` then all live in encoded byte space,
        and a boot-capable stager dequants the gathered blob on device
        (``quant.device_decode_jit`` after the gather) so the decoded
        leaves stage exactly like a full codec'd delivery's would."""
        from ..core.types import parse_shard_spec

        parsed = parse_shard_spec(spec)
        n, k = parsed if parsed is not None else (1, 0)
        with self._lock:
            if self._closed:
                return False
            rec = self._shards.setdefault(
                blob_id, {"n": n, "total": int(total), "parts": {},
                          "digest": "", "codec": codec, "queued": False})
            if rec["n"] != n or rec["total"] != int(total):
                log.error("conflicting shard geometry submitted",
                          blobID=blob_id, have_n=rec["n"], got_n=n)
                return False
            if k in rec["parts"]:
                return False
            rec["parts"][k] = bytes(data)
            if expected_digest:
                rec["digest"] = expected_digest
            if codec:
                rec["codec"] = codec
            ready = len(rec["parts"]) >= n and not rec["queued"]
            if not ready:
                return True
            rec["queued"] = True
            self._pending += 1
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name=f"boot-stream-{self.node_id}")
                self._thread.start()
            self._q.put(("gather", blob_id))
        return True

    def collect_gathered(self, blob_ids, timeout: float = 300.0
                         ) -> Dict[int, bytes]:
        """Wait for in-flight gathers, then return {blob_id: full layer
        bytes} for the requested ids whose shard sets materialized."""
        with self._lock:
            self._done.wait_for(lambda: self._pending == 0, timeout=timeout)
            if self._pending:
                log.warn("shard gathers still in flight at collect",
                         pending=self._pending)
                return {}
            return {b: self._gathered[b] for b in blob_ids
                    if b in self._gathered}

    def _gather_one(self, blob_id: int) -> None:
        from ..parallel.collectives import gather_byte_shards

        t0 = time.monotonic()
        with self._lock:
            rec = self._shards.get(blob_id)
            if rec is None:
                parts, total, digest, codec = None, 0, "", ""
            else:
                parts = sorted(rec["parts"].items())
                total, digest = rec["total"], rec["digest"]
                codec = rec.get("codec", "")
        out, leaves = None, None
        if parts is not None:
            # Boot-capable stagers decode the gathered blob in the same
            # pass (device dequant when the gather ran on-mesh) so the
            # leaves stage exactly like a full delivery's.
            decode = None
            if self.cfg is not None:
                from ..models import serde

                if blob_id <= serde.head_blob_id(self.cfg):
                    decode = (self.cfg, blob_id)
            try:
                got = gather_byte_shards(parts, total,
                                         verify_digest=digest or None,
                                         codec=codec, decode=decode)
                out, leaves = got if decode is not None else (got, None)
            except Exception as e:  # noqa: BLE001 — loud, never wedge
                log.error("on-mesh shard gather failed", blobID=blob_id,
                          err=repr(e))
        dt = time.monotonic() - t0
        with self._lock:
            if out is not None and blob_id in self._shards:
                self._gathered[blob_id] = out
                if leaves is not None:
                    # The gather's dequant already staged this blob:
                    # mark it submitted so a later full-delivery
                    # ``submit`` dedupes instead of re-decoding.
                    self._submitted.add(blob_id)
                    self._staged[blob_id] = leaves
            in_wire = not self._startup_seen
            self._pending -= 1
            if self._pending == 0:
                self._done.notify_all()
        if out is not None:
            trace.add_phase(PHASE_STREAM_STAGE, dt)
            if in_wire:
                trace.add_phase(PHASE_STREAM_IN_WIRE, dt)
            log.info("layer materialized from shards (on-mesh gather)",
                     blobID=blob_id, gather_ms=round(dt * 1000, 1),
                     in_wire=in_wire, bytes=len(out),
                     codec=codec or None, digest_verified=bool(digest))
        hook = self.on_gathered
        if hook is not None:
            try:
                hook(blob_id, out, codec)
            except Exception as e:  # noqa: BLE001 — the hook must not
                log.error("on_gathered hook failed", blobID=blob_id,
                          err=repr(e))  # kill the worker
        if out is None:
            trace.count("shard.gather_failed")

    def _stage_one(self, blob_id: int, src) -> dict:
        """One blob's staging — ``boot.stage_blob_leaves`` verbatim, so
        the mid-wire path and the boot's infill path share programs and
        bits.  Consumable device blobs (``blob_donate_ok``: host
        fallback retained) are released by reference inside the helper
        the moment their decode is dispatched: HBM peaks at params-so-
        far + the in-flight blob, not params + every wire blob.

        Per-blob codec (docs/codec.md): a blob delivered under a
        negotiated WIRE codec decodes under ITS form, not the run's —
        this is the decode-during-staging half of the quantized wire
        path (the dequant rides the same per-blob device jit the
        global-codec runs use)."""
        from .boot import stage_blob_leaves, verify_blob_digest

        verify_blob_digest(blob_id, src, self.digest_lookup,
                           self.digest_verified)
        codec = getattr(src.meta, "codec", "") or self.codec
        return stage_blob_leaves(self.cfg, blob_id, src, codec=codec,
                                 sharding=self._sharding())
