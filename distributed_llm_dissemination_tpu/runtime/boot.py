"""Model boot from disseminated bytes: the startup hook, made real.

The reference broadcasts a ``startupMsg`` whose handler is a stub — "the
hook that would launch an inference engine"
(``/root/reference/distributor/message.go:216-241``,
``distributor/node.go:1387-1389``).  Here the hook boots one: a receiver
assembles its delivered layer blobs into ``models.llama`` params and runs a
jitted forward pass, so dissemination ends at a *serving model*, not a pile
of bytes — and the leader can report time-to-first-token next to TTD.

Two boot shapes, chosen by what the node holds:
- **full**: the node's blobs cover every layer plus the head blob — the
  whole model boots and produces real logits (the reference benchmark
  scenario: one cold node receives the complete model).
- **stage**: the node holds a contiguous slice of layers (a pipeline
  stage) — its stacked stage params run over dummy activations, proving
  the stage's weights are resident and usable on its devices.

Assembly prefers the device path: blobs that the ``-hbm`` ingest landed in
HBM are bit-reinterpreted on the accelerator (``serde.stacked_from_device_
blobs``) — the disseminated bytes never make a host round-trip.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional, Sequence

from ..core.types import LayersSrc
from ..utils.logging import log


@dataclasses.dataclass
class BootResult:
    kind: str  # "full" | "stage"
    seconds: float  # wall time: blob assembly + compile + first forward
    layer_ids: Sequence[int]
    logits: Any = None  # full boots only
    activations: Any = None  # stage boots only
    tokens: Any = None  # full boots with generate_tokens > 0
    # The assembled params stay RESIDENT — they are the product of the
    # dissemination (full boots: the whole pytree; stage boots: this
    # stage's stacked layer dict, on its stage's devices) and what
    # pod-level pipelined serving (runtime/pp_serve.py) consumes.
    params: Any = None


def _device_blob(src) -> Optional[Any]:
    """The layer's HBM-resident uint8 array, when ingest staged one."""
    arr = getattr(src, "device_array", None)
    if arr is None:
        return None
    try:
        import numpy as np

        if arr.dtype == np.uint8 and arr.ndim == 1:
            return arr
    except Exception:  # noqa: BLE001 — any surprise: use host bytes
        return None
    return None


def decode_head(cfg, src, codec: str = "raw"):
    """embed/ln_f/lm_head leaves from a head-blob ``LayerSrc`` — the
    device path when the blob is HBM-resident (jax arrays), the host
    path otherwise (numpy).  Shared by the full boot and pod serving
    (``runtime/pp_serve.py``) so the decode dispatch lives once."""
    from ..models import quant

    dev = _device_blob(src)
    if dev is not None:
        return quant.head_from_device(cfg, dev, codec)
    data = src.inmem_data if src.inmem_data is not None else src.read_bytes()
    return quant.head_from_blob_host(cfg, data, codec)


def decode_after_boot(cfg, res, n: int, tokens=None):
    """Greedy-decode ``n`` tokens from a FULL boot's resident params
    (the KV-cached serving loop, models/generate.py); records
    ``res.tokens``.  THE shared post-boot decode: ``boot_from_layers``'s
    ``generate_tokens`` and the receiver's ``-gen`` both route here, and
    both keep it out of the TTFT clock — serving time, not boot time."""
    import jax
    import jax.numpy as jnp

    from ..models.generate import generate

    if n <= 0:
        return None
    if res.kind != "full" or res.params is None:
        log.warn("decode skipped: -gen needs a FULL boot (this node "
                 "booted a pipeline stage)", kind=res.kind, requested=n)
        return None
    t_gen = time.monotonic()
    if tokens is None:
        tokens = jnp.zeros((1, 16), jnp.int32)
    toks = generate(res.params, tokens, cfg, max_new=n)
    jax.block_until_ready(toks)
    res.tokens = toks
    log.info("decoded tokens after boot", generated=int(toks.shape[1]),
             decode_ms=round((time.monotonic() - t_gen) * 1000, 1))
    return toks


def boot_from_layers(
    cfg,
    layers: LayersSrc,
    placement=None,
    node_id=None,
    tokens=None,
    codec: str = "raw",
    generate_tokens: int = 0,
) -> BootResult:
    """Assemble delivered blobs into model params and run one forward.

    ``layers``: the receiver's store after dissemination.  ``placement``:
    when given (with ``node_id``), params land replicated on this node's
    stage devices via ``StagePlacement``; otherwise the default device.
    ``codec``: the transfer codec the blobs were encoded with
    (``models/quant.py``); quantized ("int8"/"int4") blobs are
    dequantized during assembly — on-device when they were ingested to
    HBM.
    Returns a BootResult whose ``seconds`` is the time from blob assembly
    to the first forward's output being ready (includes jit compile — the
    honest time-to-first-token a cold boot pays)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import quant, serde
    from ..models.llama import forward, layer_apply

    t0 = time.monotonic()
    head_id = serde.head_blob_id(cfg)
    held = sorted(lid for lid in layers if lid <= head_id)
    layer_ids = [lid for lid in held if lid < head_id]
    full = set(held) >= set(range(head_id + 1))
    if not layer_ids:
        raise ValueError(f"no model layer blobs among held layers {held}")
    if layer_ids != list(range(layer_ids[0], layer_ids[0] + len(layer_ids))):
        raise ValueError(f"held layer blobs are not contiguous: {layer_ids}")

    sharding = None
    if placement is not None and node_id in placement.node_to_stage:
        from jax.sharding import PartitionSpec as P
        from jax.sharding import NamedSharding

        sharding = NamedSharding(
            placement.stage_mesh(placement.node_to_stage[node_id]), P()
        )

    # Assembly: device blobs stay on device; host blobs go up in one
    # device_put per leaf-stack.
    dev_blobs = {lid: _device_blob(layers[lid]) for lid in held}
    if all(dev_blobs[lid] is not None for lid in layer_ids):
        stacked = quant.stacked_from_device(
            cfg, [dev_blobs[lid] for lid in layer_ids], codec
        )
        via = "device bitcast" if codec == "raw" else f"device {codec} dequant"
    else:
        blobs = {
            lid: (
                layers[lid].inmem_data
                if layers[lid].inmem_data is not None
                else layers[lid].read_bytes()
            )
            for lid in layer_ids
        }
        host = quant.stacked_from_blobs_host(cfg, blobs, layer_ids, codec)
        stacked = {
            name: jax.device_put(a, sharding) if sharding is not None
            else jnp.asarray(a)
            for name, a in host.items()
        }
        via = "host assembly"

    if full:
        head = decode_head(cfg, layers[head_id], codec)
        if dev_blobs[head_id] is None:
            # Host-decoded leaves: place per the stage sharding.
            head = {
                name: jax.device_put(a, sharding) if sharding is not None
                else jnp.asarray(a)
                for name, a in head.items()
            }
        params = {
            "embed": head["embed"],
            "layers": stacked,
            "ln_f": head["ln_f"],
            "lm_head": head["lm_head"],
        }
        if tokens is None:
            tokens = jnp.zeros((1, 16), jnp.int32)
        logits = jax.jit(forward, static_argnums=2)(params, tokens, cfg)
        jax.block_until_ready(logits)
        # TTFT stops HERE: the decode below is serving time, not boot
        # time — it must not contaminate the metric reported next to TTD.
        dt = time.monotonic() - t0
        log.info("model booted from disseminated layers", kind="full",
                 layers=len(layer_ids), via=via, ttft_ms=round(dt * 1000, 1))
        res = BootResult("full", dt, layer_ids, logits=logits,
                         params=params)
        decode_after_boot(cfg, res, generate_tokens, tokens=tokens)
        return res

    # Stage boot: run this stage's slice on dummy activations.
    def stage_forward(stacked, x):
        positions = jnp.arange(x.shape[1])

        def body(x, layer_p):
            return layer_apply(layer_p, x, positions, cfg), None

        out, _ = jax.lax.scan(body, x, stacked)
        return out

    x = jnp.zeros((1, 16, cfg.d_model), cfg.dtype)
    if sharding is not None:
        x = jax.device_put(x, sharding)
    acts = jax.jit(stage_forward)(stacked, x)
    jax.block_until_ready(acts)
    dt = time.monotonic() - t0
    log.info("pipeline stage booted from disseminated layers", kind="stage",
             layers=len(layer_ids), via=via, ttft_ms=round(dt * 1000, 1))
    return BootResult("stage", dt, layer_ids, activations=acts,
                      params=stacked)
