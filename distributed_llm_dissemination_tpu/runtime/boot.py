"""Model boot from disseminated bytes: the startup hook, made real.

The reference broadcasts a ``startupMsg`` whose handler is a stub — "the
hook that would launch an inference engine"
(``/root/reference/distributor/message.go:216-241``,
``distributor/node.go:1387-1389``).  Here the hook boots one: a receiver
assembles its delivered layer blobs into ``models.llama`` params and runs a
jitted forward pass, so dissemination ends at a *serving model*, not a pile
of bytes — and the leader can report time-to-first-token next to TTD.

Two boot shapes, chosen by what the node holds:
- **full**: the node's blobs cover every layer plus the head blob — the
  whole model boots and produces real logits (the reference benchmark
  scenario: one cold node receives the complete model).
- **stage**: the node holds a contiguous slice of layers (a pipeline
  stage) — its stacked stage params run over dummy activations, proving
  the stage's weights are resident and usable on its devices.

Assembly prefers the device path: blobs that the ``-hbm`` ingest landed in
HBM are bit-reinterpreted on the accelerator (``serde.stacked_from_device_
blobs``) — the disseminated bytes never make a host round-trip.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Dict, Optional, Sequence

from ..core.types import LayersSrc
from ..utils import env as env_util
from ..utils.logging import log

# The boot's jitted programs are MODULE-LEVEL singletons (llama.forward_jit
# and _stage_forward_jitted below): precompile_boot lowers + compiles the
# same callables boot_from_layers calls, so a precompile during
# dissemination turns the boot-time jit call into a cache hit.
_stage_fwd_lock = threading.Lock()
_stage_fwd = None

# ---------------------------------------------- persistent compilation cache
#
# DLD_COMPILE_CACHE_DIR points JAX's persistent compilation cache at a
# directory shared ACROSS runs: the hint-time precompile of run N writes
# it, the boot of run N+1 reads it — a warm host pays zero XLA compile at
# boot, and even a cold host's one-time compile overlaps the wire (the
# BootHint precompile thread).  Thresholds are dropped to zero so the
# boot's whole program set (decode jits included) is cached, not just the
# multi-second forward.  Every boot entry point calls this; it is
# idempotent and re-points (with a cache reset) when the env var changes
# — tests isolate their cache dirs that way.
_cache_lock = threading.Lock()
_cache_applied: Optional[str] = None


def ensure_compile_cache() -> str:
    """Apply ``DLD_COMPILE_CACHE_DIR`` to JAX's persistent compilation
    cache config (idempotent; safe pre- and post-backend-init).  Returns
    the active cache dir ("" = disabled)."""
    global _cache_applied
    target = os.environ.get("DLD_COMPILE_CACHE_DIR", "")
    with _cache_lock:
        if _cache_applied == target:
            return target
        import jax

        try:
            if _cache_applied is not None:
                # Re-point: drop the old singleton cache object so the
                # new dir really takes (jax initializes it lazily once).
                try:
                    from jax._src import compilation_cache as _cc

                    _cc.reset_cache()
                except Exception:  # noqa: BLE001 — older jax: no reset
                    pass
            jax.config.update("jax_compilation_cache_dir", target or None)
            if target:
                for opt, val in (
                    ("jax_persistent_cache_min_compile_time_secs", 0.0),
                    ("jax_persistent_cache_min_entry_size_bytes", -1),
                ):
                    try:
                        jax.config.update(opt, val)
                    except Exception:  # noqa: BLE001 — option drift: the
                        pass  # defaults still cache the big programs
                log.info("persistent compilation cache enabled", dir=target)
        except Exception as e:  # noqa: BLE001 — caching is an optimization
            log.warn("persistent compilation cache unavailable", err=repr(e))
        _cache_applied = target
    return target


def blob_donate_ok(src) -> bool:
    """Whether the boot may CONSUME this blob's device copy (donated
    staging).  Policy (``utils.env.boot_donate_mode``): off = never;
    force = always (tests/benchmarks — unsafe with CPU-adopted buffers);
    auto = only when a host fallback survives the consumption (later
    retransmits / update() cycles read ``inmem_data``/disk once
    ``device_array`` is gone) AND the blob lives on a non-CPU device —
    the CPU backend zero-copy-adopts host buffers, and donating an
    adopted array lets XLA overwrite the memory ``inmem_data`` aliases."""
    mode = env_util.boot_donate_mode()
    if mode == "off":
        return False
    arr = getattr(src, "device_array", None)
    if arr is None:
        return False
    if mode == "force":
        return True
    # A host RAM copy is the only fallback that survives: a bare disk
    # path does NOT — read_span's DISK branch is gated on
    # location == DISK, and an HBM-located record with device_array
    # cleared and no inmem_data has no readable bytes at all.
    if src.inmem_data is None:
        return False
    try:
        return all(d.platform != "cpu" for d in arr.devices())
    except Exception:  # noqa: BLE001 — unknown array kind: keep it
        return False


def _stage_forward_jitted():
    """The stage boot's forward (a scan of layer_apply over the stacked
    stage params), jitted once per process.  The dummy activation input
    is DONATED (it is boot-local and dead after the call) so XLA can run
    the scan's carry in place; the stacked params are NOT — they are the
    boot's product (``BootResult.params``) and must stay resident."""
    global _stage_fwd
    with _stage_fwd_lock:
        if _stage_fwd is None:
            import functools

            import jax
            import jax.numpy as jnp

            from ..models.llama import layer_apply

            @functools.partial(jax.jit, static_argnums=(2,),
                               donate_argnums=(1,))
            def stage_forward(stacked, x, cfg):
                positions = jnp.arange(x.shape[1])

                def body(x, layer_p):
                    return layer_apply(layer_p, x, positions, cfg), None

                out, _ = jax.lax.scan(body, x, stacked)
                return out

            _stage_fwd = stage_forward
    return _stage_fwd


@dataclasses.dataclass
class BootResult:
    kind: str  # "full" | "stage"
    seconds: float  # wall time: blob assembly + compile + first forward
    layer_ids: Sequence[int]
    logits: Any = None  # full boots only
    activations: Any = None  # stage boots only
    tokens: Any = None  # full boots with generate_tokens > 0
    # The assembled params stay RESIDENT — they are the product of the
    # dissemination (full boots: the whole pytree; stage boots: this
    # stage's stacked layer dict, on its stage's devices) and what
    # pod-level pipelined serving (runtime/pp_serve.py) consumes.
    params: Any = None


def classify_held_blobs(cfg, held_ids) -> tuple:
    """The boot's view of a held blob-id set: ``(layer_ids, full)``.
    Raises ValueError for sets no boot shape accepts (no layers, or a
    non-contiguous slice).  THE shared classifier: ``boot_from_layers``
    and ``precompile_boot`` must agree on what a set means, or a hint-
    time precompile warms the wrong program."""
    from ..models import serde

    head_id = serde.head_blob_id(cfg)
    held = sorted(b for b in set(held_ids) if b <= head_id)
    layer_ids = [b for b in held if b < head_id]
    if not layer_ids:
        raise ValueError(f"no model layer blobs among held layers {held}")
    if layer_ids != list(range(layer_ids[0], layer_ids[0] + len(layer_ids))):
        raise ValueError(f"held layer blobs are not contiguous: {layer_ids}")
    full = set(held) >= set(range(head_id + 1))
    return layer_ids, full


def _device_blob(src) -> Optional[Any]:
    """The layer's HBM-resident uint8 array, when ingest staged one."""
    arr = getattr(src, "device_array", None)
    if arr is None:
        return None
    try:
        import numpy as np

        if arr.dtype == np.uint8 and arr.ndim == 1:
            return arr
    except Exception:  # noqa: BLE001 — any surprise: use host bytes
        return None
    return None


def verify_blob_digest(blob_id: int, src, digest_lookup,
                       digest_verified) -> None:
    """Integrity backstop at the boot boundary (docs/integrity.md):
    verify a blob's HOST bytes against its expected layer digest
    (stamp-described algorithm: xxh3-128 or blake2b-128) before any
    decode/device placement dispatches.  Skips blobs the
    receiver's ack gate already verified (``digest_verified``), blobs
    without a known digest, and device-only blobs (their bytes were
    verified before staging).  Raises ``ValueError`` on mismatch — the
    streamed stager fails that blob's staging; the bulk boot fails
    loudly (a corrupted model must never serve)."""
    if digest_lookup is None:
        return
    if digest_verified is not None and blob_id in digest_verified:
        return
    expected = digest_lookup(blob_id)
    if expected is None or src.inmem_data is None:
        return
    from ..utils import integrity, trace

    ok, dt, got = integrity.digest_check(
        memoryview(src.inmem_data)[src.offset : src.offset + src.data_size],
        expected)
    if ok is None:
        return  # xxh3 stamp, no xxhash here: advisory skip
    trace.add_phase("integrity_digest", dt)
    if not ok:
        trace.count("integrity.digest_mismatch")
        raise ValueError(
            f"blob {blob_id} failed its boot-time digest check "
            f"(expected {expected}, got {got})")
    if digest_verified is not None:
        digest_verified.add(blob_id)


def stage_blob_leaves(cfg, blob_id: int, src, codec: str = "raw",
                      sharding=None) -> dict:
    """ONE blob's share of the boot: its decoded leaves, each with a
    leading length-1 axis so assembly is a uniform per-leaf concatenate.
    THE shared per-blob staging: the streaming stager runs it mid-wire
    (``runtime/stream_boot.py``) and ``boot_from_layers`` runs the same
    code to infill any blob the stager missed — both must produce
    identical bits and hit the same compiled programs.

    Device path: the HBM-resident wire blob decodes under the PLAIN
    (non-donated) 1-blob codec jit — callers release consumable blobs by
    dropping references (``blob_donate_ok``), never by ``donate_argnums``
    (a concurrent flow-retransmit reader holding the array would crash
    on an XLA-deleted buffer).  Host path: numpy decode + async
    ``device_put`` per leaf (under ``sharding`` when given)."""
    import jax
    import numpy as np

    from ..models import quant, serde

    head = blob_id == serde.head_blob_id(cfg)
    specs = tuple(serde.head_param_specs(cfg) if head
                  else serde.layer_param_specs(cfg))
    arr = _device_blob(src)
    data = None
    if arr is not None and codec in quant.ENTROPY_CODECS:
        # Entropy forms have no device decode program (docs/codec.md):
        # pull the HBM-resident wire blob back to host, unwrap there
        # (decode_blob_host runs the DLE1 pass before the base decode),
        # and stage via the host path.  Boot-path cost, measured by
        # quant.codec_bench and recorded in TTD_MATRIX.
        data = np.asarray(arr).tobytes()
        if blob_donate_ok(src):
            src.device_array = None
        arr = None
    if arr is not None:
        decode = quant.device_decode_jit(codec, donate=False)
        leaves = decode((arr,), specs, np.dtype(cfg.dtype).name)
        if blob_donate_ok(src):
            src.device_array = None
        return leaves
    if data is None:
        data = (src.inmem_data if src.inmem_data is not None
                else src.read_bytes())
    host = quant.decode_blob_host(cfg, blob_id, data, codec)
    out = {}
    for name, _ in specs:
        a = host[name][None]  # leading axis: uniform concat assembly
        out[name] = (jax.device_put(a, sharding)
                     if sharding is not None else jax.device_put(a))
    return out


def decode_head(cfg, src, codec: str = "raw", donate: bool = False):
    """embed/ln_f/lm_head leaves from a head-blob ``LayerSrc`` — the
    device path when the blob is HBM-resident (jax arrays), the host
    path otherwise (numpy).  Shared by the full boot and pod serving
    (``runtime/pp_serve.py``) so the decode dispatch lives once.
    ``donate``: consume the device blob in place (the record's
    ``device_array`` is cleared — host fallback serves later readers)."""
    from ..models import quant

    dev = _device_blob(src)
    if dev is not None and codec in quant.ENTROPY_CODECS:
        # No device decode program for entropy forms: host unwrap,
        # exactly like stage_blob_leaves (docs/codec.md).
        import numpy as np

        data = np.asarray(dev).tobytes()
        if donate:
            src.device_array = None
        return quant.head_from_blob_host(cfg, data, codec)
    if dev is not None:
        out = quant.head_from_device(cfg, dev, codec, donate=donate)
        if donate:
            src.device_array = None
        return out
    data = src.inmem_data if src.inmem_data is not None else src.read_bytes()
    return quant.head_from_blob_host(cfg, data, codec)


def decode_after_boot(cfg, res, n: int, tokens=None):
    """Greedy-decode ``n`` tokens from a FULL boot's resident params
    (the KV-cached serving loop, models/generate.py); records
    ``res.tokens``.  THE shared post-boot decode: ``boot_from_layers``'s
    ``generate_tokens`` and the receiver's ``-gen`` both route here, and
    both keep it out of the TTFT clock — serving time, not boot time."""
    import jax
    import jax.numpy as jnp

    from ..models.generate import generate

    if n <= 0:
        return None
    if res.kind != "full" or res.params is None:
        log.warn("decode skipped: -gen needs a FULL boot (this node "
                 "booted a pipeline stage)", kind=res.kind, requested=n)
        return None
    t_gen = time.monotonic()
    if tokens is None:
        tokens = jnp.zeros((1, 16), jnp.int32)
    toks = generate(res.params, tokens, cfg, max_new=n)
    jax.block_until_ready(toks)
    res.tokens = toks
    log.info("decoded tokens after boot", generated=int(toks.shape[1]),
             decode_ms=round((time.monotonic() - t_gen) * 1000, 1))
    return toks


def boot_from_layers(
    cfg,
    layers: LayersSrc,
    placement=None,
    node_id=None,
    tokens=None,
    codec: str = "raw",
    generate_tokens: int = 0,
    stager=None,
    digest_lookup=None,
    digest_verified=None,
) -> BootResult:
    """Assemble delivered blobs into model params and run one forward.

    ``layers``: the receiver's store after dissemination.  ``placement``:
    when given (with ``node_id``), params land replicated on this node's
    stage devices via ``StagePlacement``; otherwise the default device.
    ``codec``: the transfer codec the blobs were encoded with
    (``models/quant.py``); quantized ("int8"/"int4") blobs are
    dequantized during assembly — on-device when they were ingested to
    HBM.
    ``stager``: a ``runtime.stream_boot.StreamingBootStager`` that has
    been decoding layers per-blob AS THEY ARRIVED; when it covers every
    layer blob, assembly is one device-local concatenate per leaf (the
    decode + host→device work already overlapped the wire) — bit-
    identical to the bulk paths by construction, completion order
    included (each blob decodes independently; the concat is in layer-id
    order).
    Returns a BootResult whose ``seconds`` is the time from blob assembly
    to the first forward's output being ready (includes jit compile — the
    honest time-to-first-token a cold boot pays)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import quant, serde
    from ..models.llama import forward_jit

    ensure_compile_cache()
    t0 = time.monotonic()
    head_id = serde.head_blob_id(cfg)
    layer_ids, full = classify_held_blobs(cfg, layers)

    # Integrity gate: every host-readable blob verifies against its
    # expected digest before device placement (blobs the receiver's ack
    # path already verified skip in O(1) — ``digest_verified``).  A
    # mismatch raises: a corrupted model must never serve.
    if digest_lookup is not None:
        for lid in sorted(set(layer_ids) | ({head_id} & set(layers))):
            verify_blob_digest(lid, layers[lid], digest_lookup,
                               digest_verified)

    # Wire-codec holdings (docs/codec.md): a blob delivered under a
    # NEGOTIATED per-transfer codec differs in form from the run codec
    # the bulk decode paths below assume.  The streaming stager decodes
    # those per-blob under their own codec (the fast path); any such
    # blob that reaches the bulk/infill paths is normalized here to the
    # canonical raw form on host (wire codecs require a raw-canonical
    # run, core/config.py) so every downstream path sees one uniform
    # codec.  The overlay is local — the receiver's store keeps the
    # encoded holding it announced and serves.
    mixed = [lid for lid in (layer_ids
                             + ([head_id] if head_id in layers else []))
             if getattr(layers[lid].meta, "codec", "")
             and layers[lid].meta.codec != codec]
    if mixed:
        from ..core.types import LayerLocation as _Loc
        from ..core.types import LayerMeta as _Meta
        from ..core.types import LayerSrc as _Src

        layers = dict(layers)
        for lid in mixed:
            src = layers[lid]
            raw = quant.decode_to_raw(cfg, lid, src.read_bytes(),
                                      src.meta.codec)
            layers[lid] = _Src(
                inmem_data=bytearray(raw), data_size=len(raw),
                meta=_Meta(location=_Loc.INMEM))
        log.info("normalized wire-codec blobs for bulk assembly",
                 blobs=mixed)

    sharding = None
    if placement is not None and node_id in placement.node_to_stage:
        from jax.sharding import PartitionSpec as P
        from jax.sharding import NamedSharding

        sharding = NamedSharding(
            placement.stage_mesh(placement.node_to_stage[node_id]), P()
        )

    # Assembly: streamed per-layer leaves splice with one concat per
    # leaf; otherwise device blobs stay on device (donated when safe —
    # the wire blobs are consumed in place instead of doubling the
    # footprint); otherwise host blobs go up in one device_put per
    # leaf-stack.
    held = layer_ids + ([head_id] if head_id in layers else [])
    dev_blobs = {lid: _device_blob(layers[lid]) for lid in held}
    streamed: Dict[int, dict] = {}
    stream_wait_s = 0.0
    if stager is not None:
        t_w = time.monotonic()
        streamed = stager.collect(held)
        stream_wait_s = time.monotonic() - t_w
    stacked = None
    via = ""
    if streamed:
        try:
            missing = [lid for lid in layer_ids if lid not in streamed]
            for lid in missing:
                # Infill: the stager missed this blob (a per-blob
                # failure, or collect hit its timeout) — run the SAME
                # per-blob staging here, so one bad blob costs one
                # inline decode, never a whole-model host reassembly
                # (the stager may already have released the OTHER
                # blobs' device copies).
                streamed[lid] = stage_blob_leaves(
                    cfg, lid, layers[lid], codec=codec, sharding=sharding)
            specs = serde.layer_param_specs(cfg)
            stacked = {
                name: jnp.concatenate(
                    [streamed[lid][name] for lid in layer_ids])
                for name, _ in specs
            }
            # The decoded params exist: blobs whose device copy the boot
            # may consume are released now (same bookkeeping as the
            # donated bulk decode — host fallbacks keep serving late
            # readers).
            for lid in held:
                if lid in streamed and blob_donate_ok(layers[lid]):
                    layers[lid].device_array = None
                    dev_blobs[lid] = None
            via = ("streamed per-layer" if not missing
                   else f"streamed per-layer (+{len(missing)} infilled)")
        except Exception as e:  # noqa: BLE001 — bulk assembly still works
            log.warn("streamed assembly failed; bulk assembly instead",
                     err=repr(e))
            stacked = None
    if stacked is None and all(
            dev_blobs[lid] is not None for lid in layer_ids):
        donate = all(blob_donate_ok(layers[lid]) for lid in layer_ids)
        stacked = quant.stacked_from_device(
            cfg, [dev_blobs[lid] for lid in layer_ids], codec, donate=donate
        )
        via = "device bitcast" if codec == "raw" else f"device {codec} dequant"
        if donate:
            for lid in layer_ids:
                layers[lid].device_array = None
                dev_blobs[lid] = None
            via += " (donated)"
    elif stacked is None:
        blobs = {
            lid: (
                layers[lid].inmem_data
                if layers[lid].inmem_data is not None
                else layers[lid].read_bytes()
            )
            for lid in layer_ids
        }
        host = quant.stacked_from_blobs_host(cfg, blobs, layer_ids, codec)
        stacked = {
            name: jax.device_put(a, sharding) if sharding is not None
            else jnp.asarray(a)
            for name, a in host.items()
        }
        via = "host assembly"

    if full:
        head_on_device = dev_blobs[head_id] is not None
        if head_id in streamed:
            head = {name: a[0] for name, a in streamed[head_id].items()}
            head_on_device = True  # streamed leaves are already placed
        else:
            head = decode_head(cfg, layers[head_id], codec,
                               donate=blob_donate_ok(layers[head_id]))
        if not head_on_device:
            # Host-decoded leaves: place per the stage sharding.
            head = {
                name: jax.device_put(a, sharding) if sharding is not None
                else jnp.asarray(a)
                for name, a in head.items()
            }
        params = {
            "embed": head["embed"],
            "layers": stacked,
            "ln_f": head["ln_f"],
            "lm_head": head["lm_head"],
        }
        if tokens is None:
            tokens = jnp.zeros((1, 16), jnp.int32)
        # forward_jit is the module-level jitted forward: when a
        # BootHintMsg precompile already lowered this shape, the call
        # below is a cache hit and TTFT drops by the compile time.
        logits = forward_jit(params, tokens, cfg)
        jax.block_until_ready(logits)
        # TTFT stops HERE: the decode below is serving time, not boot
        # time — it must not contaminate the metric reported next to TTD.
        dt = time.monotonic() - t0
        log.info("model booted from disseminated layers", kind="full",
                 layers=len(layer_ids), via=via, ttft_ms=round(dt * 1000, 1),
                 stream_wait_ms=round(stream_wait_s * 1000, 1))
        res = BootResult("full", dt, layer_ids, logits=logits,
                         params=params)
        decode_after_boot(cfg, res, generate_tokens, tokens=tokens)
        return res

    # Stage boot: run this stage's slice on dummy activations (the
    # module-level jit, so a hint-time precompile makes this a cache hit).
    x = jnp.zeros((1, 16, cfg.d_model), cfg.dtype)
    if sharding is not None:
        x = jax.device_put(x, sharding)
    acts = _stage_forward_jitted()(stacked, x, cfg)
    jax.block_until_ready(acts)
    dt = time.monotonic() - t0
    log.info("pipeline stage booted from disseminated layers", kind="stage",
             layers=len(layer_ids), via=via, ttft_ms=round(dt * 1000, 1),
             stream_wait_ms=round(stream_wait_s * 1000, 1))
    return BootResult("stage", dt, layer_ids, activations=acts,
                      params=stacked)


def precompile_boot(
    cfg,
    blob_ids: Sequence[int],
    placement=None,
    node_id=None,
    codec: str = "raw",
    device_blobs: bool = False,
    streamed: Optional[bool] = None,
) -> dict:
    """Lower + compile the boot's jitted programs for the held set
    ``blob_ids`` BEFORE the bytes arrive — XLA compiles from shapes
    alone, so a receiver that gets a ``BootHintMsg`` at distribution
    start can overlap the whole compile with the network transfer and
    the post-startup boot hits warm caches.  With a persistent
    compilation cache (``DLD_COMPILE_CACHE_DIR``) the compiles also
    WRITE that cache, so the next run's precompile — or boot — is a
    disk hit instead of an XLA compile.

    Compiles the same module-level callables ``boot_from_layers`` calls
    (``llama.forward_jit`` / ``_stage_forward_jitted`` and, for
    ``device_blobs``, the codec decode jits), so the warm-up needs no
    handle passing.  ``streamed`` (default: the ``DLD_STREAM_BOOT`` env
    gate, matching the receiver) picks which decode program to warm:
    the streaming stager decodes ONE blob per call, the bulk boot
    decodes all n under one jit — distinct cache entries.  The donated
    decode twin is warmed when the donation mode resolves on for the
    target devices (per-blob host-fallback checks at boot time can still
    demote a blob — only a cache miss).  Returns {"compiled": [...]}
    naming what was warmed (for logs and tests).  Best-effort by design:
    any mismatch with the real boot (different path, sharding, shapes)
    is only a cache miss."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import quant, serde
    from ..models.llama import forward_jit

    cache_dir = ensure_compile_cache()
    if streamed is None:
        streamed = env_util.stream_boot_enabled()
    head_id = serde.head_blob_id(cfg)
    try:
        layer_ids, full = classify_held_blobs(cfg, blob_ids)
    except ValueError:
        return {"compiled": []}  # boot_from_layers would reject this set
    n = len(layer_ids)
    dt = cfg.dtype
    dt_name = np.dtype(dt).name

    # Sharding discipline mirrors boot_from_layers EXACTLY — jit cache
    # keys include argument shardings (committed-ness included), and the
    # two real paths differ:
    # - host assembly: every leaf is device_put with the stage sharding
    #   (committed) when a placement maps this node, else jnp.asarray
    #   (uncommitted);
    # - device (-hbm) assembly: the staged wire blobs are COMMITTED to
    #   the stage device (device_put / host-buffer adoption / the
    #   make_array gather — every ingest arm), and jit outputs inherit
    #   their inputs' commitment, so the decode outputs feeding the
    #   forward are committed to the same device.  The stage boot's
    #   dummy activations are still device_put with the stage sharding.
    stage_sharding = None
    stage_devs = None
    if placement is not None and node_id in placement.node_to_stage:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        stage_sharding = NamedSharding(
            placement.stage_mesh(placement.node_to_stage[node_id]), P()
        )
        stage_devs = list(placement.devices_for_node(node_id))
    if device_blobs:
        devs = stage_devs or [jax.devices()[0]]
        if len(devs) == 1:
            dev_sharding = jax.sharding.SingleDeviceSharding(devs[0])
        else:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from ..parallel.ingest import flat_mesh

            dev_sharding = NamedSharding(flat_mesh(devs), P())
        leaf_sharding = dev_sharding
    else:
        dev_sharding = None
        leaf_sharding = stage_sharding
    x_sharding = stage_sharding

    def sds(shape, dtype, sharding):
        if sharding is not None:
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
        return jax.ShapeDtypeStruct(shape, dtype)

    compiled = []
    t0 = time.monotonic()
    layer_specs = tuple(serde.layer_param_specs(cfg))
    stacked_abs = {name: sds((n, *shape), dt, leaf_sharding)
                   for name, shape in layer_specs}

    if device_blobs:
        # The -hbm path decodes HBM-resident wire blobs under these
        # jits.  The exact callable matters (donated and plain variants
        # are distinct executables): the STREAMING stager always runs
        # the PLAIN 1-blob program (it releases blobs by reference, not
        # donate_argnums — stream_boot._stage_one), while the bulk boot
        # runs the donated n-blob variant when donation resolves on for
        # these devices (blob_donate_ok minus the per-blob host-fallback
        # check, unknowable from shapes alone).
        if streamed:
            # One plain 1-blob program covers every layer blob; the
            # head's is warmed below.
            decode = quant.device_decode_jit(codec, donate=False)
            one = (sds((quant.blob_nbytes_codec(cfg, layer_ids[0], codec),),
                       jnp.uint8, dev_sharding),)
            decode.lower(one, layer_specs, dt_name).compile()
            compiled.append(f"decode[{codec}]x1")
        else:
            mode = env_util.boot_donate_mode()
            donate = (mode == "force"
                      or (mode == "auto"
                          and all(d.platform != "cpu" for d in devs)))
            decode = quant.device_decode_jit(codec, donate)
            blob_abs = tuple(
                sds((quant.blob_nbytes_codec(cfg, lid, codec),),
                    jnp.uint8, dev_sharding)
                for lid in layer_ids
            )
            decode.lower(blob_abs, layer_specs, dt_name).compile()
            compiled.append(f"decode[{codec}]x{n}")
        if full:
            head_abs = (sds(
                (quant.blob_nbytes_codec(cfg, head_id, codec),),
                jnp.uint8, dev_sharding),)
            decode.lower(
                head_abs, tuple(serde.head_param_specs(cfg)), dt_name
            ).compile()
            compiled.append(f"decode[{codec}]head")

    if full:
        head_abs = {name: sds(shape, dt, leaf_sharding)
                    for name, shape in serde.head_param_specs(cfg)}
        params_abs = {
            "embed": head_abs["embed"],
            "layers": stacked_abs,
            "ln_f": head_abs["ln_f"],
            "lm_head": head_abs["lm_head"],
        }
        tok_abs = jax.ShapeDtypeStruct((1, 16), jnp.int32)
        forward_jit.lower(params_abs, tok_abs, cfg).compile()
        compiled.append("forward")
    else:
        x_abs = sds((1, 16, cfg.d_model), dt, x_sharding)
        _stage_forward_jitted().lower(stacked_abs, x_abs, cfg).compile()
        compiled.append("stage_forward")
    return {"compiled": compiled,
            "compile_s": round(time.monotonic() - t0, 2),
            "persistent_cache": bool(cache_dir)}
