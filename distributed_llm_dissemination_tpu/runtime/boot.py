"""Model boot from disseminated bytes: the startup hook, made real.

The reference broadcasts a ``startupMsg`` whose handler is a stub — "the
hook that would launch an inference engine"
(``/root/reference/distributor/message.go:216-241``,
``distributor/node.go:1387-1389``).  Here the hook boots one: a receiver
assembles its delivered layer blobs into ``models.llama`` params and runs a
jitted forward pass, so dissemination ends at a *serving model*, not a pile
of bytes — and the leader can report time-to-first-token next to TTD.

Two boot shapes, chosen by what the node holds:
- **full**: the node's blobs cover every layer plus the head blob — the
  whole model boots and produces real logits (the reference benchmark
  scenario: one cold node receives the complete model).
- **stage**: the node holds a contiguous slice of layers (a pipeline
  stage) — its stacked stage params run over dummy activations, proving
  the stage's weights are resident and usable on its devices.

Assembly prefers the device path: blobs that the ``-hbm`` ingest landed in
HBM are bit-reinterpreted on the accelerator (``serde.stacked_from_device_
blobs``) — the disseminated bytes never make a host round-trip.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Optional, Sequence

from ..core.types import LayersSrc
from ..utils.logging import log

# The boot's jitted programs are MODULE-LEVEL singletons (llama.forward_jit
# and _stage_forward_jitted below): precompile_boot lowers + compiles the
# same callables boot_from_layers calls, so a precompile during
# dissemination turns the boot-time jit call into a cache hit.
_stage_fwd_lock = threading.Lock()
_stage_fwd = None


def _stage_forward_jitted():
    """The stage boot's forward (a scan of layer_apply over the stacked
    stage params), jitted once per process."""
    global _stage_fwd
    with _stage_fwd_lock:
        if _stage_fwd is None:
            import functools

            import jax
            import jax.numpy as jnp

            from ..models.llama import layer_apply

            @functools.partial(jax.jit, static_argnums=(2,))
            def stage_forward(stacked, x, cfg):
                positions = jnp.arange(x.shape[1])

                def body(x, layer_p):
                    return layer_apply(layer_p, x, positions, cfg), None

                out, _ = jax.lax.scan(body, x, stacked)
                return out

            _stage_fwd = stage_forward
    return _stage_fwd


@dataclasses.dataclass
class BootResult:
    kind: str  # "full" | "stage"
    seconds: float  # wall time: blob assembly + compile + first forward
    layer_ids: Sequence[int]
    logits: Any = None  # full boots only
    activations: Any = None  # stage boots only
    tokens: Any = None  # full boots with generate_tokens > 0
    # The assembled params stay RESIDENT — they are the product of the
    # dissemination (full boots: the whole pytree; stage boots: this
    # stage's stacked layer dict, on its stage's devices) and what
    # pod-level pipelined serving (runtime/pp_serve.py) consumes.
    params: Any = None


def classify_held_blobs(cfg, held_ids) -> tuple:
    """The boot's view of a held blob-id set: ``(layer_ids, full)``.
    Raises ValueError for sets no boot shape accepts (no layers, or a
    non-contiguous slice).  THE shared classifier: ``boot_from_layers``
    and ``precompile_boot`` must agree on what a set means, or a hint-
    time precompile warms the wrong program."""
    from ..models import serde

    head_id = serde.head_blob_id(cfg)
    held = sorted(b for b in set(held_ids) if b <= head_id)
    layer_ids = [b for b in held if b < head_id]
    if not layer_ids:
        raise ValueError(f"no model layer blobs among held layers {held}")
    if layer_ids != list(range(layer_ids[0], layer_ids[0] + len(layer_ids))):
        raise ValueError(f"held layer blobs are not contiguous: {layer_ids}")
    full = set(held) >= set(range(head_id + 1))
    return layer_ids, full


def _device_blob(src) -> Optional[Any]:
    """The layer's HBM-resident uint8 array, when ingest staged one."""
    arr = getattr(src, "device_array", None)
    if arr is None:
        return None
    try:
        import numpy as np

        if arr.dtype == np.uint8 and arr.ndim == 1:
            return arr
    except Exception:  # noqa: BLE001 — any surprise: use host bytes
        return None
    return None


def decode_head(cfg, src, codec: str = "raw"):
    """embed/ln_f/lm_head leaves from a head-blob ``LayerSrc`` — the
    device path when the blob is HBM-resident (jax arrays), the host
    path otherwise (numpy).  Shared by the full boot and pod serving
    (``runtime/pp_serve.py``) so the decode dispatch lives once."""
    from ..models import quant

    dev = _device_blob(src)
    if dev is not None:
        return quant.head_from_device(cfg, dev, codec)
    data = src.inmem_data if src.inmem_data is not None else src.read_bytes()
    return quant.head_from_blob_host(cfg, data, codec)


def decode_after_boot(cfg, res, n: int, tokens=None):
    """Greedy-decode ``n`` tokens from a FULL boot's resident params
    (the KV-cached serving loop, models/generate.py); records
    ``res.tokens``.  THE shared post-boot decode: ``boot_from_layers``'s
    ``generate_tokens`` and the receiver's ``-gen`` both route here, and
    both keep it out of the TTFT clock — serving time, not boot time."""
    import jax
    import jax.numpy as jnp

    from ..models.generate import generate

    if n <= 0:
        return None
    if res.kind != "full" or res.params is None:
        log.warn("decode skipped: -gen needs a FULL boot (this node "
                 "booted a pipeline stage)", kind=res.kind, requested=n)
        return None
    t_gen = time.monotonic()
    if tokens is None:
        tokens = jnp.zeros((1, 16), jnp.int32)
    toks = generate(res.params, tokens, cfg, max_new=n)
    jax.block_until_ready(toks)
    res.tokens = toks
    log.info("decoded tokens after boot", generated=int(toks.shape[1]),
             decode_ms=round((time.monotonic() - t_gen) * 1000, 1))
    return toks


def boot_from_layers(
    cfg,
    layers: LayersSrc,
    placement=None,
    node_id=None,
    tokens=None,
    codec: str = "raw",
    generate_tokens: int = 0,
) -> BootResult:
    """Assemble delivered blobs into model params and run one forward.

    ``layers``: the receiver's store after dissemination.  ``placement``:
    when given (with ``node_id``), params land replicated on this node's
    stage devices via ``StagePlacement``; otherwise the default device.
    ``codec``: the transfer codec the blobs were encoded with
    (``models/quant.py``); quantized ("int8"/"int4") blobs are
    dequantized during assembly — on-device when they were ingested to
    HBM.
    Returns a BootResult whose ``seconds`` is the time from blob assembly
    to the first forward's output being ready (includes jit compile — the
    honest time-to-first-token a cold boot pays)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import quant, serde
    from ..models.llama import forward_jit

    t0 = time.monotonic()
    head_id = serde.head_blob_id(cfg)
    layer_ids, full = classify_held_blobs(cfg, layers)

    sharding = None
    if placement is not None and node_id in placement.node_to_stage:
        from jax.sharding import PartitionSpec as P
        from jax.sharding import NamedSharding

        sharding = NamedSharding(
            placement.stage_mesh(placement.node_to_stage[node_id]), P()
        )

    # Assembly: device blobs stay on device; host blobs go up in one
    # device_put per leaf-stack.
    held = layer_ids + ([head_id] if head_id in layers else [])
    dev_blobs = {lid: _device_blob(layers[lid]) for lid in held}
    if all(dev_blobs[lid] is not None for lid in layer_ids):
        stacked = quant.stacked_from_device(
            cfg, [dev_blobs[lid] for lid in layer_ids], codec
        )
        via = "device bitcast" if codec == "raw" else f"device {codec} dequant"
    else:
        blobs = {
            lid: (
                layers[lid].inmem_data
                if layers[lid].inmem_data is not None
                else layers[lid].read_bytes()
            )
            for lid in layer_ids
        }
        host = quant.stacked_from_blobs_host(cfg, blobs, layer_ids, codec)
        stacked = {
            name: jax.device_put(a, sharding) if sharding is not None
            else jnp.asarray(a)
            for name, a in host.items()
        }
        via = "host assembly"

    if full:
        head = decode_head(cfg, layers[head_id], codec)
        if dev_blobs[head_id] is None:
            # Host-decoded leaves: place per the stage sharding.
            head = {
                name: jax.device_put(a, sharding) if sharding is not None
                else jnp.asarray(a)
                for name, a in head.items()
            }
        params = {
            "embed": head["embed"],
            "layers": stacked,
            "ln_f": head["ln_f"],
            "lm_head": head["lm_head"],
        }
        if tokens is None:
            tokens = jnp.zeros((1, 16), jnp.int32)
        # forward_jit is the module-level jitted forward: when a
        # BootHintMsg precompile already lowered this shape, the call
        # below is a cache hit and TTFT drops by the compile time.
        logits = forward_jit(params, tokens, cfg)
        jax.block_until_ready(logits)
        # TTFT stops HERE: the decode below is serving time, not boot
        # time — it must not contaminate the metric reported next to TTD.
        dt = time.monotonic() - t0
        log.info("model booted from disseminated layers", kind="full",
                 layers=len(layer_ids), via=via, ttft_ms=round(dt * 1000, 1))
        res = BootResult("full", dt, layer_ids, logits=logits,
                         params=params)
        decode_after_boot(cfg, res, generate_tokens, tokens=tokens)
        return res

    # Stage boot: run this stage's slice on dummy activations (the
    # module-level jit, so a hint-time precompile makes this a cache hit).
    x = jnp.zeros((1, 16, cfg.d_model), cfg.dtype)
    if sharding is not None:
        x = jax.device_put(x, sharding)
    acts = _stage_forward_jitted()(stacked, x, cfg)
    jax.block_until_ready(acts)
    dt = time.monotonic() - t0
    log.info("pipeline stage booted from disseminated layers", kind="stage",
             layers=len(layer_ids), via=via, ttft_ms=round(dt * 1000, 1))
    return BootResult("stage", dt, layer_ids, activations=acts,
                      params=stacked)


def precompile_boot(
    cfg,
    blob_ids: Sequence[int],
    placement=None,
    node_id=None,
    codec: str = "raw",
    device_blobs: bool = False,
) -> dict:
    """Lower + compile the boot's jitted programs for the held set
    ``blob_ids`` BEFORE the bytes arrive — XLA compiles from shapes
    alone, so a receiver that gets a ``BootHintMsg`` at distribution
    start can overlap the whole compile with the network transfer and
    the post-startup boot hits warm caches.

    Compiles the same module-level callables ``boot_from_layers`` calls
    (``llama.forward_jit`` / ``_stage_forward_jitted`` and, for
    ``device_blobs``, the codec decode jits), so the warm-up needs no
    handle passing.  Returns {"compiled": [...]} naming what was warmed
    (for logs and tests).  Best-effort by design: any mismatch with the
    real boot (different path, sharding, shapes) is only a cache miss."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import quant, serde
    from ..models.llama import forward_jit

    head_id = serde.head_blob_id(cfg)
    try:
        layer_ids, full = classify_held_blobs(cfg, blob_ids)
    except ValueError:
        return {"compiled": []}  # boot_from_layers would reject this set
    n = len(layer_ids)
    dt = cfg.dtype
    dt_name = np.dtype(dt).name

    # Sharding discipline mirrors boot_from_layers EXACTLY — jit cache
    # keys include argument shardings (committed-ness included), and the
    # two real paths differ:
    # - host assembly: every leaf is device_put with the stage sharding
    #   (committed) when a placement maps this node, else jnp.asarray
    #   (uncommitted);
    # - device (-hbm) assembly: the staged wire blobs are COMMITTED to
    #   the stage device (device_put / host-buffer adoption / the
    #   make_array gather — every ingest arm), and jit outputs inherit
    #   their inputs' commitment, so the decode outputs feeding the
    #   forward are committed to the same device.  The stage boot's
    #   dummy activations are still device_put with the stage sharding.
    stage_sharding = None
    stage_devs = None
    if placement is not None and node_id in placement.node_to_stage:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        stage_sharding = NamedSharding(
            placement.stage_mesh(placement.node_to_stage[node_id]), P()
        )
        stage_devs = list(placement.devices_for_node(node_id))
    if device_blobs:
        devs = stage_devs or [jax.devices()[0]]
        if len(devs) == 1:
            dev_sharding = jax.sharding.SingleDeviceSharding(devs[0])
        else:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from ..parallel.ingest import flat_mesh

            dev_sharding = NamedSharding(flat_mesh(devs), P())
        leaf_sharding = dev_sharding
    else:
        dev_sharding = None
        leaf_sharding = stage_sharding
    x_sharding = stage_sharding

    def sds(shape, dtype, sharding):
        if sharding is not None:
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
        return jax.ShapeDtypeStruct(shape, dtype)

    compiled = []
    t0 = time.monotonic()
    layer_specs = tuple(serde.layer_param_specs(cfg))
    stacked_abs = {name: sds((n, *shape), dt, leaf_sharding)
                   for name, shape in layer_specs}

    if device_blobs:
        # The -hbm path decodes HBM-resident wire blobs under these jits.
        decode = {"raw": serde._decode_blobs,
                  "int8": quant._decode_qblobs,
                  "int4": quant._decode_q4blobs}[codec]
        blob_abs = tuple(
            sds((quant.blob_nbytes_codec(cfg, lid, codec),),
                jnp.uint8, dev_sharding)
            for lid in layer_ids
        )
        decode.lower(blob_abs, layer_specs, dt_name).compile()
        compiled.append(f"decode[{codec}]x{n}")
        if full:
            head_abs = (sds(
                (quant.blob_nbytes_codec(cfg, head_id, codec),),
                jnp.uint8, dev_sharding),)
            decode.lower(
                head_abs, tuple(serde.head_param_specs(cfg)), dt_name
            ).compile()
            compiled.append(f"decode[{codec}]head")

    if full:
        head_abs = {name: sds(shape, dt, leaf_sharding)
                    for name, shape in serde.head_param_specs(cfg)}
        params_abs = {
            "embed": head_abs["embed"],
            "layers": stacked_abs,
            "ln_f": head_abs["ln_f"],
            "lm_head": head_abs["lm_head"],
        }
        tok_abs = jax.ShapeDtypeStruct((1, 16), jnp.int32)
        forward_jit.lower(params_abs, tok_abs, cfg).compile()
        compiled.append("forward")
    else:
        x_abs = sds((1, 16, cfg.d_model), dt, x_sharding)
        _stage_forward_jitted().lower(stacked_abs, x_abs, cfg).compile()
        compiled.append("stage_forward")
    return {"compiled": compiled,
            "compile_s": round(time.monotonic() - t0, 2)}
