"""Control-plane high availability: survive the leader (docs/failover.md).

The reference leaves node fault handling TODO (``crash(n node)``,
node.go:218-220); this repo added worker crash detection (PR on
``runtime/failure.py``) — but nothing monitored the LEADER: its death
froze every in-flight run.  This module closes that gap with three
cooperating pieces:

- :class:`ControlReplicator` — the leader half: every control-state
  mutation (status rows, acks, partial coverage, dropped assignments,
  digest stamps, mode-3 plan seq) streams to a config-declared ordered
  list of standbys as epoch-stamped ``ControlDeltaMsg``s — a full
  snapshot when a standby joins, deltas thereafter.
- :class:`ShadowLeaderState` — the standby half: the replicated shadow
  of the leader's control state, enough to construct a real leader of
  the run's mode at takeover.
- :class:`StandbyController` — lease watching + deterministic
  succession: the leader beacons ``LeaderLeaseMsg``; each standby feeds
  it to the existing :class:`~.failure.FailureDetector` with an expiry
  staggered by its succession rank, so the lowest-ranked LIVE standby
  fires first, promotes at ``epoch + 1``, and its first lease at the
  higher epoch IS the takeover announcement.  Workers re-point their
  leader and re-announce (inventory + checkpointed partials), so the
  new leader resumes delivery from partial coverage instead of
  restarting; every control message below the highest epoch seen is
  FENCED — a zombie ex-leader's plans are rejected, not raced.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

from ..core.types import (
    LayerLocation,
    LayerMeta,
    NodeID,
    layer_ids_from_json,
    layer_ids_to_json,
)
from ..transport.messages import ControlDeltaMsg, LeaderLeaseMsg
from ..utils import trace
from ..utils.logging import log
from .failure import FailureDetector


def _nested_layer_map_to_json(m: dict) -> dict:
    return {str(n): layer_ids_to_json(row) for n, row in m.items()}


def _nested_layer_map_from_json(d: dict) -> dict:
    return {int(n): layer_ids_from_json(row or {})
            for n, row in (d or {}).items()}


def _partial_to_json(p: dict) -> dict:
    # node -> {layer: {"Total": n, "Covered": [[s, e], ...]}}
    return {str(n): {str(l): info for l, info in per.items()}
            for n, per in p.items()}


def _partial_from_json(d: dict) -> dict:
    return {int(n): {int(l): info for l, info in (per or {}).items()}
            for n, per in (d or {}).items()}


class ControlReplicator:
    """Streams the leader's control-state mutations to its standbys.

    Best-effort by design: a delta lost to a dead standby only degrades
    that standby's shadow, and takeover reconciliation (every worker
    re-announces to the new leader) repairs any divergence — the shadow
    buys recovery SPEED, the re-announce buys correctness.

    Replication is ASYNCHRONOUS: publish() only enqueues; one drain
    thread per standby does the actual (possibly slow, dial-retrying)
    sends.  The hot control handlers that publish (handle_ack,
    handle_announce) must never stall behind a dead standby's TCP dial
    window — that would freeze the very control plane HA exists to
    protect.  Per-standby queues keep per-target delta ORDER; a full
    queue drops the delta (counted) rather than blocking the leader."""

    QUEUE_DEPTH = 1024

    def __init__(self, node, standbys: List[NodeID]):
        import queue as _queue

        self.node = node
        self.standbys = [s for s in standbys if s != node.my_id]
        self._seq = itertools.count()
        self._stop = threading.Event()
        self._queues = {}
        for s in self.standbys:
            self._queues[s] = _queue.Queue(maxsize=self.QUEUE_DEPTH)
            threading.Thread(target=self._drain, args=(s,), daemon=True,
                             name=f"replicate-{s}").start()

    def publish(self, epoch: int, kind: str, data: dict) -> None:
        for standby in self.standbys:
            self.publish_to(standby, epoch, kind, data)

    def publish_to(self, standby: NodeID, epoch: int, kind: str,
                   data: dict) -> None:
        import queue as _queue

        q = self._queues.get(standby)
        if q is None:
            return
        msg = ControlDeltaMsg(self.node.my_id, epoch, next(self._seq),
                              kind, data)
        try:
            q.put_nowait(msg)
        except _queue.Full:
            # Best-effort by contract: the reconcile re-announce repairs
            # a lossy shadow; blocking the leader would not.
            trace.count("failover.replica_dropped")

    def _drain(self, standby: NodeID) -> None:
        import queue as _queue

        q = self._queues[standby]
        while not self._stop.is_set():
            try:
                msg = q.get(timeout=0.2)
            except _queue.Empty:
                continue
            try:
                self.node.add_node(standby)
                self.node.transport.send(standby, msg)
            except (OSError, KeyError) as e:
                log.debug("control delta send failed", standby=standby,
                          kind=msg.kind, err=repr(e))

    def close(self) -> None:
        self._stop.set()


class ShadowLeaderState:
    """A standby's replicated view of the leader's control state."""

    def __init__(self):
        self._lock = threading.Lock()
        self.mode: Optional[int] = None
        self.assignment: dict = {}
        self.status: dict = {}
        self.partial: dict = {}
        self.dropped: dict = {}
        self.digests: Dict[int, str] = {}
        self.plan_seq = 0
        self.startup_sent = False
        self.network_bw: Dict[NodeID, int] = {}
        self.failure_timeout = 0.0
        self.boot_enabled = True
        # Telemetry plane (docs/observability.md): the leader's folded
        # per-node metric snapshots ride replication too, so a takeover
        # keeps the cluster picture instead of starting blind.
        self.metrics: dict = {}
        # Fleet health timeline (docs/observability.md): the derived
        # event ring (straggler onsets and recoveries) — a promoted
        # standby keeps the health history, not just the raw counters.
        self.health: dict = {}
        # Autonomy engine (docs/autonomy.md): the policy engine's full
        # state — armed rules, cooldowns (remaining seconds), breach
        # streaks, quarantine mask, link demotions, in-flight actions
        # and the audit tail — a promoted standby inherits the closed
        # loop mid-action.
        self.policy: dict = {}
        # Mode-3 plan generation counter: fences stale revoke/dispatch
        # pairs across replans (docs/service.md, wrong-eat race).
        self.plan_gen = 0
        # Job plane (docs/service.md): the admitted-job table (raw
        # replication records, ``sched.jobs.JobManager.record``) and the
        # BASE single-run goal (``assignment`` above is the MERGED
        # effective goal) — a promoted standby resumes every job.
        self.jobs: dict = {}
        self.base_assignment: Optional[dict] = None
        # Live-swap driver records (docs/swap.md): version -> record,
        # so a promoted standby resumes (or re-fences) a half-finished
        # weight swap instead of stranding the fleet mid-rollout.
        self.swaps: dict = {}
        # Rollout pipeline records (docs/rollout.md): rollout_id ->
        # record (waves, per-wave states, SLO, verdicts, split) — a
        # promoted standby resumes the pipeline MID-WAVE with the
        # guard still armed.
        self.rollouts: dict = {}
        # Wire-codec plane (docs/codec.md): the leader's per-(dest,
        # layer) codec choices and the cluster capability table, so a
        # promoted leader keeps planning the byte spaces in-flight
        # encoded partials live in.
        self.wire_codecs: Dict[Tuple[NodeID, int], str] = {}
        self.node_codecs: Dict[NodeID, list] = {}
        # Pod-delivery plane (docs/fabric.md): the LIVE pod membership —
        # ``{"Table": {pid: [members]}, "Broken": [pids]}``.  A standby
        # promoted after a pod break/widen must adopt the broken set, or
        # its first re-plan would re-slice (resurrect) a pod whose
        # gather contributions can never arrive.
        self.pods: dict = {}
        # Hierarchical control (docs/hierarchy.md): the group table
        # (``{gid: {"Leader", "Members", "Dissolved"}}``) — a promoted
        # standby must reconstruct the SAME hierarchy (or its dissolved
        # remains), not fall back to flat planning.
        self.groups: dict = {}
        # Elastic membership (docs/membership.md): the replicated
        # roster (``{node: record}``) + the in-flight drain re-home
        # jobs (``{job_id: node}``) — a promoted standby resumes
        # admission and drains, and keeps departed members fenced.
        self.membership: dict = {}
        self.drain_jobs: dict = {}
        self.have_snapshot = False
        self.deltas_applied = 0

    @staticmethod
    def _codec_choices(d: dict) -> Dict[Tuple[NodeID, int], str]:
        out: Dict[Tuple[NodeID, int], str] = {}
        for key, c in (d or {}).items():
            try:
                dest, lid = str(key).split(":", 1)
                out[(int(dest), int(lid))] = str(c)
            except ValueError:
                continue
        return out

    def apply(self, msg: ControlDeltaMsg) -> None:
        d = msg.data
        with self._lock:
            self.deltas_applied += 1
            k = msg.kind
            if k == "snapshot":
                self.mode = int(d.get("Mode", 0))
                self.assignment = _nested_layer_map_from_json(
                    d.get("Assignment"))
                self.status = _nested_layer_map_from_json(d.get("Status"))
                self.partial = _partial_from_json(d.get("Partial"))
                self.dropped = _nested_layer_map_from_json(d.get("Dropped"))
                self.digests = {int(l): str(h)
                                for l, h in (d.get("Digests") or {}).items()}
                self.plan_seq = int(d.get("PlanSeq", 0))
                self.startup_sent = bool(d.get("StartupSent", False))
                self.network_bw = {int(n): int(b) for n, b in
                                   (d.get("NetworkBw") or {}).items()}
                self.failure_timeout = float(d.get("FailureTimeout", 0.0))
                self.boot_enabled = bool(d.get("BootEnabled", True))
                self.metrics = {int(n): dict(s) for n, s in
                                (d.get("Metrics") or {}).items()}
                self.health = dict(d.get("Health") or {})
                self.policy = dict(d.get("Policy") or {})
                self.plan_gen = int(d.get("PlanGen", 0))
                self.jobs = {str(j): dict(rec) for j, rec in
                             (d.get("Jobs") or {}).items()}
                self.swaps = {str(v): dict(rec) for v, rec in
                              (d.get("Swaps") or {}).items()}
                self.rollouts = {str(r): dict(rec) for r, rec in
                                 (d.get("Rollouts") or {}).items()}
                self.wire_codecs = self._codec_choices(d.get("WireCodecs"))
                self.node_codecs = {
                    int(n): [str(c) for c in caps]
                    for n, caps in (d.get("NodeCodecs") or {}).items()}
                if d.get("BaseAssignment") is not None:
                    self.base_assignment = _nested_layer_map_from_json(
                        d.get("BaseAssignment"))
                self.groups = {str(g): dict(rec) for g, rec in
                               (d.get("Groups") or {}).items()}
                self.pods = dict(d.get("Pods") or {})
                self.membership = {str(n): dict(rec) for n, rec in
                                   (d.get("Membership") or {}).items()}
                self.drain_jobs = {str(j): int(n) for j, n in
                                   (d.get("DrainJobs") or {}).items()}
                self.have_snapshot = True
            elif k == "status":
                self.status[int(d["Node"])] = layer_ids_from_json(
                    d.get("Layers") or {})
            elif k == "ack":
                row = self.status.setdefault(int(d["Node"]), {})
                row[int(d["Layer"])] = LayerMeta(
                    location=LayerLocation(int(d.get("Location", 0))),
                    data_size=int(d.get("Size", 0)),
                    shard=str(d.get("Shard", "")),
                    version=str(d.get("Version", "") or ""),
                    codec=str(d.get("Codec", "") or ""))
            elif k == "partial":
                node = int(d["Node"])
                per = d.get("Partial")
                if per:
                    self.partial[node] = {int(l): info
                                          for l, info in per.items()}
                else:
                    self.partial.pop(node, None)
            elif k == "crash":
                node = int(d["Node"])
                self.status.pop(node, None)
                dropped = d.get("Dropped")
                if dropped:
                    self.dropped[node] = layer_ids_from_json(dropped)
                    self.assignment.pop(node, None)
            elif k == "assignment":
                self.assignment = _nested_layer_map_from_json(
                    d.get("Assignment"))
                self.dropped = {}
            elif k == "digests":
                for l, h in (d.get("Digests") or {}).items():
                    self.digests[int(l)] = str(h)
            elif k == "startup":
                self.startup_sent = bool(d.get("Sent", True))
            elif k == "plan_seq":
                self.plan_seq = max(self.plan_seq, int(d.get("Seq", 0)))
            elif k == "revive":
                # A declared-dead node re-announced and was restored:
                # it is no longer written off (the adopt-time job-pair
                # re-drop must not hit a live dest).
                self.dropped.pop(int(d["Node"]), None)
            elif k == "base_assignment":
                self.base_assignment = _nested_layer_map_from_json(
                    d.get("Assignment"))
            elif k == "groups":
                # The hierarchical group table (docs/hierarchy.md):
                # always the full current table (a dissolve re-sends
                # it), so REPLACE.
                self.groups = {str(g): dict(rec) for g, rec in
                               (d.get("Groups") or {}).items()}
            elif k == "pods":
                # Pod membership (docs/fabric.md): always the full
                # current table + broken set (a break re-sends it), so
                # REPLACE like the group table.
                self.pods = dict(d.get("Pods") or {})
            elif k == "codecs":
                # Wire-codec choices + capability table (docs/codec.md).
                # REPLACE, don't merge: the delta always carries the
                # leader's full current maps, and a revoked capability
                # (or a reverted choice) is exactly an ABSENT entry —
                # an update-merge would resurrect it at takeover.
                self.wire_codecs = self._codec_choices(d.get("Choices"))
                self.node_codecs = {
                    int(n): [str(c) for c in caps]
                    for n, caps in (d.get("NodeCodecs") or {}).items()}
            elif k == "job":
                self.jobs[str(d["JobID"])] = dict(d)
            elif k == "swap":
                self.swaps[str(d["Version"])] = dict(d)
            elif k == "job_done":
                rec = self.jobs.get(str(d.get("JobID", "")))
                if rec is not None:
                    rec["State"] = "done"
                    rec["Remaining"] = []
            elif k == "membership":
                # Elastic membership (docs/membership.md): always the
                # leader's full current roster + drain map — REPLACE,
                # so a departed seat is exactly an absent/LEFT row.
                self.membership = {str(n): dict(rec) for n, rec in
                                   (d.get("Members") or {}).items()}
                self.drain_jobs = {str(j): int(n) for j, n in
                                   (d.get("DrainJobs") or {}).items()}
            elif k == "member_left":
                # A clean leave (drain finalize): the seat's control
                # rows vanish WITHOUT entering the dropped/crash
                # bookkeeping — a takeover must not resurrect it or
                # re-apply crash-path drops against it.
                node = int(d["Node"])
                self.status.pop(node, None)
                self.assignment.pop(node, None)
                self.partial.pop(node, None)
                self.dropped.pop(node, None)
            elif k == "metrics":
                self.metrics[int(d["Node"])] = {
                    "counters": dict(d.get("Counters") or {}),
                    "gauges": dict(d.get("Gauges") or {}),
                    "links": dict(d.get("Links") or {}),
                    "hists": dict(d.get("Hists") or {}),
                    "spans": [dict(ev) for ev in d.get("Spans") or []],
                    "t_wall_ms": float(d.get("T", 0.0)),
                    "proc": str(d.get("Proc", "")),
                }
            elif k == "health":
                # Fleet health events append-only (each delta carries
                # the new events; the snapshot carries the full ring),
                # BOUNDED like the leader's own ring — a flapping link
                # in a long service run must not grow shadow memory and
                # snapshot payloads without limit.
                from ..utils.telemetry import health_ring_size

                evs = self.health.setdefault("events", [])
                evs.extend(dict(ev) for ev in d.get("Events") or [])
                cap = health_ring_size()
                if len(evs) > cap:
                    del evs[:-cap]
            elif k == "rollout":
                # Rollout pipeline records (docs/rollout.md): the full
                # current record per delta — REPLACE per rollout id.
                self.rollouts[str(d["RolloutID"])] = dict(d)
            elif k == "policy":
                # Autonomy engine state (docs/autonomy.md): every delta
                # carries the engine's FULL current state (rules,
                # cooldowns, mask, in-flight actions) — REPLACE, so a
                # lifted quarantine or completed action is exactly an
                # absent entry.
                self.policy = dict(d)
            elif k == "plan_gen":
                self.plan_gen = max(self.plan_gen, int(d.get("Gen", 0)))
            else:
                log.warn("unknown control delta kind", kind=k)

    def export(self) -> dict:
        """A typed copy for :meth:`LeaderNode.adopt_shadow`."""
        with self._lock:
            return {
                "mode": self.mode,
                "assignment": {n: dict(r)
                               for n, r in self.assignment.items()},
                "status": {n: dict(r) for n, r in self.status.items()},
                "partial": {n: dict(p) for n, p in self.partial.items()},
                "dropped": {n: dict(r) for n, r in self.dropped.items()},
                "digests": dict(self.digests),
                "plan_seq": self.plan_seq,
                "startup_sent": self.startup_sent,
                "network_bw": dict(self.network_bw),
                "failure_timeout": self.failure_timeout,
                "boot_enabled": self.boot_enabled,
                "metrics": {n: dict(s) for n, s in self.metrics.items()},
                "health": {k: list(v) if isinstance(v, list) else dict(v)
                           for k, v in self.health.items()},
                "policy": dict(self.policy),
                "plan_gen": self.plan_gen,
                "jobs": {j: dict(rec) for j, rec in self.jobs.items()},
                "swaps": {v: dict(rec) for v, rec in self.swaps.items()},
                "rollouts": {r: dict(rec)
                             for r, rec in self.rollouts.items()},
                "base_assignment": (
                    {n: dict(r) for n, r in self.base_assignment.items()}
                    if self.base_assignment is not None else None),
                "wire_codecs": dict(self.wire_codecs),
                "node_codecs": {n: list(c)
                                for n, c in self.node_codecs.items()},
                "groups": {g: dict(rec) for g, rec in self.groups.items()},
                "pods": {k: (dict(v) if isinstance(v, dict) else list(v))
                         for k, v in self.pods.items()},
                "membership": {n: dict(rec)
                               for n, rec in self.membership.items()},
                "drain_jobs": dict(self.drain_jobs),
                "have_snapshot": self.have_snapshot,
            }


class StandbyController:
    """Attach to a receiver to make its node a leader standby.

    Registers the ``ControlDeltaMsg`` handler on the receiver's (already
    running) message loop, hooks the receiver's lease path, and monitors
    the leader's lease with an expiry staggered by succession rank —
    rank r waits ``lease_timeout * (1 + r)``, so the lowest-ranked LIVE
    standby always fires first and a dead first-in-line simply yields to
    the next by timeout, with no extra election protocol.  Promotion is
    deterministic takeover: build the run-mode leader over the worker's
    own loop/layers, adopt the shadow, bump the epoch, and beacon the
    new lease (workers re-point + re-announce = reconcile)."""

    def __init__(self, receiver, rank: int = 0, lease_timeout: float = 5.0,
                 standbys: Optional[List[NodeID]] = None,
                 mode: Optional[int] = None,
                 node_network_bw: Optional[Dict[NodeID, int]] = None,
                 failure_timeout: Optional[float] = None,
                 lease_interval: Optional[float] = None):
        self.receiver = receiver
        self.node = receiver.node
        self.shadow = ShadowLeaderState()
        self.rank = rank
        self.lease_timeout = lease_timeout
        self.lease_interval = lease_interval
        self.standbys = list(standbys or [])
        self._mode = mode
        self._bw = node_network_bw
        self._ft = failure_timeout
        self.promoted = threading.Event()  # set once self.leader is live
        self._promoting = False  # reentrancy latch (under self._lock)
        self.leader = None  # the promoted leader instance, post-takeover
        self.takeover_seconds: Optional[float] = None  # promote wall cost
        self._lock = threading.Lock()
        self._armed = False
        self._epoch_seen = -1
        timeout = lease_timeout * (1.0 + rank)
        self.detector = FailureDetector(timeout, self._leader_expired)
        receiver.loop.register(ControlDeltaMsg, self.handle_delta)
        receiver.on_leader_lease = self.handle_lease

    def close(self) -> None:
        self.detector.stop()
        if self.leader is not None:
            self.leader.close()

    # ------------------------------------------------------------- intake

    def handle_delta(self, msg: ControlDeltaMsg) -> None:
        with self._lock:
            if msg.epoch < self._epoch_seen:
                # A deposed leader's stale deltas must not pollute the
                # shadow the CURRENT leader is feeding.
                trace.count("failover.fenced_delta")
                return
            self._epoch_seen = max(self._epoch_seen, msg.epoch)
        self.detector.touch(msg.src_id)  # deltas are leader liveness too
        self.shadow.apply(msg)

    def handle_lease(self, msg: LeaderLeaseMsg) -> None:
        """Receiver hook — called AFTER the receiver's own fencing and
        leader re-pointing, so ``node.leader_id`` is already current."""
        with self._lock:
            if msg.epoch < self._epoch_seen:
                return  # zombie lease: the receiver fenced it already
            self._epoch_seen = max(self._epoch_seen, msg.epoch)
            if msg.standbys:
                self.standbys = [int(s) for s in msg.standbys]
                if self.node.my_id in self.standbys:
                    new_rank = self.standbys.index(self.node.my_id)
                    if new_rank != self.rank:
                        # Succession shortened (a standby ahead of us
                        # was promoted or dropped): tighten our expiry.
                        self.rank = new_rank
                        self.detector._timeout = self.lease_timeout * (
                            1.0 + new_rank)
            if msg.interval > 0:
                # Size the expiry off the leader's advisory beacon
                # period when it is longer than our config (never
                # shorter: a slow beacon must not fake-expire).
                floor = msg.interval * 3 * (1.0 + self.rank)
                if floor > self.detector._timeout:
                    self.detector._timeout = floor
            arm = not self._armed
            self._armed = True
        self.detector.touch(msg.src_id)
        if arm:
            self.detector.start()
        ldr = self.leader
        if ldr is not None:
            # The receiver owns the LeaderLeaseMsg handler on the shared
            # loop; forward so a PROMOTED leader can still depose itself
            # when a better claim (higher epoch, or equal-epoch lower
            # id) appears — without this, a double promotion would leave
            # two schedulers beaconing forever.
            ldr.handle_leader_lease(msg)

    # ----------------------------------------------------------- takeover

    def _leader_expired(self, node_id: NodeID) -> None:
        with self._lock:
            if self._promoting:
                return
        if node_id != self.node.leader_id:
            # A PREVIOUS leader's stale lease entry expired after a
            # takeover we already followed; only the current leader's
            # silence is a failover trigger.
            return
        self.promote(dead=node_id)

    def promote(self, dead: Optional[NodeID] = None) -> None:
        """Assume leadership: the deterministic takeover."""
        with self._lock:
            if self._promoting:
                return
            self._promoting = True
            epoch = max(self._epoch_seen, 0) + 1
            remaining = [s for s in self.standbys if s != self.node.my_id]
        t0 = time.monotonic()
        shadow = self.shadow.export()
        if not shadow["have_snapshot"]:
            log.error("promoting WITHOUT a replicated snapshot; recovery "
                      "depends entirely on worker re-announces")
        mode = self._mode if self._mode is not None else (
            shadow["mode"] or 0)
        ft = self._ft if self._ft is not None else shadow["failure_timeout"]
        interval = self.lease_interval or max(self.lease_timeout / 3.0, 0.05)
        log.error("leader lease expired; standby assuming leadership",
                  dead=dead, epoch=epoch, mode=mode, rank=self.rank,
                  shadow_deltas=self.shadow.deltas_applied)
        trace.count("failover.takeover")
        # Leadership is self-directed now: our own acks/announces must
        # short-circuit back into our loop.
        self.node.add_node(self.node.my_id)
        self.node.update_leader(self.node.my_id)
        self.receiver.note_leader_epoch(epoch)
        from .leader import (
            FlowRetransmitLeaderNode,
            HierarchicalFlowLeaderNode,
            LeaderNode,
            PullRetransmitLeaderNode,
            RetransmitLeaderNode,
        )

        classes = [LeaderNode, RetransmitLeaderNode,
                   PullRetransmitLeaderNode, FlowRetransmitLeaderNode]
        cls = classes[mode]
        groups = shadow.get("groups") or {}
        if mode == 3 and groups:
            # The dead root ran the hierarchy (docs/hierarchy.md): the
            # promoted leader must keep it — flat planning would send
            # leases to grouped members and re-point them all.
            cls = HierarchicalFlowLeaderNode
        kwargs = dict(start_loop=False, loop=self.receiver.loop,
                      lock=self.receiver._lock,
                      expected_nodes=set(), failure_timeout=ft,
                      standbys=remaining, lease_interval=interval,
                      epoch=epoch,
                      # The promoted leader inherits this seat's
                      # wire-codec plane (docs/codec.md): adopted codec
                      # choices stay plannable/stampable in encoded
                      # byte space across the takeover.
                      codecs=getattr(self.receiver, "codec_plane", None))
        args = (self.node, self.receiver.layers, shadow["assignment"])
        if mode == 3:
            bw = self._bw if self._bw is not None else shadow["network_bw"]
            if cls is HierarchicalFlowLeaderNode:
                kwargs["groups"] = groups
            pod_table = (shadow.get("pods") or {}).get("Table") or {}
            if pod_table:
                # The dead leader ran pod delivery (docs/fabric.md): the
                # promoted leader must keep the pod table — without it
                # the adopted goal's 1/R@k slices would never re-derive
                # their pod pairs, and the open-until-materialized
                # invariant would wedge on shard acks with no gather.
                kwargs["pods"] = {int(p): [int(m) for m in ms]
                                  for p, ms in pod_table.items()}
            leader = cls(*args, bw, **kwargs)
        else:
            leader = cls(*args, **kwargs)
        leader.boot_enabled = shadow["boot_enabled"]
        leader.adopt_shadow(shadow, dead_leader=dead)
        # Leader-bound swap traffic (confirm/query/error) arrives on the
        # shared loop's RECEIVER handler; forward it to the promoted
        # leader's swap driver (docs/swap.md).
        self.receiver.on_swap_leader_msg = leader.handle_swap_commit
        self.leader = leader
        self.promoted.set()  # only after self.leader is observable
        leader.detector.start()
        # First lease at the bumped epoch = the takeover announcement:
        # workers re-point their leader, flush requeued messages, and
        # re-announce (inventory + partials) — the reconcile channel.
        leader.start_ha()
        leader.resume_from_takeover()
        self.takeover_seconds = time.monotonic() - t0
        log.info("takeover complete; delivery resuming",
                 epoch=epoch, takeover_ms=round(
                     self.takeover_seconds * 1000, 1))
